"""Speculative decoding (reference: models/model_base.py ``NeuronFusedSpecModel``
:1598-3021 and the vanilla speculation submodel).

Two modes, mirroring the reference:

* **Vanilla speculation** — separate draft and target apps; the host loop
  alternates k draft steps and one target verify call
  (reference: utils/hf_adapter.py assisted decoding :439-632).
* **Fused speculation** — draft loop + target verify + acceptance in ONE
  jitted graph per step (reference: _token_gen_forward :1812-1929): the
  draft's k-step autoregressive loop is a ``lax.scan``, the target scores
  all k+1 candidate positions in one batched forward, and acceptance is the
  cumsum-of-mismatch trick (reference: :2726-2730).

Greedy speculation is exactly equivalent to greedy decoding — the tests
assert token-identical output vs the plain decode path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from ..config import TpuConfig
from ..modules import kv_cache as kv_mod
from ..modules.token_tree import TokenTree
from ..ops import attention as attn_ops
from ..ops.normalization import rms_norm
from ..parallel.layers import ParamSpec
from . import model_base
from .model_base import DecoderSpec


def draft_k_tokens(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                   first_token, positions, seq_ids, k: int):
    """Run k greedy draft steps (lax.scan). Returns (draft_tokens (B, k),
    cache). first_token (B,), positions (B,) = position of first_token."""

    def step(carry, _):
        tok, pos, cch = carry
        out = model_base.token_generation_step(
            spec, tpu_cfg, params, cch, tok[:, None], pos[:, None], seq_ids,
            None, jax.random.PRNGKey(0))
        return (out["tokens"], pos + 1, out["cache"]), out["tokens"]

    (_, _, new_cache), toks = jax.lax.scan(
        step, (first_token, positions, cache), None, length=k)
    return jnp.transpose(toks, (1, 0)), new_cache


def fused_speculation_step(draft_spec: DecoderSpec, target_spec: DecoderSpec,
                           tpu_cfg: TpuConfig, draft_params, target_params,
                           draft_cache, target_cache, last_token, positions,
                           seq_ids, rng):
    """One fused speculation step (reference: _token_gen_forward :1812-1929).

    last_token (B,): last accepted token. positions (B,): its position.
    Returns dict(tokens (B, k+1), num_accepted (B,), caches).
    Greedy acceptance: accept draft token i iff target's greedy choice at
    position i equals it; always emit one bonus token from the target
    (reference acceptance: cumsum-of-mismatch :2726-2730).
    """
    k = tpu_cfg.speculation_length
    b = last_token.shape[0]

    # 1) k-step draft loop (in-graph scan; reference unrolls :2552-2611)
    draft_tokens, new_draft_cache = draft_k_tokens(
        draft_spec, tpu_cfg, draft_params, draft_cache, last_token, positions,
        seq_ids, k)

    # 2) target verifies all k+1 positions in one forward
    #    (reference: target_model(candidate_ids…) :2617-2642)
    cand = jnp.concatenate([last_token[:, None], draft_tokens], axis=1)  # (B, k+1)
    cand_pos = positions[:, None] + jnp.arange(k + 1, dtype=positions.dtype)
    t_out = model_base.token_generation_multi(
        target_spec, tpu_cfg, target_params, target_cache, cand, cand_pos,
        seq_ids)
    target_logits = t_out["logits_all"]            # (B, k+1, V)
    new_target_cache = t_out["cache"]
    target_greedy = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # (B, k+1)

    # 3) acceptance: n_matches = count of leading draft tokens equal to the
    #    target's choices (cumsum-of-mismatch, reference :2726-2730)
    mismatch = (draft_tokens != target_greedy[:, :k]).astype(jnp.int32)
    n_accepted = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)  # (B,) in [0, k]

    # 4) emitted tokens: accepted draft tokens then the target's correction /
    #    bonus token at position n_accepted
    idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    accepted_mask = idx < n_accepted[:, None]
    bonus = jnp.take_along_axis(target_greedy, n_accepted[:, None], axis=1)
    tokens = jnp.where(accepted_mask,
                       jnp.concatenate([draft_tokens,
                                        jnp.zeros((b, 1), jnp.int32)], axis=1),
                       jnp.where(idx == n_accepted[:, None], bonus, 0))
    return {
        "tokens": tokens,                 # (B, k+1); positions > n_accepted are 0
        "num_accepted": n_accepted + 1,   # emitted per row (accepted + bonus)
        "draft_cache": new_draft_cache,
        "target_cache": new_target_cache,
    }


class SpeculativeDecoder:
    """Host orchestration for fused speculation
    (reference: NeuronBaseForCausalLM fused-spec routing :3078,
    hf_adapter fused decode loop :495).

    Wraps a target CausalLMApplication and a draft CausalLMApplication that
    share batch geometry; both caches advance together. The per-row emitted
    count varies, so rows advance at different positions — handled exactly
    like the reference by tracking per-row positions.
    """

    def __init__(self, target_app, draft_app):
        from .application import CausalLMApplication  # noqa: F401 (typing)
        self.target = target_app
        self.draft = draft_app
        cfg = target_app.tpu_config
        if not cfg.speculation_config or cfg.speculation_config.speculation_length < 1:
            raise ValueError("target app needs speculation_config.speculation_length >= 1")
        self.k = cfg.speculation_config.speculation_length
        self._step_fn = None

    def _build_step(self):
        if self._step_fn is None:
            fn = partial(fused_speculation_step, self.draft.spec,
                         self.target.spec, self.target.tpu_config)
            self._step_fn = jax.jit(fn, donate_argnums=(2, 3))
        return self._step_fn

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None,
                 attention_mask: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Greedy speculative generation; exactly matches greedy decode."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        eos_set = (None if eos_token_id is None else
                   set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
        eos_fill = None if eos_set is None else next(iter(eos_set))

        # prefill BOTH models (reference: EAGLE/fused CTE runs both)
        t_out = self.target._run_prefill(input_ids.astype(np.int32), seq_lens)
        self.draft._run_prefill(input_ids.astype(np.int32), seq_lens)
        first = np.asarray(t_out["tokens"]).astype(np.int32)   # (B,)

        step = self._build_step()
        out_rows = [[int(first[i])] for i in range(b)]
        last = first
        positions = seq_lens.astype(np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        done = np.zeros((b,), bool)
        total_accepted_stats = []
        max_total = self.target.tpu_config.seq_len
        while (min(len(r) for r in out_rows) < max_new_tokens
               and int(positions.max()) + self.k + 1 < max_total
               and not done.all()):
            res = step(self.draft.params, self.target.params,
                       self.draft.cache, self.target.cache,
                       jnp.asarray(last), jnp.asarray(positions),
                       jnp.asarray(seq_ids), jax.random.PRNGKey(0))
            self.draft.cache = res["draft_cache"]
            self.target.cache = res["target_cache"]
            toks = np.asarray(res["tokens"])
            n_emit = np.asarray(res["num_accepted"])
            total_accepted_stats.append(n_emit.copy())
            for i in range(b):
                if done[i]:
                    continue
                row = toks[i, :n_emit[i]].tolist()
                for t in row:
                    out_rows[i].append(int(t))
                    if eos_set is not None and int(t) in eos_set:
                        done[i] = True
                        break
            positions = positions + n_emit.astype(np.int32)
            last = toks[np.arange(b), n_emit - 1].astype(np.int32)

        gen = np.zeros((b, max_new_tokens), np.int32)
        for i in range(b):
            row = out_rows[i][:max_new_tokens]
            gen[i, :len(row)] = row
            if len(row) < max_new_tokens:
                gen[i, len(row):] = row[-1] if eos_fill is None else eos_fill
        mean_emitted = (float(np.mean(np.concatenate(total_accepted_stats)))
                        if total_accepted_stats else 0.0)
        return {
            "sequences": np.concatenate([input_ids, gen], axis=1),
            "generated": gen,
            "mean_tokens_per_step": mean_emitted,
        }


# ===========================================================================
# EAGLE speculation (reference: NeuronFusedSpecModel EAGLE paths,
# models/model_base.py:1931-2754 + modules/eagle/hidden_state.py)
# ===========================================================================

def eagle_draft_param_specs(draft_spec: DecoderSpec,
                            input_norm: bool = False) -> Dict[str, Any]:
    """Draft param tree = a small decoder + the EAGLE fusion fc mapping
    concat(embed, prev_hidden) (2H) -> H (reference: EAGLE draft hidden-state
    fusion, model_base.py:1526-1592)."""
    specs = model_base.decoder_param_specs(draft_spec)
    H = draft_spec.hidden_size
    specs["fc"] = ParamSpec((2 * H, H), P(), draft_spec.dtype)
    if input_norm:
        specs["fc_norm"] = ParamSpec((H,), P(), draft_spec.dtype, "ones")
    return specs


def init_eagle_draft_params(draft_spec: DecoderSpec, key, mesh=None,
                            input_norm: bool = False):
    specs = eagle_draft_param_specs(draft_spec, input_norm)
    return model_base.init_param_tree(specs, key, mesh)


def eagle_forward(draft_spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                  tokens, prev_hidden, positions, seq_ids,
                  input_norm: bool = False):
    """EAGLE draft forward: token embeddings fused with the previous
    positions' hidden states through fc, then the draft layer stack.

    tokens (B,T); prev_hidden (B,T,H) = feature of position[t]-1;
    positions (B,T). The draft writes its KV at ``positions``.
    """
    e = model_base._embed(draft_spec, params, tokens)
    if input_norm:
        e = rms_norm(e, params["fc_norm"], draft_spec.rms_eps)
    fused = jnp.concatenate([e, prev_hidden.astype(e.dtype)], axis=-1)
    h0 = fused @ params["fc"]
    cache_len = kv_mod.cache_len_of(cache)
    ai = model_base.attn_inputs(
        draft_spec, positions,
        lambda w, c=0: attn_ops.decode_mask(positions, cache_len, window=w, chunk=c))
    hidden, new_cache, _ = model_base.run_layers(
        draft_spec, params, cache, h0, ai, seq_ids, positions, "decode",
        identity_seq_ids=not tpu_cfg.is_continuous_batching)
    logits = model_base._lm_head(draft_spec, params, hidden)
    return {"logits": logits[..., :draft_spec.vocab_size], "hidden": hidden,
            "cache": new_cache}


def eagle_speculation_step(draft_spec: DecoderSpec, target_spec: DecoderSpec,
                           tpu_cfg: TpuConfig, draft_params, target_params,
                           draft_cache, target_cache, last_token, prev_hidden,
                           positions, seq_ids, input_norm: bool = False):
    """One fused EAGLE step (reference: _eagle_token_gen_forward
    :2517-2754): k-step draft scan -> target verify -> cumsum acceptance ->
    final draft cache-refresh run with the verified target features.

    last_token (B,) at position ``positions``; prev_hidden (B,H) = target
    feature at positions-1. Returns emitted tokens, per-row count, updated
    caches, and the next (token, feature) pair.
    """
    k = tpu_cfg.speculation_length
    b = last_token.shape[0]

    def dstep(carry, _):
        tok, hid, pos, cch = carry
        out = eagle_forward(draft_spec, tpu_cfg, draft_params, cch,
                            tok[:, None], hid[:, None, :], pos[:, None],
                            seq_ids, input_norm)
        ntok = jnp.argmax(out["logits"][:, -1, :], axis=-1).astype(jnp.int32)
        nhid = out["hidden"][:, -1, :]
        return (ntok, nhid, pos + 1, out["cache"]), ntok

    (_, _, _, dcache), dtoks = jax.lax.scan(
        dstep, (last_token, prev_hidden, positions, draft_cache), None,
        length=k)
    draft_tokens = jnp.transpose(dtoks, (1, 0))              # (B, k)

    cand = jnp.concatenate([last_token[:, None], draft_tokens], axis=1)
    cand_pos = positions[:, None] + jnp.arange(k + 1, dtype=positions.dtype)
    t_out = model_base.token_generation_multi(
        target_spec, tpu_cfg, target_params, target_cache, cand, cand_pos,
        seq_ids)
    greedy = jnp.argmax(t_out["logits_all"], axis=-1).astype(jnp.int32)

    mismatch = (draft_tokens != greedy[:, :k]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)  # [0, k]
    idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    bonus = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)[:, 0]
    padded_draft = jnp.concatenate(
        [draft_tokens, jnp.zeros((b, 1), jnp.int32)], axis=1)
    tokens = jnp.where(idx < n_acc[:, None], padded_draft,
                       jnp.where(idx == n_acc[:, None], bonus[:, None], 0))

    # next feature: target hidden at position positions + n_acc
    next_hidden = jnp.take_along_axis(
        t_out["hidden"], n_acc[:, None, None], axis=1)[:, 0, :]

    # draft cache refresh (reference: final draft cache-update run
    # :2663-2694): slot p gets the verified pair (token at p, target feature
    # at p-1); slots beyond the accepted prefix are pushed out of range
    cache_len = kv_mod.cache_len_of(draft_cache)
    hid_seq = jnp.concatenate(
        [prev_hidden[:, None, :], t_out["hidden"][:, :k, :]], axis=1)
    refresh_pos = jnp.where(idx <= n_acc[:, None], cand_pos, cache_len)
    upd = eagle_forward(draft_spec, tpu_cfg, draft_params, dcache, cand,
                        hid_seq, refresh_pos, seq_ids, input_norm)
    return {
        "tokens": tokens,
        "num_emitted": n_acc + 1,
        "next_token": bonus,
        "next_hidden": next_hidden,
        "draft_cache": upd["cache"],
        "target_cache": t_out["cache"],
    }



def _prime_eagle_draft(decoder, input_ids, hs, seq_ids):
    """Prime the draft cache over the prompt: slot p <- (token p, feature
    p-1) (reference: EAGLE CTE, model_base.py:1931-2092)."""
    b, s = input_ids.shape
    if s > 1:
        d_out = decoder._prefill(
            decoder.draft_params, decoder.draft_cache,
            jnp.asarray(input_ids[:, 1:]), jnp.asarray(hs[:, :-1]),
            jnp.broadcast_to(jnp.arange(1, s, dtype=jnp.int32), (b, s - 1)),
            jnp.asarray(seq_ids))
        decoder.draft_cache = d_out["cache"]


def _eagle_host_loop(input_ids, first, prev_hidden, seq_lens, max_new_tokens,
                     eos_token_id, seq_len_cap, budget, step_fn):
    """Shared EAGLE host loop: call ``step_fn(root, prev_hidden, positions)
    -> (tokens (B,W), n_emit (B,), next_root (B,), next_hidden (B,H))``
    until every row has max_new_tokens or hit EOS; assemble the padded
    output (reference: hf_adapter fused decode loop :495)."""
    b = input_ids.shape[0]
    eos_set = (None if eos_token_id is None else
               set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
    out_rows = [[int(first[i])] for i in range(b)]
    root = first
    positions = seq_lens.copy()
    done = np.zeros((b,), bool)
    emitted_counts = []
    while (min(len(r) for r in out_rows) < max_new_tokens
           and int(positions.max()) + budget < seq_len_cap
           and not done.all()):
        toks, n_emit, root, prev_hidden = step_fn(root, prev_hidden,
                                                  positions)
        emitted_counts.append(n_emit.copy())
        for i in range(b):
            if done[i]:
                continue
            for t in toks[i, :n_emit[i]].tolist():
                out_rows[i].append(int(t))
                if eos_set is not None and int(t) in eos_set:
                    done[i] = True
                    break
        positions = positions + n_emit.astype(np.int32)
    gen = np.zeros((b, max_new_tokens), np.int32)
    for i in range(b):
        row = out_rows[i][:max_new_tokens]
        gen[i, :len(row)] = row
        if len(row) < max_new_tokens:
            gen[i, len(row):] = row[-1]
    return {
        "sequences": np.concatenate([input_ids, gen], axis=1),
        "generated": gen,
        "mean_tokens_per_step": (float(np.mean(np.concatenate(
            emitted_counts))) if emitted_counts else 0.0),
    }


class EagleDecoder:
    """Host orchestration for fused EAGLE speculation. The per-(seq, position)
    hidden-state rolling buffer of the reference (modules/eagle/
    hidden_state.py) collapses to the (next_token, next_hidden) pair threaded
    between steps — per-seq storage only matters for continuous batching,
    handled by keying on seq_id here."""

    def __init__(self, target_app, draft_spec: DecoderSpec, draft_params,
                 draft_cache, input_norm: bool = False):
        self.target = target_app
        self.draft_spec = draft_spec
        self.draft_params = draft_params
        self.draft_cache = draft_cache
        self.input_norm = input_norm
        cfg = target_app.tpu_config
        if not cfg.speculation_config or cfg.speculation_config.speculation_length < 1:
            raise ValueError("speculation_config.speculation_length >= 1 required")
        self.k = cfg.speculation_config.speculation_length
        self._step = jax.jit(
            partial(eagle_speculation_step, draft_spec, target_app.spec,
                    target_app.tpu_config, input_norm=input_norm),
            donate_argnums=(2, 3))
        self._prefill = jax.jit(
            partial(eagle_forward, draft_spec, target_app.tpu_config,
                    input_norm=input_norm),
            donate_argnums=(1,))

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids).astype(np.int32)
        b, s = input_ids.shape
        cfg = self.target.tpu_config
        if not cfg.output_full_hidden:
            raise ValueError("target app needs output_full_hidden=True "
                             "(EAGLE primes the draft from prefill hiddens)")
        seq_lens = np.full((b,), s, np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        t_out = self.target._run_prefill(input_ids, seq_lens)
        hs = np.asarray(t_out["hidden_states"])[:, :s]       # (B,S,H)
        first = np.asarray(t_out["tokens"]).astype(np.int32)
        _prime_eagle_draft(self, input_ids, hs, seq_ids)

        def step_fn(root, prev_hidden, positions):
            res = self._step(self.draft_params, self.target.params,
                             self.draft_cache, self.target.cache,
                             jnp.asarray(root), prev_hidden,
                             jnp.asarray(positions), jnp.asarray(seq_ids))
            self.draft_cache = res["draft_cache"]
            self.target.cache = res["target_cache"]
            return (np.asarray(res["tokens"]),
                    np.asarray(res["num_emitted"]),
                    np.asarray(res["next_token"]).astype(np.int32),
                    res["next_hidden"])

        return _eagle_host_loop(input_ids, first, jnp.asarray(hs[:, -1]),
                                seq_lens, max_new_tokens, eos_token_id,
                                cfg.seq_len, self.k + 1, step_fn)


# ===========================================================================
# Medusa speculation (reference: medusa_speculation_model submodel +
# hf_adapter medusa decode loop :799-890)
# ===========================================================================

def medusa_propose(spec: DecoderSpec, params, hidden, top_k: int = 1):
    """Run the medusa heads on (B,H) features: head j = ResBlock + lm head
    predicting position +j+2. Returns (B, M, top_k) token ids."""
    return medusa_propose_scored(spec, params, hidden, top_k)[0]


def medusa_propose_scored(spec: DecoderSpec, params, hidden, top_k: int = 1):
    """medusa_propose returning (ids (B,M,k), logprobs (B,M,k)) — the
    per-level scores feeding dynamic tree construction (reference:
    modules/eagle/dynamic_token_tree.py candidate scoring)."""
    h = hidden[:, None, :]                                   # (B,1,H)
    r = h + jax.nn.silu(
        jnp.einsum("bmh,mhk->bmk", jnp.broadcast_to(
            h, (h.shape[0], params["medusa_blocks"].shape[0], h.shape[-1])),
            params["medusa_blocks"]) + params["medusa_bias"])
    logits = jnp.einsum("bmh,mhv->bmv", r, params["medusa_lm"])
    logits = logits[..., :spec.vocab_size].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    top_lp, idx = jax.lax.top_k(logp, top_k)
    return idx.astype(jnp.int32), top_lp                     # (B,M,k) each


def medusa_speculation_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params,
                            cache, cand, positions, seq_ids):
    """One fused medusa step: verify the candidate chain
    [last_emitted, p1..p_{k-1}] in one forward, accept the matching prefix,
    emit the bonus, and propose the next chain from the accepted feature
    (reference: medusa speculation graph + postprocessor)."""
    b, k = cand.shape
    cand_pos = positions[:, None] + jnp.arange(k, dtype=positions.dtype)
    out = model_base.token_generation_multi(
        spec, tpu_cfg, params, cache, cand, cand_pos, seq_ids)
    greedy = jnp.argmax(out["logits_all"], axis=-1).astype(jnp.int32)  # (B,k)
    mismatch = (cand[:, 1:] != greedy[:, :k - 1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)  # [0, k-1]
    idx = jnp.arange(k, dtype=jnp.int32)[None, :]
    bonus = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)[:, 0]
    shifted = jnp.concatenate([cand[:, 1:], jnp.zeros((b, 1), jnp.int32)], 1)
    tokens = jnp.where(idx < n_acc[:, None], shifted,
                       jnp.where(idx == n_acc[:, None], bonus[:, None], 0))
    feat = jnp.take_along_axis(out["hidden"], n_acc[:, None, None], axis=1)[:, 0]
    props = medusa_propose(spec, params, feat)[:, :k - 1, 0]   # (B,k-1)
    next_cand = jnp.concatenate([bonus[:, None], props], axis=1)
    return {"tokens": tokens, "num_emitted": n_acc + 1,
            "next_cand": next_cand, "cache": out["cache"]}


class MedusaDecoder:
    """Host loop for medusa speculation (chain mode). The target app's spec
    must carry medusa_heads > 0 (params include the heads)."""

    def __init__(self, target_app):
        self.target = target_app
        cfg = target_app.tpu_config
        sc = cfg.speculation_config
        if not sc or sc.medusa_speculation_length < 1:
            raise ValueError("speculation_config.medusa_speculation_length "
                             ">= 1 required")
        self.k = min(sc.medusa_speculation_length,
                     target_app.spec.medusa_heads + 1)
        self._step = jax.jit(
            partial(medusa_speculation_step, target_app.spec, cfg),
            donate_argnums=(1,))
        self._propose = jax.jit(partial(medusa_propose, target_app.spec),
                                static_argnames=("top_k",))

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids).astype(np.int32)
        b, s = input_ids.shape
        cfg = self.target.tpu_config
        seq_lens = np.full((b,), s, np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        t_out = self.target._run_prefill(input_ids, seq_lens)
        first = np.asarray(t_out["tokens"]).astype(np.int32)
        feat = t_out["last_hidden"]
        props = np.asarray(self._propose(self.target.params, feat))[:, :self.k - 1, 0]
        cand = np.concatenate([first[:, None], props], axis=1)

        eos_set = (None if eos_token_id is None else
                   set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
        out_rows = [[int(first[i])] for i in range(b)]
        positions = seq_lens.copy()
        done = np.zeros((b,), bool)
        emitted_counts = []
        while (min(len(r) for r in out_rows) < max_new_tokens
               and int(positions.max()) + self.k < cfg.seq_len
               and not done.all()):
            res = self._step(self.target.params, self.target.cache,
                             jnp.asarray(cand), jnp.asarray(positions),
                             jnp.asarray(seq_ids))
            self.target.cache = res["cache"]
            toks = np.asarray(res["tokens"])
            n_emit = np.asarray(res["num_emitted"])
            emitted_counts.append(n_emit.copy())
            for i in range(b):
                if done[i]:
                    continue
                for t in toks[i, :n_emit[i]].tolist():
                    out_rows[i].append(int(t))
                    if eos_set is not None and int(t) in eos_set:
                        done[i] = True
                        break
            positions = positions + n_emit.astype(np.int32)
            cand = np.asarray(res["next_cand"])

        gen = np.zeros((b, max_new_tokens), np.int32)
        for i in range(b):
            row = out_rows[i][:max_new_tokens]
            gen[i, :len(row)] = row
            if len(row) < max_new_tokens:
                gen[i, len(row):] = row[-1]
        return {
            "sequences": np.concatenate([input_ids, gen], axis=1),
            "generated": gen,
            "mean_tokens_per_step": (float(np.mean(np.concatenate(
                emitted_counts))) if emitted_counts else 0.0),
        }


# ===========================================================================
# Token-tree verification (reference: modules/eagle/token_tree.py per-level
# masks + tree-attention verify; used here in medusa tree mode)
# ===========================================================================

def tree_forward(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                 node_tokens, rope_positions, write_positions, seq_ids, mask):
    """Forward over tree nodes with an explicit attention mask: node i writes
    cache slot ``write_positions[:, i]`` and attends per ``mask`` (committed
    prefix + ancestors). rope uses the node's logical position (base+depth)."""
    assert spec.layer_pattern is None, "tree verify + layer patterns TBD"
    if spec.alibi:
        # tree nodes occupy slots base+i with logical positions = depth, so
        # the slot-index ALiBi bias would be silently wrong
        raise NotImplementedError("token-tree speculation over ALiBi models")
    ai = {"mask": mask.astype(bool)}
    from ..ops.rope import rope_cos_sin
    ai["cos"], ai["sin"] = rope_cos_sin(rope_positions, spec.rope)
    hidden = model_base._embed(spec, params, node_tokens)
    hidden, new_cache, _ = model_base.run_layers(
        spec, params, cache, hidden, ai, seq_ids, write_positions, "decode",
        identity_seq_ids=not tpu_cfg.is_continuous_batching)
    logits = model_base._lm_head(spec, params, hidden)
    return {"logits_all": logits[..., :spec.vocab_size], "hidden": hidden,
            "cache": new_cache}


def medusa_tree_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                     node_tokens, base_pos, seq_ids, tree_mask,
                     paths, path_lens, depth):
    """One medusa tree-verify step. node_tokens (B,N) — node 0 is the last
    emitted token; tree_mask (B,N,S) from TokenTree.attention_mask; paths
    (P,D+1)/path_lens (P,) from leaf_path_matrix; depth (N,).

    Accept the path with the most leading greedy matches; emit its tokens +
    the bonus; return the accepted feature for the next proposals."""
    b, n = node_tokens.shape
    rope_pos = base_pos[:, None] + depth[None, :]
    write_pos = base_pos[:, None] + jnp.arange(n, dtype=base_pos.dtype)
    out = tree_forward(spec, tpu_cfg, params, cache, node_tokens, rope_pos,
                       write_pos, seq_ids, tree_mask)
    greedy = jnp.argmax(out["logits_all"], axis=-1).astype(jnp.int32)  # (B,N)

    safe_paths = jnp.maximum(paths, 0)                       # (P,D+1)
    tok_at = node_tokens[:, safe_paths]                      # (B,P,D+1)
    pred_at = greedy[:, safe_paths]
    edge_valid = (paths[None, :, 1:] >= 0)
    match = (tok_at[:, :, 1:] == pred_at[:, :, :-1]) & edge_valid
    acc_len = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=-1), axis=-1)
    best = jnp.argmax(acc_len, axis=-1).astype(jnp.int32)    # (B,)
    n_acc = jnp.take_along_axis(acc_len, best[:, None], 1)[:, 0]

    best_path = safe_paths[best]                             # (B,D+1)
    d1 = paths.shape[1]
    idx = jnp.arange(d1, dtype=jnp.int32)[None, :]
    path_toks = jnp.take_along_axis(node_tokens, best_path, axis=1)
    path_pred = jnp.take_along_axis(greedy, best_path, axis=1)
    bonus = jnp.take_along_axis(path_pred, n_acc[:, None], 1)[:, 0]
    # emitted: path tokens 1..n_acc then the bonus
    shifted = jnp.concatenate(
        [path_toks[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1)
    tokens = jnp.where(idx < n_acc[:, None], shifted,
                       jnp.where(idx == n_acc[:, None], bonus[:, None], 0))
    feat_node = jnp.take_along_axis(best_path, n_acc[:, None], 1)[:, 0]
    feat = jnp.take_along_axis(out["hidden"], feat_node[:, None, None],
                               axis=1)[:, 0]

    # cache refresh: rewrite slots base..base+n_acc+1 with the linear
    # accepted sequence [root, accepted..., bonus]; stale tree slots beyond
    # are overwritten by the next step's writes
    refresh_toks = jnp.concatenate([node_tokens[:, :1], tokens], axis=1)
    r_w = refresh_toks.shape[1]
    ridx = jnp.arange(r_w, dtype=jnp.int32)[None, :]
    rpos = base_pos[:, None] + ridx
    # invalid tail slots: push writes out of range (dropped)
    rpos = jnp.where(ridx <= (n_acc + 1)[:, None], rpos,
                     kv_mod.cache_len_of(out["cache"]))
    upd = model_base.token_generation_multi(
        spec, tpu_cfg, params, out["cache"], refresh_toks, rpos, seq_ids)
    return {"tokens": tokens, "num_emitted": n_acc + 1, "bonus": bonus,
            "feature": feat, "cache": upd["cache"]}


class MedusaTreeDecoder:
    """Host loop for medusa TREE speculation: heads propose top-w candidates
    per level, the tree is verified in one forward, the best path wins."""

    def __init__(self, target_app, tree: Optional[TokenTree] = None):
        from ..modules.token_tree import DEFAULT_TREE
        self.target = target_app
        cfg = target_app.tpu_config
        sc = cfg.speculation_config
        if not sc or target_app.spec.medusa_heads < 1:
            raise ValueError("medusa heads required")
        if tree is None:
            tree = TokenTree.from_config(sc.token_tree_config or DEFAULT_TREE)
        if tree.max_depth > target_app.spec.medusa_heads:
            raise ValueError("tree deeper than medusa head count")
        self.tree = tree
        self.paths, self.path_lens = tree.leaf_path_matrix()
        self.max_width = int(tree.level_widths.max())
        self._step = jax.jit(
            partial(medusa_tree_step, target_app.spec, cfg),
            donate_argnums=(1,))
        self._propose = jax.jit(partial(medusa_propose, target_app.spec),
                                static_argnames=("top_k",))

    def _node_tokens(self, root, props):
        """Assemble (B,N) node tokens: node at depth d, branch b takes
        props[:, d-1, b]; node 0 = root."""
        t = self.tree
        b = root.shape[0]
        out = np.zeros((b, t.num_nodes), np.int32)
        out[:, 0] = root
        for i in range(1, t.num_nodes):
            out[:, i] = props[:, t.depth[i] - 1, t.branch[i]]
        return out

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids).astype(np.int32)
        b, s = input_ids.shape
        cfg = self.target.tpu_config
        t = self.tree
        seq_lens = np.full((b,), s, np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        t_out = self.target._run_prefill(input_ids, seq_lens)
        root = np.asarray(t_out["tokens"]).astype(np.int32)
        props = np.asarray(self._propose(self.target.params,
                                         t_out["last_hidden"],
                                         top_k=self.max_width))

        eos_set = (None if eos_token_id is None else
                   set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
        out_rows = [[int(root[i])] for i in range(b)]
        positions = seq_lens.copy()
        done = np.zeros((b,), bool)
        emitted_counts = []
        cache_len = cfg.seq_len
        depth = jnp.asarray(t.depth)
        paths = jnp.asarray(self.paths)
        plens = jnp.asarray(self.path_lens)
        while (min(len(r) for r in out_rows) < max_new_tokens
               and int(positions.max()) + t.num_nodes + 1 < cache_len
               and not done.all()):
            node_toks = self._node_tokens(root, props)
            mask = t.attention_mask(positions, cache_len)
            res = self._step(self.target.params, self.target.cache,
                             jnp.asarray(node_toks), jnp.asarray(positions),
                             jnp.asarray(seq_ids), jnp.asarray(mask),
                             paths, plens, depth)
            self.target.cache = res["cache"]
            toks = np.asarray(res["tokens"])
            n_emit = np.asarray(res["num_emitted"])
            emitted_counts.append(n_emit.copy())
            for i in range(b):
                if done[i]:
                    continue
                for tk in toks[i, :n_emit[i]].tolist():
                    out_rows[i].append(int(tk))
                    if eos_set is not None and int(tk) in eos_set:
                        done[i] = True
                        break
            positions = positions + n_emit.astype(np.int32)
            root = np.asarray(res["bonus"]).astype(np.int32)
            props = np.asarray(self._propose(self.target.params,
                                             res["feature"],
                                             top_k=self.max_width))

        gen = np.zeros((b, max_new_tokens), np.int32)
        for i in range(b):
            row = out_rows[i][:max_new_tokens]
            gen[i, :len(row)] = row
            if len(row) < max_new_tokens:
                gen[i, len(row):] = row[-1]
        return {
            "sequences": np.concatenate([input_ids, gen], axis=1),
            "generated": gen,
            "mean_tokens_per_step": (float(np.mean(np.concatenate(
                emitted_counts))) if emitted_counts else 0.0),
        }


# ===========================================================================
# Dynamic token tree (reference: modules/eagle/dynamic_token_tree.py, 352
# LoC — EAGLE-2-style): instead of a FIXED tree shape, each step selects the
# top-``num_nodes`` lattice nodes by cumulative joint log-probability. The
# candidate lattice is the full k-ary tree of the proposal depth (static
# tables); selection is in-graph. Joint scores are monotone non-increasing
# along a path, so the top-N set is automatically ancestor-closed.
# ===========================================================================

def build_lattice(branch_k: int, depth: int):
    """Static numpy tables for the full k-ary lattice: depth (N,),
    parent (N,), branch (N,), ancestor (N,N) incl. self, path (N, depth+1)
    lattice ids from root (-1 padded)."""
    nodes = [()]
    for d in range(depth):
        nodes += [p + (b,) for p in nodes if len(p) == d
                  for b in range(branch_k)]
    nodes = sorted(nodes, key=lambda p: (len(p), p))
    idx = {p: i for i, p in enumerate(nodes)}
    n = len(nodes)
    dep = np.array([len(p) for p in nodes], np.int32)
    par = np.array([idx[p[:-1]] if p else 0 for p in nodes], np.int32)
    br = np.array([p[-1] if p else 0 for p in nodes], np.int32)
    anc = np.zeros((n, n), bool)
    path = np.full((n, depth + 1), -1, np.int32)
    for i, p in enumerate(nodes):
        for d in range(len(p) + 1):
            anc[i, idx[p[:d]]] = True
            path[i, d] = idx[p[:d]]
    return dep, par, br, anc, path


def dynamic_tree_select(lat, prop_logp, num_nodes: int):
    """Select the top-``num_nodes`` lattice nodes by joint logprob.
    prop_logp (B, D, k). Returns sel (B, M) lattice ids (root first,
    depth-sorted) and their scores."""
    dep, par, br, anc, path = lat
    b = prop_logp.shape[0]
    n = dep.shape[0]
    # node score = sum of branch logprobs along the path
    edge_lp = jnp.where(
        dep[None, :] > 0,
        prop_logp[:, jnp.maximum(dep - 1, 0), br],           # (B, N)
        0.0)
    # accumulate over ancestors (anc includes self; root contributes 0)
    score = jnp.einsum("bn,mn->bm", edge_lp,
                       jnp.asarray(anc, jnp.float32))        # (B, N)
    _, sel = jax.lax.top_k(score, num_nodes)
    # stable depth-major order (root at slot 0)
    order = jnp.argsort(dep[sel] * n + sel, axis=-1)
    sel = jnp.take_along_axis(sel, order, axis=-1)
    return sel, jnp.take_along_axis(score, sel, axis=-1)


def dynamic_medusa_tree_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params,
                             cache, root, prop_toks, prop_logp, base_pos,
                             seq_ids, lat_dep, lat_par, lat_br, lat_anc,
                             lat_path, num_nodes: int, cache_len: int,
                             return_path_features: bool = False):
    """One dynamic-tree verify step: build the tree in-graph from the
    proposal scores, verify, accept the deepest fully-matching path.
    root (B,) last emitted token; prop_toks/prop_logp (B, D, k)."""
    b = root.shape[0]
    n_lat = lat_dep.shape[0]
    sel, _ = dynamic_tree_select(
        (lat_dep, lat_par, lat_br, lat_anc, lat_path), prop_logp, num_nodes)
    m = sel.shape[1]
    dep_s = lat_dep[sel]                                      # (B, M)
    # node tokens: lattice node -> proposal token at (depth-1, branch)
    tok_lat = jnp.where(
        lat_dep[None, :] > 0,
        prop_toks[:, jnp.maximum(lat_dep - 1, 0), lat_br],    # (B, N)
        root[:, None])
    node_toks = jnp.take_along_axis(tok_lat, sel, axis=-1)    # (B, M)
    # ancestor relation among SELECTED nodes + committed-prefix mask
    anc_pair = jnp.asarray(lat_anc)[sel[:, :, None], sel[:, None, :]]
    slot = jnp.arange(cache_len, dtype=base_pos.dtype)[None, None, :]
    committed = slot < base_pos[:, None, None]                # (B, M, S)
    node_slot = base_pos[:, None] + jnp.arange(m, dtype=base_pos.dtype)
    tree_part = jnp.zeros((b, m, cache_len), bool).at[
        jnp.arange(b)[:, None, None], jnp.arange(m)[None, :, None],
        node_slot[:, None, :]].max(anc_pair)
    mask = committed | tree_part

    rope_pos = base_pos[:, None] + dep_s
    write_pos = node_slot
    out = tree_forward(spec, tpu_cfg, params, cache, node_toks, rope_pos,
                       write_pos, seq_ids, mask)
    greedy = jnp.argmax(out["logits_all"], axis=-1).astype(jnp.int32)

    # selection-index of each node's parent: inverse map lattice id -> slot
    inv = jnp.full((b, n_lat), 0, jnp.int32).at[
        jnp.arange(b)[:, None], sel].set(
        jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None], (b, m)))
    par_slot = jnp.take_along_axis(inv, lat_par[sel], axis=-1)  # (B, M)
    pred_at_parent = jnp.take_along_axis(greedy, par_slot, axis=-1)
    edge_ok = jnp.where(dep_s > 0, node_toks == pred_at_parent, True)
    # chain: every selected ancestor's edge matches
    chain = jnp.all(~anc_pair | edge_ok[:, None, :], axis=-1)  # (B, M)
    cand_depth = jnp.where(chain, dep_s, -1)
    best = jnp.argmax(cand_depth, axis=-1).astype(jnp.int32)   # (B,)
    n_acc = jnp.take_along_axis(dep_s, best[:, None], 1)[:, 0]
    bonus = jnp.take_along_axis(greedy, best[:, None], 1)[:, 0]

    # accepted path tokens: lattice path of best -> selection slots -> toks
    best_lat = jnp.take_along_axis(sel, best[:, None], 1)[:, 0]
    path_lat = jnp.maximum(jnp.asarray(lat_path)[best_lat], 0)  # (B, D+1)
    path_slot = jnp.take_along_axis(inv, path_lat, axis=-1)
    path_toks = jnp.take_along_axis(node_toks, path_slot, axis=-1)
    d1 = path_toks.shape[1]
    idx = jnp.arange(d1, dtype=jnp.int32)[None, :]
    shifted = jnp.concatenate(
        [path_toks[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1)
    tokens = jnp.where(idx < n_acc[:, None], shifted,
                       jnp.where(idx == n_acc[:, None], bonus[:, None], 0))
    feat = jnp.take_along_axis(
        out["hidden"], best[:, None, None], axis=1)[:, 0]

    # cache refresh: linearize [root, accepted..., bonus]
    refresh_toks = jnp.concatenate([root[:, None], tokens], axis=1)
    r_w = refresh_toks.shape[1]
    ridx = jnp.arange(r_w, dtype=jnp.int32)[None, :]
    rpos = base_pos[:, None] + ridx
    rpos = jnp.where(ridx <= (n_acc + 1)[:, None], rpos,
                     kv_mod.cache_len_of(out["cache"]))
    upd = model_base.token_generation_multi(
        spec, tpu_cfg, params, out["cache"], refresh_toks, rpos, seq_ids)
    res = {"tokens": tokens, "num_emitted": n_acc + 1, "bonus": bonus,
           "feature": feat, "cache": upd["cache"]}
    if return_path_features:
        # features along the accepted path (node j = depth j), for the
        # EAGLE draft refresh: slot base+j+1 pairs with the feature of
        # position base+j — only the EAGLE tree step pays for this gather
        res["path_features"] = jnp.take_along_axis(
            out["hidden"], path_slot[:, :, None], axis=1)    # (B, D+1, H)
    return res


class DynamicTreeDecoder:
    """Host loop for DYNAMIC-tree medusa speculation (reference:
    modules/eagle/dynamic_token_tree.py): per step the tree shape follows
    the proposal distribution instead of a fixed template."""

    def __init__(self, target_app, branch_k: int = 4,
                 num_nodes: int = 16):
        self.target = target_app
        cfg = target_app.tpu_config
        if target_app.spec.medusa_heads < 1:
            raise ValueError("medusa heads required")
        self.depth = target_app.spec.medusa_heads
        self.branch_k = branch_k
        self.num_nodes = num_nodes
        dep, par, br, anc, path = build_lattice(branch_k, self.depth)
        if num_nodes > dep.shape[0]:
            raise ValueError("num_nodes exceeds the candidate lattice")
        self._lat = tuple(jnp.asarray(x) for x in (dep, par, br, anc, path))
        self._step = jax.jit(
            partial(dynamic_medusa_tree_step, target_app.spec, cfg,
                    num_nodes=num_nodes, cache_len=cfg.seq_len),
            donate_argnums=(1,))
        self._propose = jax.jit(
            partial(medusa_propose_scored, target_app.spec),
            static_argnames=("top_k",))

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None):
        input_ids = np.asarray(input_ids).astype(np.int32)
        b, s = input_ids.shape
        seq_lens = np.full((b,), s, np.int32)
        seq_ids = jnp.arange(b, dtype=jnp.int32)
        t_out = self.target._run_prefill(input_ids, seq_lens)
        root = jnp.asarray(np.asarray(t_out["tokens"]).astype(np.int32))
        ptoks, plogp = self._propose(self.target.params,
                                     t_out["last_hidden"],
                                     top_k=self.branch_k)
        eos_set = (None if eos_token_id is None else
                   set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
        out_rows = [[int(np.asarray(root)[i])] for i in range(b)]
        positions = seq_lens.copy()
        done = np.zeros((b,), bool)
        emitted_counts = []
        while (min(len(r) for r in out_rows) < max_new_tokens
               and int(positions.max()) + self.num_nodes + 1
               < self.target.tpu_config.seq_len
               and not done.all()):
            res = self._step(self.target.params, self.target.cache, root,
                             ptoks, plogp, jnp.asarray(positions), seq_ids,
                             *self._lat)
            self.target.cache = res["cache"]
            toks = np.asarray(res["tokens"])
            n_emit = np.asarray(res["num_emitted"])
            emitted_counts.append(n_emit.copy())
            for i in range(b):
                if done[i]:
                    continue
                for tk in toks[i, :n_emit[i]].tolist():
                    out_rows[i].append(int(tk))
                    if eos_set is not None and int(tk) in eos_set:
                        done[i] = True
                        break
            positions = positions + n_emit.astype(np.int32)
            root = res["bonus"]
            ptoks, plogp = self._propose(self.target.params, res["feature"],
                                         top_k=self.branch_k)
        gen = np.zeros((b, max_new_tokens), np.int32)
        for i in range(b):
            row = out_rows[i][:max_new_tokens]
            gen[i, :len(row)] = row
        return {"generated": gen,
                "sequences": np.concatenate([input_ids, gen], axis=1),
                "mean_accept": (float(np.mean(np.concatenate(emitted_counts)))
                                if emitted_counts else 0.0)}


# ===========================================================================
# EAGLE token-tree speculation (reference: the EAGLE token-tree flagship
# mode, models/model_base.py:2094-2515 — tree proposals come from the EAGLE
# DRAFT model, the target verifies the tree in one forward; the dynamic
# lattice (EAGLE-2 style, modules/eagle/dynamic_token_tree.py) selects the
# tree shape from the draft's own scores)
# ===========================================================================

def eagle_propose_scored(draft_spec: DecoderSpec, tpu_cfg: TpuConfig,
                         draft_params, draft_cache, last_token, prev_hidden,
                         positions, seq_ids, depth: int, top_k: int,
                         input_norm: bool = False):
    """Chain-rollout tree proposals from the EAGLE draft: D greedy draft
    steps; at each depth record the top-k tokens + logprobs of that depth's
    distribution. Returns (prop_toks (B,D,k), prop_logp (B,D,k), dcache).
    The rollout's speculative draft-KV writes are overwritten by the
    post-acceptance refresh (reference: :2663-2694)."""

    def dstep(carry, _):
        tok, hid, pos, cch = carry
        out = eagle_forward(draft_spec, tpu_cfg, draft_params, cch,
                            tok[:, None], hid[:, None, :], pos[:, None],
                            seq_ids, input_norm)
        logp = jax.nn.log_softmax(out["logits"][:, -1, :].astype(jnp.float32))
        top_lp, top_ids = jax.lax.top_k(logp, top_k)
        ntok = top_ids[:, 0].astype(jnp.int32)
        return (ntok, out["hidden"][:, -1, :], pos + 1, out["cache"]), \
            (top_ids.astype(jnp.int32), top_lp)

    (_, _, _, dcache), (toks, lps) = jax.lax.scan(
        dstep, (last_token, prev_hidden, positions, draft_cache), None,
        length=depth)
    return (jnp.transpose(toks, (1, 0, 2)), jnp.transpose(lps, (1, 0, 2)),
            dcache)


def eagle_tree_step(draft_spec: DecoderSpec, target_spec: DecoderSpec,
                    tpu_cfg: TpuConfig, draft_params, target_params,
                    draft_cache, target_cache, root, prev_hidden, base_pos,
                    seq_ids, lat_dep, lat_par, lat_br, lat_anc, lat_path,
                    num_nodes: int, cache_len: int, depth: int,
                    branch_k: int, input_norm: bool = False):
    """One fused EAGLE token-tree step: draft chain rollout scores the
    lattice, the dynamic top-N tree is verified by the target in one
    forward, and BOTH caches are refreshed with the accepted linear
    sequence. root (B,) at position base_pos (already emitted);
    prev_hidden (B,H) = target feature at base_pos-1."""
    prop_toks, prop_logp, dcache = eagle_propose_scored(
        draft_spec, tpu_cfg, draft_params, draft_cache, root, prev_hidden,
        base_pos, seq_ids, depth, branch_k, input_norm)
    res = dynamic_medusa_tree_step(
        target_spec, tpu_cfg, target_params, target_cache, root, prop_toks,
        prop_logp, base_pos, seq_ids, lat_dep, lat_par, lat_br, lat_anc,
        lat_path, num_nodes=num_nodes, cache_len=cache_len,
        return_path_features=True)

    # draft refresh with the VERIFIED pairs: slot base+j <- (token at
    # base+j, target feature at base+j-1). The rollout's chain writes are
    # stale wherever the accepted path deviated from the draft's greedy
    # chain (reference: final draft cache-update run :2663-2694).
    n_acc = res["num_emitted"] - 1
    refresh_toks = jnp.concatenate([root[:, None], res["tokens"]], axis=1)
    # widths agree by construction: 1 root + (depth+1) tokens vs
    # 1 prev_hidden + (depth+1) path features
    hid_seq = jnp.concatenate(
        [prev_hidden[:, None, :], res["path_features"]], axis=1)
    ridx = jnp.arange(refresh_toks.shape[1], dtype=jnp.int32)[None, :]
    rpos = base_pos[:, None] + ridx
    rpos = jnp.where(ridx <= (n_acc + 1)[:, None], rpos,
                     kv_mod.cache_len_of(dcache))
    upd = eagle_forward(draft_spec, tpu_cfg, draft_params, dcache,
                        refresh_toks, hid_seq, rpos, seq_ids, input_norm)
    return {"tokens": res["tokens"], "num_emitted": res["num_emitted"],
            "bonus": res["bonus"], "feature": res["feature"],
            "draft_cache": upd["cache"], "target_cache": res["cache"]}


class EagleTreeDecoder:
    """Host loop for EAGLE token-tree speculation: the EAGLE draft proposes,
    the dynamic lattice picks the top-N tree, the target verifies it in one
    forward (reference: model_base.py:2094-2515)."""

    def __init__(self, target_app, draft_spec: DecoderSpec, draft_params,
                 draft_cache, depth: int = 4, branch_k: int = 4,
                 num_nodes: int = 16, input_norm: bool = False):
        self.target = target_app
        self.draft_spec = draft_spec
        self.draft_params = draft_params
        self.draft_cache = draft_cache
        self.depth = depth
        self.num_nodes = num_nodes
        self.branch_k = branch_k
        cfg = target_app.tpu_config
        dep, par, br, anc, path = build_lattice(branch_k, depth)
        if num_nodes > dep.shape[0]:
            raise ValueError("num_nodes exceeds the candidate lattice")
        self._lat = tuple(jnp.asarray(x) for x in (dep, par, br, anc, path))
        self._step = jax.jit(
            partial(eagle_tree_step, draft_spec, target_app.spec, cfg,
                    num_nodes=num_nodes, cache_len=cfg.seq_len, depth=depth,
                    branch_k=branch_k, input_norm=input_norm),
            donate_argnums=(2, 3))
        self._prefill = jax.jit(
            partial(eagle_forward, draft_spec, cfg, input_norm=input_norm),
            donate_argnums=(1,))

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids).astype(np.int32)
        b, s = input_ids.shape
        cfg = self.target.tpu_config
        if not cfg.output_full_hidden:
            raise ValueError("target app needs output_full_hidden=True "
                             "(EAGLE primes the draft from prefill hiddens)")
        seq_lens = np.full((b,), s, np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        t_out = self.target._run_prefill(input_ids, seq_lens)
        hs = np.asarray(t_out["hidden_states"])[:, :s]
        first = np.asarray(t_out["tokens"]).astype(np.int32)
        _prime_eagle_draft(self, input_ids, hs, seq_ids)

        def step_fn(root, prev_hidden, positions):
            res = self._step(self.draft_params, self.target.params,
                             self.draft_cache, self.target.cache,
                             jnp.asarray(root), prev_hidden,
                             jnp.asarray(positions), jnp.asarray(seq_ids),
                             *self._lat)
            self.draft_cache = res["draft_cache"]
            self.target.cache = res["target_cache"]
            return (np.asarray(res["tokens"]),
                    np.asarray(res["num_emitted"]),
                    np.asarray(res["bonus"]).astype(np.int32),
                    res["feature"])

        budget = max(self.num_nodes, self.depth) + 2
        return _eagle_host_loop(input_ids, first, jnp.asarray(hs[:, -1]),
                                seq_lens, max_new_tokens, eos_token_id,
                                cfg.seq_len, budget, step_fn)
