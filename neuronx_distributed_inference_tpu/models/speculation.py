"""Speculative decoding (reference: models/model_base.py ``NeuronFusedSpecModel``
:1598-3021 and the vanilla speculation submodel).

Two modes, mirroring the reference:

* **Vanilla speculation** — separate draft and target apps; the host loop
  alternates k draft steps and one target verify call
  (reference: utils/hf_adapter.py assisted decoding :439-632).
* **Fused speculation** — draft loop + target verify + acceptance in ONE
  jitted graph per step (reference: _token_gen_forward :1812-1929): the
  draft's k-step autoregressive loop is a ``lax.scan``, the target scores
  all k+1 candidate positions in one batched forward, and acceptance is the
  cumsum-of-mismatch trick (reference: :2726-2730).

Greedy speculation is exactly equivalent to greedy decoding — the tests
assert token-identical output vs the plain decode path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import TpuConfig
from . import model_base
from .model_base import DecoderSpec


def draft_k_tokens(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                   first_token, positions, seq_ids, k: int):
    """Run k greedy draft steps (lax.scan). Returns (draft_tokens (B, k),
    cache). first_token (B,), positions (B,) = position of first_token."""

    def step(carry, _):
        tok, pos, cch = carry
        out = model_base.token_generation_step(
            spec, tpu_cfg, params, cch, tok[:, None], pos[:, None], seq_ids,
            None, jax.random.PRNGKey(0))
        return (out["tokens"], pos + 1, out["cache"]), out["tokens"]

    (_, _, new_cache), toks = jax.lax.scan(
        step, (first_token, positions, cache), None, length=k)
    return jnp.transpose(toks, (1, 0)), new_cache


def fused_speculation_step(draft_spec: DecoderSpec, target_spec: DecoderSpec,
                           tpu_cfg: TpuConfig, draft_params, target_params,
                           draft_cache, target_cache, last_token, positions,
                           seq_ids, rng):
    """One fused speculation step (reference: _token_gen_forward :1812-1929).

    last_token (B,): last accepted token. positions (B,): its position.
    Returns dict(tokens (B, k+1), num_accepted (B,), caches).
    Greedy acceptance: accept draft token i iff target's greedy choice at
    position i equals it; always emit one bonus token from the target
    (reference acceptance: cumsum-of-mismatch :2726-2730).
    """
    k = tpu_cfg.speculation_length
    b = last_token.shape[0]

    # 1) k-step draft loop (in-graph scan; reference unrolls :2552-2611)
    draft_tokens, new_draft_cache = draft_k_tokens(
        draft_spec, tpu_cfg, draft_params, draft_cache, last_token, positions,
        seq_ids, k)

    # 2) target verifies all k+1 positions in one forward
    #    (reference: target_model(candidate_ids…) :2617-2642)
    cand = jnp.concatenate([last_token[:, None], draft_tokens], axis=1)  # (B, k+1)
    cand_pos = positions[:, None] + jnp.arange(k + 1, dtype=positions.dtype)
    t_out = model_base.token_generation_multi(
        target_spec, tpu_cfg, target_params, target_cache, cand, cand_pos,
        seq_ids)
    target_logits = t_out["logits_all"]            # (B, k+1, V)
    new_target_cache = t_out["cache"]
    target_greedy = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # (B, k+1)

    # 3) acceptance: n_matches = count of leading draft tokens equal to the
    #    target's choices (cumsum-of-mismatch, reference :2726-2730)
    mismatch = (draft_tokens != target_greedy[:, :k]).astype(jnp.int32)
    n_accepted = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)  # (B,) in [0, k]

    # 4) emitted tokens: accepted draft tokens then the target's correction /
    #    bonus token at position n_accepted
    idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    accepted_mask = idx < n_accepted[:, None]
    bonus = jnp.take_along_axis(target_greedy, n_accepted[:, None], axis=1)
    tokens = jnp.where(accepted_mask,
                       jnp.concatenate([draft_tokens,
                                        jnp.zeros((b, 1), jnp.int32)], axis=1),
                       jnp.where(idx == n_accepted[:, None], bonus, 0))
    return {
        "tokens": tokens,                 # (B, k+1); positions > n_accepted are 0
        "num_accepted": n_accepted + 1,   # emitted per row (accepted + bonus)
        "draft_cache": new_draft_cache,
        "target_cache": new_target_cache,
    }


class SpeculativeDecoder:
    """Host orchestration for fused speculation
    (reference: NeuronBaseForCausalLM fused-spec routing :3078,
    hf_adapter fused decode loop :495).

    Wraps a target CausalLMApplication and a draft CausalLMApplication that
    share batch geometry; both caches advance together. The per-row emitted
    count varies, so rows advance at different positions — handled exactly
    like the reference by tracking per-row positions.
    """

    def __init__(self, target_app, draft_app):
        from .application import CausalLMApplication  # noqa: F401 (typing)
        self.target = target_app
        self.draft = draft_app
        cfg = target_app.tpu_config
        if not cfg.speculation_config or cfg.speculation_config.speculation_length < 1:
            raise ValueError("target app needs speculation_config.speculation_length >= 1")
        self.k = cfg.speculation_config.speculation_length
        self._step_fn = None

    def _build_step(self):
        if self._step_fn is None:
            fn = partial(fused_speculation_step, self.draft.spec,
                         self.target.spec, self.target.tpu_config)
            self._step_fn = jax.jit(fn, donate_argnums=(2, 3))
        return self._step_fn

    def generate(self, input_ids: np.ndarray, max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None,
                 attention_mask: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Greedy speculative generation; exactly matches greedy decode."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        eos_set = (None if eos_token_id is None else
                   set(np.atleast_1d(np.asarray(eos_token_id)).tolist()))
        eos_fill = None if eos_set is None else next(iter(eos_set))

        # prefill BOTH models (reference: EAGLE/fused CTE runs both)
        t_out = self.target._run_prefill(input_ids.astype(np.int32), seq_lens)
        self.draft._run_prefill(input_ids.astype(np.int32), seq_lens)
        first = np.asarray(t_out["tokens"]).astype(np.int32)   # (B,)

        step = self._build_step()
        out_rows = [[int(first[i])] for i in range(b)]
        last = first
        positions = seq_lens.astype(np.int32)
        seq_ids = np.arange(b, dtype=np.int32)
        done = np.zeros((b,), bool)
        total_accepted_stats = []
        max_total = self.target.tpu_config.seq_len
        while (min(len(r) for r in out_rows) < max_new_tokens
               and int(positions.max()) + self.k + 1 < max_total
               and not done.all()):
            res = step(self.draft.params, self.target.params,
                       self.draft.cache, self.target.cache,
                       jnp.asarray(last), jnp.asarray(positions),
                       jnp.asarray(seq_ids), jax.random.PRNGKey(0))
            self.draft.cache = res["draft_cache"]
            self.target.cache = res["target_cache"]
            toks = np.asarray(res["tokens"])
            n_emit = np.asarray(res["num_accepted"])
            total_accepted_stats.append(n_emit.copy())
            for i in range(b):
                if done[i]:
                    continue
                row = toks[i, :n_emit[i]].tolist()
                for t in row:
                    out_rows[i].append(int(t))
                    if eos_set is not None and int(t) in eos_set:
                        done[i] = True
                        break
            positions = positions + n_emit.astype(np.int32)
            last = toks[np.arange(b), n_emit - 1].astype(np.int32)

        gen = np.zeros((b, max_new_tokens), np.int32)
        for i in range(b):
            row = out_rows[i][:max_new_tokens]
            gen[i, :len(row)] = row
            if len(row) < max_new_tokens:
                gen[i, len(row):] = row[-1] if eos_fill is None else eos_fill
        mean_emitted = (float(np.mean(np.concatenate(total_accepted_stats)))
                        if total_accepted_stats else 0.0)
        return {
            "sequences": np.concatenate([input_ids, gen], axis=1),
            "generated": gen,
            "mean_tokens_per_step": mean_emitted,
        }
