"""Gemma2 family (reference analog: contrib gemma models — SURVEY §2.7
contrib hub). Gemma3's sibling: same sandwich norms / (1+w) RMSNorm /
sqrt(H) embed scale / alternating sliding layers, but a single rope theta
and attn+final logit softcapping."""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..gemma3.modeling_gemma3 import Gemma3Family
from ..model_base import DecoderSpec, spec_from_config


class Gemma2InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "head_dim", "sliding_window"]


@register_family("gemma2")
class Gemma2Family(DecoderFamily):
    config_cls = Gemma2InferenceConfig
    post_norm_src = "pre_feedforward_layernorm"
    # sandwich-norm weights load identically to gemma3
    convert_extra_layer_weights = Gemma3Family.convert_extra_layer_weights

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        n_layers = config.num_hidden_layers
        layer_types = getattr(config, "layer_types", None)
        if layer_types is None:
            pattern_n = getattr(config, "sliding_window_pattern", 2)
            layer_types = ["sliding_attention" if (i + 1) % pattern_n else
                           "full_attention" for i in range(n_layers)]
        pattern = tuple(t == "sliding_attention" for t in layer_types)
        scalar = float(getattr(config, "query_pre_attn_scalar",
                               config.head_dim))
        return spec_from_config(
            config, tp_degree,
            sliding_window=int(config.sliding_window),
            layer_pattern=pattern,
            sandwich_norm=True,
            norm_offset=1.0,
            attn_scale=scalar ** -0.5,
            embed_scale=math.sqrt(config.hidden_size),
            logits_soft_cap=getattr(config, "final_logit_softcapping", 30.0),
            attn_soft_cap=getattr(config, "attn_logit_softcapping", 50.0),
            act=getattr(config, "hidden_activation", "gelu_pytorch_tanh"),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )


def TpuGemma2ForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, Gemma2Family)
