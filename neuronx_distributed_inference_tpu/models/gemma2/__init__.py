from .modeling_gemma2 import (Gemma2Family, Gemma2InferenceConfig,
                            TpuGemma2ForCausalLM)
