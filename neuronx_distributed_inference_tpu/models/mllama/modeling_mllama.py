"""MLlama (Llama-3.2 Vision) family — cross-attention decoder + multimodal
KV manager (reference: models/mllama/ — modeling_mllama.py cross-attention
decoder layers, modules/kvcache/multimodal_kv_cache_manager.py,
model_wrapper_mllama.py; 3380 LoC).

TPU design:
  * The text stack interleaves standard self-attention layers (the shared
    DecoderSpec machinery, scanned per contiguous segment via
    model_base.run_layer_slice) with tanh-gated cross-attention layers that
    attend to vision states.
  * Cross-attention K/V is the multimodal KV cache: computed ONCE per
    request from the vision states (``compute_cross_kv``) and fed read-only
    into every prefill/decode step — the analog of the reference's
    MultimodalKVCacheManager holding cross-attention caches outside the
    autoregressive cache.
  * ``full_text_row_masked_out_mask`` semantics preserved: a text row whose
    cross-attention mask is fully off attends uniformly (its additive mask
    zeroes out) and its gated-MLP delta is suppressed
    (HF _prepare_cross_attention_mask).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig, TpuConfig
from ...modules.kv_cache import KVCacheSpec, cache_len_of, init_cache
from ...ops import attention as attn_ops
from ...ops import sampling as sampling_ops
from ...ops.normalization import rms_norm
from ...parallel.layers import place_q_weight, replicate_kv_weight
from ...utils import checkpoint as ckpt
from ..family import DecoderFamily, register_family
from ..model_base import (DecoderSpec, _embed, _lm_head, attn_inputs,
                          run_layer_slice, spec_from_config)


@dataclass(frozen=True)
class MllamaSpec:
    """Layer interleave plan: walk ``segments`` = [(n_self, has_cross), ...]
    over the total stack (cross layer indices from HF
    ``cross_attention_layers``)."""
    segments: Tuple[Tuple[int, bool], ...]
    num_self: int
    num_cross: int


def build_mllama_plan(total_layers: int, cross_layers: Tuple[int, ...]
                      ) -> MllamaSpec:
    cross = set(int(c) for c in cross_layers)
    segments: List[Tuple[int, bool]] = []
    run = 0
    for i in range(total_layers):
        if i in cross:
            segments.append((run, True))
            run = 0
        else:
            run += 1
    if run:
        segments.append((run, False))
    return MllamaSpec(tuple(segments), total_layers - len(cross), len(cross))


class MllamaTextConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "cross_attention_layers"]


@register_family("mllama_text")
class MllamaTextFamily(DecoderFamily):
    """Self-attention side of the stack (llama-shaped); cross layers are
    converted separately by ``convert_cross_layers``."""
    config_cls = MllamaTextConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        from ..model_base import pad_vocab
        plan = build_mllama_plan(config.num_hidden_layers,
                                 tuple(config.cross_attention_layers))
        tcfg = config.tpu_config
        tp = tp_degree if tp_degree is not None else tcfg.tp_degree
        # HF mllama embeds vocab_size + 8 special image tokens; the embed
        # table (and input ids) cover them while lm_head stays vocab_size
        return spec_from_config(
            config, tp_degree, num_layers=plan.num_self,
            padded_vocab=pad_vocab(config.vocab_size + 8, tp))

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        # remap the non-contiguous self-layer indices onto 0..num_self-1,
        # then run the standard llama conversion
        cross = set()
        i = 0
        remapped = dict(sd)
        # discover cross layers by key shape: cross layers have cross_attn.*
        total = 0
        for k in sd:
            if ".layers." in k:
                total = max(total, int(k.split(".layers.")[1].split(".")[0]) + 1)
            if ".cross_attn." in k:
                cross.add(int(k.split(".layers.")[1].split(".")[0]))
        self_ids = [i for i in range(total) if i not in cross]
        out = {}
        for k, v in sd.items():
            if ".layers." in k:
                li = int(k.split(".layers.")[1].split(".")[0])
                if li in cross:
                    continue
                k = k.replace(f".layers.{li}.",
                              f".layers.{self_ids.index(li)}.")
            out[k] = v
        return super().convert_hf_state_dict(out, spec)


def convert_cross_layers(sd: Dict[str, np.ndarray], spec: DecoderSpec,
                         cross_ids: List[int], prefix: str = "model"
                         ) -> Dict[str, np.ndarray]:
    g, D = spec.gqa, spec.head_dim

    def get(n):
        return np.asarray(sd[n])

    def q_t(w):
        return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

    def kv_t(w):
        return replicate_kv_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

    def o_t(w):
        return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=0)

    def t(w):
        return np.ascontiguousarray(w.T)

    def stack(fmt, tr):
        return np.stack([tr(get(fmt.format(i=i))) for i in cross_ids])

    p = prefix
    return {
        "input_norm": stack(p + ".layers.{i}.input_layernorm.weight",
                            np.asarray),
        "q_proj": stack(p + ".layers.{i}.cross_attn.q_proj.weight", q_t),
        "k_proj": stack(p + ".layers.{i}.cross_attn.k_proj.weight", kv_t),
        "v_proj": stack(p + ".layers.{i}.cross_attn.v_proj.weight", kv_t),
        "o_proj": stack(p + ".layers.{i}.cross_attn.o_proj.weight", o_t),
        "q_norm": stack(p + ".layers.{i}.cross_attn.q_norm.weight",
                        np.asarray),
        "k_norm": stack(p + ".layers.{i}.cross_attn.k_norm.weight",
                        np.asarray),
        "attn_gate": stack(p + ".layers.{i}.cross_attn_attn_gate",
                           np.asarray),
        "mlp_gate": stack(p + ".layers.{i}.cross_attn_mlp_gate", np.asarray),
        "post_norm": stack(p + ".layers.{i}.post_attention_layernorm.weight",
                           np.asarray),
        "gate_proj": stack(p + ".layers.{i}.mlp.gate_proj.weight", t),
        "up_proj": stack(p + ".layers.{i}.mlp.up_proj.weight", t),
        "down_proj": stack(p + ".layers.{i}.mlp.down_proj.weight", t),
    }


def compute_cross_kv(spec: DecoderSpec, cross_params, vision_states):
    """The multimodal KV cache fill (reference:
    multimodal_kv_cache_manager.py): per cross layer,
    k = k_norm(k_proj(vision)), v = v_proj(vision).
    vision_states (B, S_vis, H_text) -> k/v (Lc, B, S_vis, Hkv, D)."""
    b, s, _ = vision_states.shape
    g = spec.gqa

    def one(lw):
        k = (vision_states @ lw["k_proj"]).reshape(b, s, g.num_kv_heads,
                                                   spec.head_dim)
        k = rms_norm(k, lw["k_norm"], spec.rms_eps)
        v = (vision_states @ lw["v_proj"]).reshape(b, s, g.num_kv_heads,
                                                   spec.head_dim)
        return k, v

    ks, vs = jax.lax.map(one, cross_params)
    return {"k": ks, "v": vs}


def _cross_block(spec: DecoderSpec, hidden, lw, ck, cv, cross_mask):
    """One tanh-gated cross-attention decoder layer (HF
    MllamaCrossAttentionDecoderLayer semantics).

    hidden (B, T, H); ck/cv (B, S_vis, Hkv, D); cross_mask (B, T, S_vis)
    bool. HF row semantics (_prepare_cross_attention_mask): a text row whose
    mask is fully off attends ALL keys uniformly (its additive mask zeroes
    out), and only its gated-MLP delta is suppressed."""
    b, t, _ = hidden.shape
    g = spec.gqa
    row_any = cross_mask.any(axis=-1, keepdims=True)        # (B, T, 1)
    eff_mask = jnp.where(row_any, cross_mask, True)
    r = rms_norm(hidden, lw["input_norm"], spec.rms_eps)
    q = (r @ lw["q_proj"]).reshape(b, t, g.num_q_heads, spec.head_dim)
    q = rms_norm(q, lw["q_norm"], spec.rms_eps)
    a = attn_ops.mha(q, ck, cv, eff_mask, spec.scale)
    a = a.reshape(b, t, -1) @ lw["o_proj"]
    hidden = hidden + jnp.tanh(lw["attn_gate"]) * a
    r = rms_norm(hidden, lw["post_norm"], spec.rms_eps)
    m = (jax.nn.silu(r @ lw["gate_proj"]) * (r @ lw["up_proj"])) \
        @ lw["down_proj"]
    m = m * row_any.astype(m.dtype)
    return hidden + jnp.tanh(lw["mlp_gate"]) * m


def mllama_forward(spec: DecoderSpec, mspec: MllamaSpec, tcfg: TpuConfig,
                   params, cache, cross_kv, input_ids, position_ids, seq_ids,
                   seq_lens, cross_mask, sampling_params, rng,
                   phase: str):
    """One prefill or decode step through the interleaved stack.

    phase "prefill": causal in-window self attention; cross_mask covers the
    padded window. phase "decode": T=1 over the self cache."""
    if phase == "prefill":
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.prefill_causal_mask(
                             input_ids.shape[1], position_ids, window=w, chunk=c))
    else:
        cache_len = cache_len_of(cache)
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.decode_mask(position_ids,
                                                        cache_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids)
    kf, vf = cache["k"], cache["v"]
    si = ci = 0
    empty_local = jnp.zeros((0,), bool)
    for n_self, has_cross in mspec.segments:
        if n_self:
            seg = jax.tree.map(lambda a: a[si:si + n_self], params["layers"])
            hidden, kf, vf, _ = run_layer_slice(
                spec, seg, kf, vf, hidden, ai, cache_offset=si,
                is_local=jnp.zeros((n_self,), bool), rep={}, mlp_kind=None,
                seq_ids=seq_ids, positions=position_ids, phase=phase,
                identity_seq_ids=not tcfg.is_continuous_batching,
                arange_positions=(phase == "prefill"))
            si += n_self
        if has_cross:
            lw = jax.tree.map(lambda a: a[ci], params["cross_layers"])
            hidden = _cross_block(spec, hidden, lw, cross_kv["k"][ci],
                                  cross_kv["v"][ci], cross_mask)
            ci += 1
    out: Dict[str, Any] = {"cache": {"k": kf, "v": vf}}
    if phase == "prefill":
        idx = jnp.maximum(seq_lens - 1, 0)
        last_h = jnp.take_along_axis(hidden, idx[:, None, None].astype(jnp.int32),
                                     axis=1)
        logits = _lm_head(spec, params, last_h)[:, 0, :]
        if tcfg.output_logits:
            out["logits"] = _lm_head(spec, params,
                                     hidden)[..., :spec.vocab_size]
    else:
        full = _lm_head(spec, params, hidden)
        logits = full[:, -1, :]
        if tcfg.output_logits:
            out["logits"] = full[..., :spec.vocab_size]
    out["tokens"] = sampling_ops.sample(
        logits, tcfg.on_device_sampling_config, sampling_params, rng)
    return out


class MllamaInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_index"]


class MllamaApplication:
    """Cross-attention text application (reference: NeuronMllamaForCausalLM +
    its dedicated ModelWrapper, model_wrapper_mllama.py). Vision states come
    either from the vision tower or directly (``vision_states=`` argument —
    the reference supports the same split via its two builders)."""

    def __init__(self, model_path: Optional[str], config, mesh=None):
        from ...parallel.mesh import mesh_from_config
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.model_path = model_path
        tc = dict(config.text_config) if hasattr(config, "text_config") \
            else {}
        self.text_config = MllamaTextConfig(self.tpu_config, **tc)
        self.mesh = mesh or mesh_from_config(self.tpu_config)
        mp = self.mesh.shape["tp"] * self.mesh.shape["ep"]
        self.spec = MllamaTextFamily.build_spec(self.text_config, mp)
        self.plan = build_mllama_plan(
            self.text_config.num_hidden_layers,
            tuple(self.text_config.cross_attention_layers))
        self.params = None
        self.cache = None
        self._rng = jax.random.PRNGKey(self.tpu_config.seed)
        self._cross_fn = jax.jit(partial(compute_cross_kv, self.spec))
        self._steps: Dict[str, Any] = {}

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
            for pre in ("model.language_model.", "language_model.model.",
                        "language_model."):
                if k.startswith(pre):
                    text_sd["model." + k[len(pre):]] = v
                    break
            else:
                if k.startswith("model.layers."):
                    text_sd[k] = v
                elif k.startswith("model.") and ".layers." not in k:
                    text_sd[k] = v
        from .. import model_base
        host = model_base.fuse_qkv_host(
            MllamaTextFamily.convert_hf_state_dict(text_sd, self.spec))
        cross_ids = sorted(
            int(c) for c in self.text_config.cross_attention_layers)
        host["cross_layers"] = convert_cross_layers(text_sd, self.spec,
                                                    cross_ids)
        self.params = jax.tree.map(jnp.asarray, host)
        return self

    def init_cache(self):
        cfg = self.tpu_config
        kvspec = KVCacheSpec(
            num_layers=self.spec.num_layers, batch_size=cfg.kv_cache_batch_size,
            max_seq_len=cfg.seq_len, num_kv_heads=self.spec.gqa.num_kv_heads,
            head_dim=self.spec.head_dim, dtype=self.spec.kv_dtype)
        self.cache = init_cache(kvspec, self.mesh)
        return self

    def _step(self, phase):
        if phase not in self._steps:
            self._steps[phase] = jax.jit(
                partial(mllama_forward, self.spec, self.plan,
                        self.tpu_config, phase=phase), donate_argnums=(1,))
        return self._steps[phase]

    def generate(self, input_ids: np.ndarray, vision_states: np.ndarray,
                 cross_attention_mask: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 16,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        """vision_states (B, S_vis, H_text): flattened projected vision
        hidden states; cross_attention_mask (B, S_text, S_vis) bool (True =
        attend) — defaults to all-on."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        if self.cache is None:
            self.init_cache()
        s_vis = vision_states.shape[1]
        if cross_attention_mask is None:
            cross_attention_mask = np.ones((b, s, s_vis), bool)
        cross_kv = self._cross_fn(params_cross(self.params),
                                  jnp.asarray(vision_states,
                                              self.spec.dtype))

        self._rng, k1 = jax.random.split(self._rng)
        pos = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s))
        out = self._step("prefill")(
            self.params, self.cache, cross_kv, jnp.asarray(input_ids),
            jnp.asarray(pos), jnp.arange(b, dtype=jnp.int32),
            jnp.asarray(seq_lens), jnp.asarray(cross_attention_mask),
            None, k1)
        self.cache = out["cache"]
        tokens = [np.asarray(out["tokens"]).reshape(b, 1)]
        logits = [np.asarray(out["logits"])] if "logits" in out else []

        # decode: the new token reuses the LAST text row's cross mask (HF
        # extends the mask with the final row during generation)
        dec_mask = cross_attention_mask[:, -1:, :]
        positions = seq_lens.astype(np.int32)
        eos_ids = (None if eos_token_id is None
                   else np.atleast_1d(np.asarray(eos_token_id)))
        for _ in range(max_new_tokens - 1):
            self._rng, k1 = jax.random.split(self._rng)
            o = self._step("decode")(
                self.params, self.cache, cross_kv,
                jnp.asarray(tokens[-1][:, -1:].astype(np.int32)),
                jnp.asarray(positions[:, None]),
                jnp.arange(b, dtype=jnp.int32), None,
                jnp.asarray(dec_mask), None, k1)
            self.cache = o["cache"]
            tokens.append(np.asarray(o["tokens"]).reshape(b, 1))
            if "logits" in o:
                logits.append(np.asarray(o["logits"]))
            positions = positions + 1
            if eos_ids is not None and np.isin(tokens[-1], eos_ids).all():
                break
        gen = np.concatenate(tokens, axis=1)
        res = {"sequences": np.concatenate([input_ids, gen], axis=1),
               "generated": gen}
        if logits:
            res["logits"] = logits
        return res

    def reset(self):
        self.init_cache()
        return self


def params_cross(params):
    return params["cross_layers"]
