"""MLlama (Llama-3.2 Vision) family — cross-attention decoder + multimodal
KV manager (reference: models/mllama/ — modeling_mllama.py cross-attention
decoder layers, modules/kvcache/multimodal_kv_cache_manager.py,
model_wrapper_mllama.py; 3380 LoC).

TPU design:
  * The text stack interleaves standard self-attention layers (the shared
    DecoderSpec machinery, scanned per contiguous segment via
    model_base.run_layer_slice) with tanh-gated cross-attention layers that
    attend to vision states.
  * Cross-attention K/V is the multimodal KV cache: computed ONCE per
    request from the vision states (``compute_cross_kv``) and fed read-only
    into every prefill/decode step — the analog of the reference's
    MultimodalKVCacheManager holding cross-attention caches outside the
    autoregressive cache.
  * ``full_text_row_masked_out_mask`` semantics preserved: a text row whose
    cross-attention mask is fully off attends uniformly (its additive mask
    zeroes out) and its gated-MLP delta is suppressed
    (HF _prepare_cross_attention_mask).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.host_loop import greedy_host_loop

from ...config import InferenceConfig, TpuConfig
from ...modules.kv_cache import KVCacheSpec, cache_len_of, init_cache
from ...ops import attention as attn_ops
from ...ops import sampling as sampling_ops
from ...ops.normalization import rms_norm
from ...parallel.layers import place_q_weight, replicate_kv_weight
from ...utils import checkpoint as ckpt
from ..family import DecoderFamily, register_family
from ..model_base import (DecoderSpec, _embed, _lm_head, attn_inputs,
                          run_layer_slice, spec_from_config)


@dataclass(frozen=True)
class MllamaSpec:
    """Layer interleave plan: walk ``segments`` = [(n_self, has_cross), ...]
    over the total stack (cross layer indices from HF
    ``cross_attention_layers``)."""
    segments: Tuple[Tuple[int, bool], ...]
    num_self: int
    num_cross: int


def build_mllama_plan(total_layers: int, cross_layers: Tuple[int, ...]
                      ) -> MllamaSpec:
    cross = set(int(c) for c in cross_layers)
    segments: List[Tuple[int, bool]] = []
    run = 0
    for i in range(total_layers):
        if i in cross:
            segments.append((run, True))
            run = 0
        else:
            run += 1
    if run:
        segments.append((run, False))
    return MllamaSpec(tuple(segments), total_layers - len(cross), len(cross))


class MllamaTextConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "cross_attention_layers"]


@register_family("mllama_text")
class MllamaTextFamily(DecoderFamily):
    """Self-attention side of the stack (llama-shaped); cross layers are
    converted separately by ``convert_cross_layers``."""
    config_cls = MllamaTextConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        from ..model_base import pad_vocab
        plan = build_mllama_plan(config.num_hidden_layers,
                                 tuple(config.cross_attention_layers))
        tcfg = config.tpu_config
        tp = tp_degree if tp_degree is not None else tcfg.tp_degree
        # HF mllama embeds vocab_size + 8 special image tokens; the embed
        # table (and input ids) cover them while lm_head stays vocab_size
        return spec_from_config(
            config, tp_degree, num_layers=plan.num_self,
            padded_vocab=pad_vocab(config.vocab_size + 8, tp))

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        # remap the non-contiguous self-layer indices onto 0..num_self-1,
        # then run the standard llama conversion
        cross = set()
        i = 0
        remapped = dict(sd)
        # discover cross layers by key shape: cross layers have cross_attn.*
        total = 0
        for k in sd:
            if ".layers." in k:
                total = max(total, int(k.split(".layers.")[1].split(".")[0]) + 1)
            if ".cross_attn." in k:
                cross.add(int(k.split(".layers.")[1].split(".")[0]))
        self_ids = [i for i in range(total) if i not in cross]
        out = {}
        for k, v in sd.items():
            if ".layers." in k:
                li = int(k.split(".layers.")[1].split(".")[0])
                if li in cross:
                    continue
                k = k.replace(f".layers.{li}.",
                              f".layers.{self_ids.index(li)}.")
            out[k] = v
        return super().convert_hf_state_dict(out, spec)


def convert_cross_layers(sd: Dict[str, np.ndarray], spec: DecoderSpec,
                         cross_ids: List[int], prefix: str = "model"
                         ) -> Dict[str, np.ndarray]:
    g, D = spec.gqa, spec.head_dim

    def get(n):
        return np.asarray(sd[n])

    def q_t(w):
        return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

    def kv_t(w):
        return replicate_kv_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

    def o_t(w):
        return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=0)

    def t(w):
        return np.ascontiguousarray(w.T)

    def stack(fmt, tr):
        return np.stack([tr(get(fmt.format(i=i))) for i in cross_ids])

    p = prefix
    return {
        "input_norm": stack(p + ".layers.{i}.input_layernorm.weight",
                            np.asarray),
        "q_proj": stack(p + ".layers.{i}.cross_attn.q_proj.weight", q_t),
        "k_proj": stack(p + ".layers.{i}.cross_attn.k_proj.weight", kv_t),
        "v_proj": stack(p + ".layers.{i}.cross_attn.v_proj.weight", kv_t),
        "o_proj": stack(p + ".layers.{i}.cross_attn.o_proj.weight", o_t),
        "q_norm": stack(p + ".layers.{i}.cross_attn.q_norm.weight",
                        np.asarray),
        "k_norm": stack(p + ".layers.{i}.cross_attn.k_norm.weight",
                        np.asarray),
        "attn_gate": stack(p + ".layers.{i}.cross_attn_attn_gate",
                           np.asarray),
        "mlp_gate": stack(p + ".layers.{i}.cross_attn_mlp_gate", np.asarray),
        "post_norm": stack(p + ".layers.{i}.post_attention_layernorm.weight",
                           np.asarray),
        "gate_proj": stack(p + ".layers.{i}.mlp.gate_proj.weight", t),
        "up_proj": stack(p + ".layers.{i}.mlp.up_proj.weight", t),
        "down_proj": stack(p + ".layers.{i}.mlp.down_proj.weight", t),
    }


def compute_cross_kv(spec: DecoderSpec, cross_params, vision_states):
    """The multimodal KV cache fill (reference:
    multimodal_kv_cache_manager.py): per cross layer,
    k = k_norm(k_proj(vision)), v = v_proj(vision).
    vision_states (B, S_vis, H_text) -> k/v (Lc, B, S_vis, Hkv, D)."""
    b, s, _ = vision_states.shape
    g = spec.gqa

    def one(lw):
        k = (vision_states @ lw["k_proj"]).reshape(b, s, g.num_kv_heads,
                                                   spec.head_dim)
        k = rms_norm(k, lw["k_norm"], spec.rms_eps)
        v = (vision_states @ lw["v_proj"]).reshape(b, s, g.num_kv_heads,
                                                   spec.head_dim)
        return k, v

    ks, vs = jax.lax.map(one, cross_params)
    return {"k": ks, "v": vs}


def _cross_block(spec: DecoderSpec, hidden, lw, ck, cv, cross_mask):
    """One tanh-gated cross-attention decoder layer (HF
    MllamaCrossAttentionDecoderLayer semantics).

    hidden (B, T, H); ck/cv (B, S_vis, Hkv, D); cross_mask (B, T, S_vis)
    bool. HF row semantics (_prepare_cross_attention_mask): a text row whose
    mask is fully off attends ALL keys uniformly (its additive mask zeroes
    out), and only its gated-MLP delta is suppressed."""
    b, t, _ = hidden.shape
    g = spec.gqa
    row_any = cross_mask.any(axis=-1, keepdims=True)        # (B, T, 1)
    eff_mask = jnp.where(row_any, cross_mask, True)
    r = rms_norm(hidden, lw["input_norm"], spec.rms_eps)
    q = (r @ lw["q_proj"]).reshape(b, t, g.num_q_heads, spec.head_dim)
    q = rms_norm(q, lw["q_norm"], spec.rms_eps)
    a = attn_ops.mha(q, ck, cv, eff_mask, spec.scale)
    a = a.reshape(b, t, -1) @ lw["o_proj"]
    hidden = hidden + jnp.tanh(lw["attn_gate"]) * a
    r = rms_norm(hidden, lw["post_norm"], spec.rms_eps)
    m = (jax.nn.silu(r @ lw["gate_proj"]) * (r @ lw["up_proj"])) \
        @ lw["down_proj"]
    m = m * row_any.astype(m.dtype)
    return hidden + jnp.tanh(lw["mlp_gate"]) * m


def mllama_forward(spec: DecoderSpec, mspec: MllamaSpec, tcfg: TpuConfig,
                   params, cache, cross_kv, input_ids, position_ids, seq_ids,
                   seq_lens, cross_mask, sampling_params, rng,
                   phase: str):
    """One prefill or decode step through the interleaved stack.

    phase "prefill": causal in-window self attention; cross_mask covers the
    padded window. phase "decode": T=1 over the self cache."""
    if phase == "prefill":
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.prefill_causal_mask(
                             input_ids.shape[1], position_ids, window=w, chunk=c))
    else:
        cache_len = cache_len_of(cache)
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.decode_mask(position_ids,
                                                        cache_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids)
    kf, vf = cache["k"], cache["v"]
    si = ci = 0
    empty_local = jnp.zeros((0,), bool)
    for n_self, has_cross in mspec.segments:
        if n_self:
            seg = jax.tree.map(lambda a: a[si:si + n_self], params["layers"])
            hidden, kf, vf, _ = run_layer_slice(
                spec, seg, kf, vf, hidden, ai, cache_offset=si,
                is_local=jnp.zeros((n_self,), bool), rep={}, mlp_kind=None,
                seq_ids=seq_ids, positions=position_ids, phase=phase,
                identity_seq_ids=not tcfg.is_continuous_batching,
                arange_positions=(phase == "prefill"))
            si += n_self
        if has_cross:
            lw = jax.tree.map(lambda a: a[ci], params["cross_layers"])
            hidden = _cross_block(spec, hidden, lw, cross_kv["k"][ci],
                                  cross_kv["v"][ci], cross_mask)
            ci += 1
    out: Dict[str, Any] = {"cache": {"k": kf, "v": vf}}
    if phase == "prefill":
        idx = jnp.maximum(seq_lens - 1, 0)
        last_h = jnp.take_along_axis(hidden, idx[:, None, None].astype(jnp.int32),
                                     axis=1)
        logits = _lm_head(spec, params, last_h)[:, 0, :]
        if tcfg.output_logits:
            out["logits"] = _lm_head(spec, params,
                                     hidden)[..., :spec.vocab_size]
    else:
        full = _lm_head(spec, params, hidden)
        logits = full[:, -1, :]
        if tcfg.output_logits:
            out["logits"] = full[..., :spec.vocab_size]
    out["tokens"] = sampling_ops.sample(
        logits, tcfg.on_device_sampling_config, sampling_params, rng)
    return out


class MllamaInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_index"]


class MllamaApplication:
    """Cross-attention text application (reference: NeuronMllamaForCausalLM +
    its dedicated ModelWrapper, model_wrapper_mllama.py). Vision states come
    either from the vision tower or directly (``vision_states=`` argument —
    the reference supports the same split via its two builders)."""

    def __init__(self, model_path: Optional[str], config, mesh=None):
        from ...parallel.mesh import mesh_from_config
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.model_path = model_path
        tc = dict(config.text_config) if hasattr(config, "text_config") \
            else {}
        self.text_config = MllamaTextConfig(self.tpu_config, **tc)
        self.mesh = mesh or mesh_from_config(self.tpu_config)
        mp = self.mesh.shape["tp"] * self.mesh.shape["ep"]
        self.spec = MllamaTextFamily.build_spec(self.text_config, mp)
        self.plan = build_mllama_plan(
            self.text_config.num_hidden_layers,
            tuple(self.text_config.cross_attention_layers))
        self.params = None
        self.cache = None
        self._rng = jax.random.PRNGKey(self.tpu_config.seed)
        self._cross_fn = jax.jit(partial(compute_cross_kv, self.spec))
        self._steps: Dict[str, Any] = {}

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
            for pre in ("model.language_model.", "language_model.model.",
                        "language_model."):
                if k.startswith(pre):
                    text_sd["model." + k[len(pre):]] = v
                    break
            else:
                if k.startswith("model.layers."):
                    text_sd[k] = v
                elif k.startswith("model.") and ".layers." not in k:
                    text_sd[k] = v
        from .. import model_base
        host = model_base.fuse_qkv_host(
            MllamaTextFamily.convert_hf_state_dict(text_sd, self.spec))
        cross_ids = sorted(
            int(c) for c in self.text_config.cross_attention_layers)
        host["cross_layers"] = convert_cross_layers(text_sd, self.spec,
                                                    cross_ids)
        self.params = jax.tree.map(jnp.asarray, host)
        # vision tower + projector, when the checkpoint ships them
        vis_prefix = next((p for p in ("model.vision_model", "vision_model")
                           if any(k.startswith(p + ".") for k in sd)), None)
        if vis_prefix is not None and hasattr(self.config, "vision_config"):
            self.vis_spec = mllama_vision_spec(dict(self.config.vision_config))
            self.vision_params = jax.tree.map(
                jnp.asarray,
                convert_mllama_vision(sd, self.vis_spec, vis_prefix))
            proj = next(p for p in ("model.multi_modal_projector",
                                    "multi_modal_projector")
                        if f"{p}.weight" in sd)
            self.projector_w = jnp.asarray(
                np.ascontiguousarray(np.asarray(sd[f"{proj}.weight"],
                                                np.float32).T))
            self.projector_b = jnp.asarray(
                np.asarray(sd[f"{proj}.bias"], np.float32))
            self._vis_fn = jax.jit(partial(mllama_vision_forward,
                                           self.vis_spec))
        return self

    def encode_images(self, pixel_values: np.ndarray,
                      aspect_ratio_ids: np.ndarray,
                      aspect_ratio_mask: np.ndarray) -> jnp.ndarray:
        """HF-processor-layout pixels -> projected cross-attention states
        (B, M*T*(P+1), H_text) (reference: vision builder of the mllama
        wrapper + multi_modal_projector)."""
        feats = self._vis_fn(self.vision_params,
                             jnp.asarray(pixel_values, jnp.float32),
                             jnp.asarray(aspect_ratio_ids),
                             jnp.asarray(aspect_ratio_mask))
        b, m, t, p1, _ = feats.shape
        proj = feats @ self.projector_w + self.projector_b
        return proj.reshape(b, m * t * p1, -1)

    def generate_from_images(self, input_ids: np.ndarray,
                             pixel_values: np.ndarray,
                             aspect_ratio_ids: np.ndarray,
                             aspect_ratio_mask: np.ndarray,
                             cross_attention_mask: Optional[np.ndarray] = None,
                             **kw) -> Dict[str, Any]:
        """End-to-end image->text: cross_attention_mask arrives in the HF
        processor layout (B, S_text, M, T) and is expanded per patch
        (reference: _prepare_cross_attention_mask)."""
        states = self.encode_images(pixel_values, aspect_ratio_ids,
                                    aspect_ratio_mask)
        if cross_attention_mask is not None:
            cm = np.asarray(cross_attention_mask)
            b, s, m, t = cm.shape
            p1 = (self.vis_spec["image_size"] //
                  self.vis_spec["patch_size"]) ** 2 + 1
            cross_attention_mask = np.repeat(
                cm.reshape(b, s, m * t), p1, axis=2).astype(bool)
        return self.generate(input_ids, np.asarray(states),
                             cross_attention_mask=cross_attention_mask, **kw)

    def init_cache(self):
        cfg = self.tpu_config
        kvspec = KVCacheSpec(
            num_layers=self.spec.num_layers, batch_size=cfg.kv_cache_batch_size,
            max_seq_len=cfg.seq_len, num_kv_heads=self.spec.gqa.num_kv_heads,
            head_dim=self.spec.head_dim, dtype=self.spec.kv_dtype)
        self.cache = init_cache(kvspec, self.mesh)
        return self

    def _step(self, phase):
        if phase not in self._steps:
            self._steps[phase] = jax.jit(
                partial(mllama_forward, self.spec, self.plan,
                        self.tpu_config, phase=phase), donate_argnums=(1,))
        return self._steps[phase]

    def generate(self, input_ids: np.ndarray, vision_states: np.ndarray,
                 cross_attention_mask: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 16,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        """vision_states (B, S_vis, H_text): flattened projected vision
        hidden states; cross_attention_mask (B, S_text, S_vis) bool (True =
        attend) — defaults to all-on."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        if self.cache is None:
            self.init_cache()
        s_vis = vision_states.shape[1]
        if cross_attention_mask is None:
            cross_attention_mask = np.ones((b, s, s_vis), bool)
        cross_kv = self._cross_fn(params_cross(self.params),
                                  jnp.asarray(vision_states,
                                              self.spec.dtype))

        self._rng, k1 = jax.random.split(self._rng)
        pos = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s))
        out = self._step("prefill")(
            self.params, self.cache, cross_kv, jnp.asarray(input_ids),
            jnp.asarray(pos), jnp.arange(b, dtype=jnp.int32),
            jnp.asarray(seq_lens), jnp.asarray(cross_attention_mask),
            None, k1)
        self.cache = out["cache"]
        tokens = [np.asarray(out["tokens"]).reshape(b, 1)]
        logits = [np.asarray(out["logits"])] if "logits" in out else []

        # decode: the new token reuses the LAST text row's cross mask (HF
        # extends the mask with the final row during generation)
        dec_mask = jnp.asarray(cross_attention_mask[:, -1:, :])
        eos_ids = (None if eos_token_id is None
                   else np.atleast_1d(np.asarray(eos_token_id)))
        state = {"pos": seq_lens.astype(np.int32)}
        rows = jnp.arange(b, dtype=jnp.int32)

        def step(last):
            self._rng, k1 = jax.random.split(self._rng)
            o = self._step("decode")(
                self.params, self.cache, cross_kv, last[:, None],
                jnp.asarray(state["pos"][:, None]), rows, None, dec_mask,
                None, k1)
            self.cache = o["cache"]
            state["pos"] = state["pos"] + 1
            if "logits" in o:
                logits.append(o["logits"])   # device array; fetched below
            return o["tokens"].reshape(b).astype(jnp.int32)

        # shared chunked host loop (utils/host_loop.py): no per-token fetch
        first = jnp.asarray(tokens[0].reshape(b).astype(np.int32))
        gen = greedy_host_loop(step, first, max_new_tokens, eos_ids=eos_ids)
        res = {"sequences": np.concatenate([input_ids, gen], axis=1),
               "generated": gen}
        if logits:
            res["logits"] = [np.asarray(lg) for lg in logits]
        return res

    def reset(self):
        self.init_cache()
        return self


def params_cross(params):
    return params["cross_layers"]


# ---------------------------------------------------------------------------
# Vision tower (reference: models/mllama/modeling_mllama_vision.py +
# encoder_utils.py — tiled ViT with gated positional embeddings, local +
# gated-global encoders, intermediate-layer feature concat) and the
# aspect-ratio / image-transform host pipeline (reference:
# models/mllama/image_transform.py, aspect_ratio_utils.py).
# ---------------------------------------------------------------------------

from ...ops.normalization import layer_norm as _ln


def mllama_vision_spec(vc: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "image_size": int(vc["image_size"]),
        "patch_size": int(vc["patch_size"]),
        "hidden": int(vc["hidden_size"]),
        "heads": int(vc["attention_heads"]),
        "layers": int(vc["num_hidden_layers"]),
        "global_layers": int(vc["num_global_layers"]),
        "max_tiles": int(vc["max_num_tiles"]),
        "norm_eps": float(vc.get("norm_eps", 1e-5)),
        "intermediate_indices": tuple(
            int(i) for i in vc["intermediate_layers_indices"]),
        "act": vc.get("hidden_act", "gelu"),
    }


def _vision_mha(h, lw, nh, mask_add):
    b, n, dim = h.shape
    hd = dim // nh
    q = (h @ lw["q"]).reshape(b, n, nh, hd)
    k = (h @ lw["k"]).reshape(b, n, nh, hd)
    v = (h @ lw["v"]).reshape(b, n, nh, hd)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (hd ** -0.5)
    if mask_add is not None:
        s = s + mask_add
    p = jax.nn.softmax(s, axis=-1)
    a = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return (a.reshape(b, n, dim).astype(h.dtype)) @ lw["o"]


def _vision_layer(vs, h, lw, mask_add, gated):
    eps = vs["norm_eps"]
    # HF ACT2FN["gelu"] is the exact erf GELU
    act = (partial(jax.nn.gelu, approximate=False) if vs["act"] == "gelu"
           else partial(jax.nn.gelu, approximate=True))
    r = _ln(h, lw["ln1_w"], lw["ln1_b"], eps)
    a = _vision_mha(r, lw, vs["heads"], mask_add)
    if gated:
        a = jnp.tanh(lw["gate_attn"]) * a
    h = h + a
    r = _ln(h, lw["ln2_w"], lw["ln2_b"], eps)
    m = act((r @ lw["fc1"] + lw["fc1_b"]).astype(jnp.float32)).astype(h.dtype)
    m = m @ lw["fc2"] + lw["fc2_b"]
    if gated:
        m = jnp.tanh(lw["gate_ffn"]) * m
    return h + m


def mllama_vision_forward(vs: Dict[str, Any], params: Dict[str, Any],
                          pixel_values: jnp.ndarray,
                          aspect_ratio_ids: jnp.ndarray,
                          aspect_ratio_mask: jnp.ndarray) -> jnp.ndarray:
    """HF MllamaVisionModel.forward parity. pixel_values
    (B, M, T, C, H, W); aspect_ratio_ids (B, M); aspect_ratio_mask
    (B, M, T). Returns (B, M, T, P+1, hidden*(1+len(intermediate)))."""
    b, m, t, c, hh, ww = pixel_values.shape
    p = vs["patch_size"]
    dim = vs["hidden"]
    grid = hh // p
    npatch = grid * grid
    x = pixel_values.reshape(b * m * t, c, grid, p, grid, p)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(b * m * t, npatch, -1)
    x = x @ params["patch_proj"]                      # (BMT, P, dim)

    ar = aspect_ratio_ids.reshape(b * m)
    # pre-tile positional embedding (gated)
    pre = params["pre_tile_embed"][ar].reshape(b * m, vs["max_tiles"], 1, dim)
    x = x.reshape(b * m, t, npatch, dim) + jnp.tanh(params["pre_tile_gate"]) \
        * pre[:, :t]
    # cls token FIRST (HF cat([class, patches]))
    cls = jnp.broadcast_to(params["class_embedding"][None, None, None, :],
                           (b * m, t, 1, dim))
    x = jnp.concatenate([cls, x.reshape(b * m, t, npatch, dim)], axis=2)
    np1 = npatch + 1
    # gated positional embedding: (1-tanh(g))*pos + tanh(g)*tile_pos[ar]
    g = jnp.tanh(params["pos_gate"])
    x = x + (1.0 - g) * params["pos_embed"][None, None]
    tile_pos = params["tile_pos_embed"][ar].reshape(
        b * m, vs["max_tiles"], np1, dim)
    x = x + g * tile_pos[:, :t]
    x = _ln(x, params["ln_pre_w"], params["ln_pre_b"], 1e-5)

    # pad patches to a multiple of 8 (HF does; the zero-content pad rows ARE
    # attendable under HF's mask semantics, so parity requires the pad)
    pad = (8 - np1 % 8) % 8
    x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    L = np1 + pad
    # additive mask (HF _prepare_aspect_ratio_attention_mask): mark pad
    # TILES and pad PATCH rows, mask only pairs where both sides are pad
    mask = jnp.broadcast_to(
        aspect_ratio_mask.reshape(b * m, t, 1).astype(jnp.float32),
        (b * m, t, L))
    if pad:
        mask = mask.at[:, :, -pad:].set(0.0)
    inv = (1.0 - mask).reshape(b * m, t * L, 1)
    mask_add = (inv @ jnp.swapaxes(inv, 1, 2)) * jnp.finfo(jnp.float32).min
    mask_add = mask_add[:, None]                      # (BM, 1, TL, TL)

    h = x.reshape(b * m, t * L, dim)
    inter = []
    for i in range(vs["layers"]):
        if i in vs["intermediate_indices"]:
            inter.append(h)
        lw = jax.tree.map(lambda a, i=i: a[i], params["layers"])
        h = _vision_layer(vs, h, lw, mask_add, gated=False)
    if vs["layers"] in vs["intermediate_indices"]:
        inter.append(h)
    h = _ln(h, params["ln_post_w"], params["ln_post_b"], 1e-5)

    # global encoder with post-tile embedding
    post = params["post_tile_embed"][ar].reshape(
        b * m, vs["max_tiles"], 1, dim)
    h = h.reshape(b * m, t, L, dim) + jnp.tanh(params["post_tile_gate"]) \
        * post[:, :t]
    h = h.reshape(b * m, t * L, dim)
    for i in range(vs["global_layers"]):
        lw = jax.tree.map(lambda a, i=i: a[i], params["global_layers"])
        h = _vision_layer(vs, h, lw, mask_add, gated=True)

    h = h.reshape(b * m, t, L, dim)[:, :, :np1]
    inter = jnp.stack([y.reshape(b * m, t, L, dim)[:, :, :np1]
                       for y in inter], axis=-1)
    inter = inter.reshape(b * m, t, np1, -1)
    out = jnp.concatenate([h, inter], axis=-1)
    return out.reshape(b, m, t, np1, -1)


def convert_mllama_vision(sd: Dict[str, np.ndarray], vs: Dict[str, Any],
                          prefix: str = "vision_model") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def enc_layers(base, n, gated):
        def lw(i):
            b = f"{base}.layers.{i}"
            d = {
                "ln1_w": get(f"{b}.input_layernorm.weight"),
                "ln1_b": get(f"{b}.input_layernorm.bias"),
                "ln2_w": get(f"{b}.post_attention_layernorm.weight"),
                "ln2_b": get(f"{b}.post_attention_layernorm.bias"),
                "q": t(get(f"{b}.self_attn.q_proj.weight")),
                "k": t(get(f"{b}.self_attn.k_proj.weight")),
                "v": t(get(f"{b}.self_attn.v_proj.weight")),
                "o": t(get(f"{b}.self_attn.o_proj.weight")),
                "fc1": t(get(f"{b}.mlp.fc1.weight")),
                "fc1_b": get(f"{b}.mlp.fc1.bias"),
                "fc2": t(get(f"{b}.mlp.fc2.weight")),
                "fc2_b": get(f"{b}.mlp.fc2.bias"),
            }
            if gated:
                d["gate_attn"] = get(f"{b}.gate_attn").reshape(())
                d["gate_ffn"] = get(f"{b}.gate_ffn").reshape(())
            return d

        ls = [lw(i) for i in range(n)]
        return {k: np.stack([d[k] for d in ls]) for k in ls[0]}

    return {
        "patch_proj": t(get("patch_embedding.weight").reshape(
            vs["hidden"], -1)),
        "class_embedding": get("class_embedding"),
        "pos_embed": get("gated_positional_embedding.embedding"),
        "pos_gate": get("gated_positional_embedding.gate").reshape(()),
        "tile_pos_embed": get("gated_positional_embedding.tile_embedding.weight"),
        "pre_tile_embed": get("pre_tile_positional_embedding.embedding.weight"),
        "pre_tile_gate": get("pre_tile_positional_embedding.gate").reshape(()),
        "post_tile_embed": get("post_tile_positional_embedding.embedding.weight"),
        "post_tile_gate": get("post_tile_positional_embedding.gate").reshape(()),
        "ln_pre_w": get("layernorm_pre.weight"),
        "ln_pre_b": get("layernorm_pre.bias"),
        "ln_post_w": get("layernorm_post.weight"),
        "ln_post_b": get("layernorm_post.bias"),
        "layers": enc_layers("transformer", vs["layers"], False),
        "global_layers": enc_layers("global_transformer",
                                    vs["global_layers"], True),
    }


# ---------------------------------------------------------------------------
# Host-side aspect-ratio / image-transform pipeline (reference:
# models/mllama/aspect_ratio_utils.py + image_transform.py): choose a tile
# arrangement for an arbitrary image, resize + pad onto the tile canvas,
# split into tiles, and produce aspect_ratio_ids/mask for the tower.
# ---------------------------------------------------------------------------

def supported_aspect_ratios(max_num_tiles: int):
    """All (w, h) tile arrangements with w*h <= max_num_tiles, in HF
    processor order (width-major)."""
    out = []
    for w in range(1, max_num_tiles + 1):
        for h in range(1, max_num_tiles + 1):
            if w * h <= max_num_tiles:
                out.append((w, h))
    return out


def choose_canvas(img_h: int, img_w: int, tile_size: int,
                  max_num_tiles: int):
    """Pick the (w_tiles, h_tiles) canvas: smallest upscale that fits, else
    the largest-area downscale (HF get_optimal_tiled_canvas semantics)."""
    best_up = None
    best_down = None
    for (tw, th) in supported_aspect_ratios(max_num_tiles):
        cw, ch = tw * tile_size, th * tile_size
        scale = min(cw / img_w, ch / img_h)
        if scale >= 1:
            key = (scale, cw * ch)
            if best_up is None or key < best_up[0]:
                best_up = (key, (tw, th))
        else:
            # largest scale first, then SMALLEST canvas area (HF
            # get_optimal_tiled_canvas tie-break)
            key = (-scale, cw * ch)
            if best_down is None or key < best_down[0]:
                best_down = (key, (tw, th))
    return (best_up or best_down)[1]


def image_to_tiles(img: np.ndarray, tile_size: int, max_num_tiles: int):
    """img (C, H, W) float -> (tiles (T, C, tile, tile), aspect_ratio_id,
    num_tiles). Bilinear resize preserving aspect, zero-pad, split."""
    c, h, w = img.shape
    tw, th = choose_canvas(h, w, tile_size, max_num_tiles)
    cw, ch = tw * tile_size, th * tile_size
    scale = min(cw / w, ch / h)
    nh, nw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
    # bilinear resize via jax.image (host-side, tiny)
    resized = np.asarray(jax.image.resize(jnp.asarray(img, jnp.float32),
                                          (c, nh, nw), "bilinear"))
    canvas = np.zeros((c, ch, cw), np.float32)
    canvas[:, :nh, :nw] = resized
    tiles = canvas.reshape(c, th, tile_size, tw, tile_size)
    tiles = np.transpose(tiles, (1, 3, 0, 2, 4)).reshape(
        th * tw, c, tile_size, tile_size)
    ar_id = supported_aspect_ratios(max_num_tiles).index((tw, th)) + 1
    return tiles, ar_id, th * tw
