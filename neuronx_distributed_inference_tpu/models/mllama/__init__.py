from .modeling_mllama import (MllamaApplication, MllamaInferenceConfig,
                              MllamaTextFamily, build_mllama_plan,
                              compute_cross_kv)
