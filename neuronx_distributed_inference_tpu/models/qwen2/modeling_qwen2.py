"""Qwen2 family (reference: models/qwen2/modeling_qwen2.py
``NeuronQwen2ForCausalLM``). Llama-shaped with QKV projection biases."""

from __future__ import annotations

from typing import List, Optional

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class Qwen2InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]


@register_family("qwen2")
class Qwen2Family(DecoderFamily):
    config_cls = Qwen2InferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        # sliding window exists in the HF config but is disabled by default
        window = 0
        if getattr(config, "use_sliding_window", False):
            window = getattr(config, "sliding_window", None) or 0
        return spec_from_config(config, tp_degree, qkv_bias=True,
                                sliding_window=int(window))
