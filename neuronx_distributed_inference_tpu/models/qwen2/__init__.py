"""qwen2 family."""
from .modeling_qwen2 import *  # noqa: F401,F403
