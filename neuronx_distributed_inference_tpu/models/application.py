"""Application layer — compile/load/warmup lifecycle + host generation loop
(reference: models/application_base.py ``NeuronApplicationBase``,
models/model_wrapper.py ``ModelWrapper``, models/model_base.py
``NeuronBaseForCausalLM``:3024).

TPU redesign of the three reference classes into one:
  * compile()  -> ``jax.jit(...).lower().compile()`` per (submodel, bucket);
    the persistent XLA compilation cache replaces the NEFF artifact dir.
  * load()     -> checkpoint load + convert + device_put with shardings.
  * generate() -> host loop; the decode hot path runs ``decode_chunk_tokens``
    steps per device call via lax.scan (see model_base.decode_loop), which is
    the TPU replacement for async double-buffering
    (reference: modules/async_execution.py).
KV cache buffers are donated every call (reference I/O aliasing,
model_wrapper.py:1578-1627).
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import InferenceConfig, TpuConfig
from ..modules import autobucketing
from ..telemetry import get_registry
from ..telemetry import metrics as tmetrics
from ..telemetry import trace as trace_mod
from ..modules.kv_cache import KVCacheSpec, cache_pspec, init_cache
from ..ops.sampling import prepare_sampling_params
from ..parallel.mesh import AXIS_DP, AXIS_TP, MeshConfig, build_mesh, mesh_from_config
from ..utils import checkpoint as ckpt
from .family import DecoderFamily, family_for_config
from . import model_base

logger = logging.getLogger("nxdi_tpu")

# Submodel tags (reference: models/model_wrapper.py:37-42)
CONTEXT_ENCODING_MODEL_TAG = "context_encoding_model"
TOKEN_GENERATION_MODEL_TAG = "token_generation_model"
SPECULATION_MODEL_TAG = "speculation_model"
FUSED_SPECULATION_MODEL_TAG = "fused_speculation_model"


class CausalLMApplication:
    """Compile/load/run a causal LM on a TPU mesh."""

    def __init__(self, model_path: Optional[str], config: InferenceConfig,
                 family: Optional[Type[DecoderFamily]] = None,
                 mesh: Optional[Mesh] = None):
        self.model_path = model_path
        self.config = config
        self.tpu_config: TpuConfig = config.tpu_config
        self.family = family or family_for_config(config)
        self.mesh = mesh if mesh is not None else mesh_from_config(self.tpu_config)
        # heads/vocab/mlp shard over the COMBINED ("ep","tp") axes, so GQA
        # padding and vocab padding resolve against ep*tp (the reference's
        # full tp_degree; ep subdivides it, moe_v2.py:135-161)
        mp_degree = self.mesh.shape["tp"] * self.mesh.shape["ep"]
        self.spec = self.family.build_spec(config, tp_degree=mp_degree)
        self.params = None
        self.cache = None
        self._compiled: Dict[Tuple[str, int], Any] = {}
        # telemetry: None = follow the process-global registry (disabled by
        # default); assign app.telemetry = reg to pin one. _jit_seen tracks
        # (kind, bucket, shape) signatures for the recompile counter — it
        # never feeds the jit cache key itself.
        self._telemetry_override = None
        self._jit_seen: set = set()
        # cold-start discipline (serving/warmup.py): after precompile()
        # declares steady state, any first-seen signature is a tracked
        # incident. _trace_ctx carries the request trace ids of the
        # dispatch currently executing so the incident is attributed.
        self._steady_state = False
        self._steady_incidents: List[Dict[str, Any]] = []
        self._trace_ctx: Tuple[str, ...] = ()
        self._warmup_report: Optional[Dict[str, Any]] = None
        self._rng = jax.random.PRNGKey(self.tpu_config.seed)
        self.ctx_buckets = autobucketing.context_encoding_buckets(self.tpu_config)
        self.tkg_buckets = autobucketing.token_generation_buckets(self.tpu_config)
        # 2-D bucketing: allowed compiled batch sizes (reference: batch x
        # seq TKG buckets, autobucketing.py:203)
        self.batch_buckets = autobucketing.batch_buckets(self.tpu_config)
        # observability (reference: utils/snapshot.py env-driven capture;
        # utils/tensor_replacement/ golden injection)
        from ..utils.snapshot import SnapshotManager
        self.snapshot = SnapshotManager()
        self.replacements = None
        if self.tpu_config.tensor_replacement_config is not None:
            self.load_tensor_replacements()
        if self.tpu_config.compile_cache_dir:
            jax.config.update("jax_compilation_cache_dir",
                              self.tpu_config.compile_cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    # ------------------------------------------------------------------
    # weights
    # ------------------------------------------------------------------
    def load_weights(self, model_path: Optional[str] = None):
        """Load + convert + shard a HF checkpoint
        (reference: application_base.py:375-421 ``load_weights``)."""
        path = model_path or self.model_path
        sd = ckpt.load_state_dict(path)
        host = self.family.convert_hf_state_dict(sd, self.spec)
        self._put_params(host)
        return self

    def init_random_weights(self, seed: int = 0):
        """Synthetic weights (tiny-model tests / benches — reference:
        modules/checkpoint.py:202-287)."""
        if self.spec.quant is None and self.spec.low_rank is None:
            self.params = model_base.init_params(
                self.spec, jax.random.PRNGKey(seed), self.mesh)
        else:
            host = jax.device_get(model_base.init_params(
                self.spec, jax.random.PRNGKey(seed)))
            self._put_params(host)
        return self

    def _put_params(self, host: Dict[str, Any]):
        """Shard-on-load; factorize (SVD) and/or quantize first when the
        config asks for it (reference: application_base.py:746-799
        quantize-and-save path). Order matters: the SVD needs the fp
        weight, so low-rank factorization runs BEFORE quantization and
        quantizes its own factors (modules/low_rank.factorize_params)."""
        from ..modules import quantization as quant
        host = model_base.fuse_qkv_host(host)
        host = model_base.stack_lora_host(self.spec, host)
        fp_shardings = model_base.param_shardings(self.spec, self.mesh)
        if self.spec.quant is None and self.spec.low_rank is None:
            self.params = ckpt.device_put_params(host, fp_shardings,
                                                 dtype=self.spec.dtype)
            return
        host = jax.tree.map(
            lambda x: (np.asarray(x).astype(self.spec.dtype)
                       if np.issubdtype(np.asarray(x).dtype, np.floating)
                       else np.asarray(x)), host)
        if self.spec.low_rank is not None:
            from ..modules import low_rank as low_rank_mod
            host = low_rank_mod.factorize_params(
                host, self.spec.low_rank, quant=self.spec.quant)
        if self.spec.quant is not None:
            host = quant.quantize_params(host, self.spec.quant)
        shardings = quant.quantized_shardings(fp_shardings, host, self.mesh)
        self.params = ckpt.device_put_params(host, shardings, dtype=None)

    def save_quantized_state_dict(self, path: str):
        """Quantize the loaded/initialized weights and save them flat
        (reference: application_base.py:746-799
        ``save_quantized_state_dict``). Reload with
        :meth:`load_quantized_state_dict`."""
        if self.spec.quant is None:
            raise ValueError("config.tpu_config.quantized must be set")
        if self.params is None:
            raise RuntimeError("load_weights() first")
        host = jax.device_get(self.params)
        flat = _flatten_tree(host)
        ckpt.save_state_dict_safetensors(
            {k: np.asarray(v) for k, v in flat.items()}, path)
        self.config.save(path + os.sep)

    def load_quantized_state_dict(self, path: str):
        sd = ckpt.load_state_dict(path)
        host = _unflatten_tree(sd)
        from ..modules import quantization as quant
        fp_shardings = model_base.param_shardings(self.spec, self.mesh)
        shardings = quant.quantized_shardings(fp_shardings, host, self.mesh)
        self.params = ckpt.device_put_params(host, shardings, dtype=None)
        return self

    def save_converted_checkpoint(self, path: str):
        """Save the post-conversion param tree (fused qkv, padded heads,
        stacked layers) so reload skips HF conversion — the analog of the
        reference's pre-sharded per-rank checkpoints
        (application_base.py:389-399 save_sharded_checkpoint); triggered by
        ``save_sharded_checkpoint`` at compile()."""
        if self.params is None:
            raise RuntimeError("load_weights() first")
        host = jax.device_get(self.params)
        flat = _flatten_tree(host)
        ckpt.save_state_dict_safetensors(
            {k: np.asarray(v) for k, v in flat.items()},
            os.path.join(path, "converted"))
        self.config.save(path + os.sep)

    def load_converted_checkpoint(self, path: str):
        """Load a :meth:`save_converted_checkpoint` artifact (no HF
        conversion pass)."""
        sd = ckpt.load_state_dict(os.path.join(path, "converted"))
        host = _unflatten_tree(sd)
        shardings = model_base.param_shardings(self.spec, self.mesh)
        self.params = ckpt.device_put_params(host, shardings,
                                             dtype=self.spec.dtype)
        return self

    def init_cache(self):
        cfg = self.tpu_config
        spec = KVCacheSpec(
            # SSM-only layers carry no KV rows (recurrent/hybrid stacks)
            num_layers=self.spec.num_attn_layers,
            batch_size=cfg.kv_cache_batch_size,
            max_seq_len=cfg.seq_len,
            num_kv_heads=self.spec.gqa.num_kv_heads,
            head_dim=self.spec.head_dim,
            dtype=self.spec.kv_dtype,
            # rolling sliding-window cache: w slots instead of seq_len
            # (reference: kv_cache_manager.py:605-606)
            window=(self.spec.sliding_window if self.spec.rolling_window
                    else 0),
            v_head_dim=(self.spec.v_head_dim
                        if self.spec.v_head_dim != self.spec.head_dim else None),
        )
        if self.spec.mixed_kv:
            # per-layer cache sizes: local layers roll at W (reference:
            # gpt-oss per-layer KV, gpt_oss_kv_cache_manager.py)
            from ..modules.kv_cache import init_mixed_cache
            self.cache = init_mixed_cache(
                spec, self.spec.layer_pattern, self.spec.sliding_window,
                self.mesh)
        else:
            self.cache = init_cache(spec, self.mesh,
                                    flash_decoding=self.spec.flash_decoding)
        if self.spec.ssm is not None:
            # recurrent state pytree rides the same cache dict (reference
            # analog: the conv/ssm state tensors of
            # contrib Falcon-H1 FalconHybridMambaAttentionDynamicCache)
            from ..modules.ssm import init_ssm_state
            self.cache.update(init_ssm_state(
                self.spec.ssm, self.spec.num_ssm_layers,
                cfg.kv_cache_batch_size, self.spec.dtype, self.mesh))
        return self

    # ------------------------------------------------------------------
    # compile
    # ------------------------------------------------------------------
    def _io_shardings(self):
        repl = NamedSharding(self.mesh, P())
        cache_sh = NamedSharding(self.mesh, cache_pspec())
        return repl, cache_sh

    def _jit_prefill(self):
        fn = partial(model_base.context_encoding_step, self.spec, self.tpu_config)
        return jax.jit(fn, donate_argnums=(1,))

    def _jit_decode(self, kv_bucket: Optional[int] = None):
        fn = partial(model_base.token_generation_step, self.spec,
                     self.tpu_config, kv_view=kv_bucket)
        return jax.jit(fn, donate_argnums=(1,))

    def _jit_decode_loop(self, num_steps: int,
                         kv_bucket: Optional[int] = None):
        fn = partial(model_base.decode_loop, self.spec, self.tpu_config,
                     kv_view=kv_bucket)
        return jax.jit(fn, static_argnames=("num_steps",), donate_argnums=(1,))

    def _check_decode_fits(self, needed: int):
        """Decode writing KV slots up to ``needed - 1`` must stay inside
        the compiled seq_len — past it the scatter writes out of bounds
        (wrapping or dropping silently, depending on layout). Rolling
        caches store slot = pos % window, so they can never overflow."""
        if self.spec.rolling_window:
            return
        limit = self.tpu_config.seq_len
        if needed > limit:
            from ..resilience.errors import CapacityError
            raise CapacityError(
                f"decode would write KV at position {needed - 1} past the "
                f"compiled seq_len {limit}")

    def _kv_bucket(self, needed: int) -> Optional[int]:
        """Smallest TKG seq bucket covering ``needed`` cache slots — the
        decode graph compiled for bucket b reads cache[:b] only (reference:
        TKG seq buckets, autobucketing.py:226). None = full cache."""
        if self.spec.rolling_window:
            return None        # rolling cache: slot != position, no view cut
        buckets = self.tkg_buckets
        if len(buckets) <= 1:
            return None
        return autobucketing.get_target_bucket(buckets, needed, kind="tkg")

    def get_compiled(self, tag: str, bucket=0):
        key = (tag, bucket)
        if key not in self._compiled:
            if tag == CONTEXT_ENCODING_MODEL_TAG:
                self._compiled[key] = self._jit_prefill()
            elif tag == TOKEN_GENERATION_MODEL_TAG:
                self._compiled[key] = self._jit_decode(bucket or None)
            elif tag == "decode_loop":
                steps, kv_bucket = bucket if isinstance(bucket, tuple) \
                    else (bucket, None)
                self._compiled[key] = self._jit_decode_loop(steps, kv_bucket)
            elif tag == "windowed_cte":
                fn = partial(model_base.token_generation_multi, self.spec,
                             self.tpu_config)
                self._compiled[key] = jax.jit(fn, donate_argnums=(1,))
            else:
                raise KeyError(tag)
        return self._compiled[key]

    def compile(self, compiled_model_path: Optional[str] = None):
        """AOT warm the compilation cache for every (submodel, bucket)
        (reference: application_base.py:292-316 ``compile``). With the
        persistent XLA cache enabled this also serializes executables."""
        if compiled_model_path:
            os.makedirs(compiled_model_path, exist_ok=True)
            self.config.save(compiled_model_path + os.sep)
            if not self.tpu_config.compile_cache_dir:
                jax.config.update("jax_compilation_cache_dir", compiled_model_path)
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
            if self.tpu_config.save_sharded_checkpoint and \
                    self.params is not None:
                self.save_converted_checkpoint(compiled_model_path)
        self.warmup()
        return self

    def warmup(self):
        """Run every bucket once (reference: application_base.py:349-373)."""
        if self.params is None:
            self.init_random_weights()
        if self.cache is None:
            self.init_cache()
        cfg = self.tpu_config
        b = cfg.ctx_batch_size
        for s in self.ctx_buckets:
            self._run_prefill(np.zeros((b, s), np.int32),
                              np.zeros((b,), np.int32) + 1)
        chunk = max(cfg.decode_chunk_tokens, 1)
        # compile every TKG seq bucket (reference: warmup runs every bucket
        # of every submodel, application_base.py:349-373)
        starts = [1] if len(self.tkg_buckets) <= 1 else [
            max(b - chunk, 1) for b in self.tkg_buckets]
        warm_batches = sorted(set(self.batch_buckets)
                              | {cfg.tkg_batch_size or cfg.batch_size})
        for start in starts:
            for bb in warm_batches:           # 2-D: every batch bucket
                if chunk > 1:
                    self._run_decode_loop(np.zeros((bb,), np.int32),
                                          np.full((bb,), start, np.int32),
                                          chunk)
                # the chunk tail of generate() uses the single-step graph —
                # warm it per bucket too, or the first request reaching a
                # new bucket stalls on a mid-request compile
                self._run_decode(np.zeros((bb, 1), np.int32),
                                 np.full((bb, 1), start, np.int32))
        # 2-D batch buckets: warm each short-batch prefill at the smallest
        # ctx bucket (the remaining grid compiles lazily; the decode loop —
        # the stall that matters mid-request — is warmed above)
        for bb in self.batch_buckets:
            if bb != b:
                self._run_prefill(np.zeros((bb, self.ctx_buckets[0]),
                                           np.int32),
                                  np.ones((bb,), np.int32))
        return self

    # ------------------------------------------------------------------
    # execution helpers
    # ------------------------------------------------------------------
    def _mesh_ctx(self):
        """Execute compiled fns inside the mesh context: bare-PartitionSpec
        sharding constraints in model code resolve against it, and
        ops/decode_attention.dispatch reads it to shard_map the Pallas
        kernel over the dp/mp axes (outside a mesh context both silently
        degrade to GSPMD-propagated-only sharding)."""
        return jax.sharding.set_mesh(self.mesh)

    # -- telemetry (host-boundary only; all no-ops while disabled) ---------
    @property
    def telemetry(self):
        return (self._telemetry_override
                if self._telemetry_override is not None else get_registry())

    @telemetry.setter
    def telemetry(self, reg):
        self._telemetry_override = reg

    def _tel_start(self):
        """perf_counter() when telemetry OR the flight recorder is live,
        else None (the sentinel keeps the disabled path free of timing
        work AND of the device sync in :meth:`_tel_end`)."""
        if self.telemetry.enabled or trace_mod.get_recorder().enabled:
            return time.perf_counter()
        return None

    def _tel_end(self, kind: str, t0, out, n_rows: int):
        """Observe one _run_* call: host-prep (entry → dispatch return) vs
        device wait (block_until_ready). Runs strictly OUTSIDE traced code;
        the sync only happens when telemetry is enabled. The flight
        recorder gets a ``run.<kind>`` slice covering the HOST window only
        (entry → dispatch return) — recording never adds a device sync."""
        if t0 is None:
            return
        t1 = time.perf_counter()
        rec = trace_mod.get_recorder()
        if rec.enabled:
            rec.complete(f"run.{kind}", t0, cat="app", t1=t1, rows=n_rows)
        tel = self.telemetry
        if not tel.enabled:
            return
        jax.block_until_ready(out["tokens"])
        t2 = time.perf_counter()
        hist = tmetrics.run_seconds_histogram(tel)
        hist.observe(t1 - t0, kind=kind, part="host")
        hist.observe(t2 - t1, kind=kind, part="device")
        tmetrics.device_sampled_rows_counter(tel).inc(n_rows, kind=kind)

    def _note_jit(self, kind: str, bucket, sig):
        """Recompile accounting: the first time a (kind, bucket, shape)
        signature runs it is a graph build (trace + XLA compile, or a
        persistent-cache load); afterwards it is a cache hit. The single
        most useful "why is serving slow" signal. Signatures are tracked
        even while telemetry is disabled (one set-add, no syncs) so that
        enabling the registry after warmup does not misreport every warm
        graph as a fresh compile. First-time signatures also land on the
        flight recorder as ``compile`` instants, so a trace timeline shows
        WHERE mid-serving compile stalls interleave with dispatches."""
        key = (kind, bucket, sig)
        seen = key in self._jit_seen
        if not seen:
            self._jit_seen.add(key)
            rec = trace_mod.get_recorder()
            if rec.enabled:
                rec.instant("compile", cat="app", kind=kind,
                            bucket=str(bucket), sig=str(sig))
            if self._steady_state:
                self._note_steady_recompile(kind, bucket, sig, rec)
        tel = self.telemetry
        if not tel.enabled:
            return
        if seen:
            tmetrics.jit_cache_hits_counter(tel).inc(kind=kind)
        else:
            tmetrics.jit_compiles_counter(tel).inc(kind=kind,
                                                   bucket=str(bucket))

    # -- steady-state compile discipline (serving/warmup.py) ---------------
    _MAX_STEADY_INCIDENTS = 256

    def _note_steady_recompile(self, kind: str, bucket, sig, rec) -> None:
        """A first-seen signature AFTER precompile() declared steady state:
        a tracked incident — counter, ``compile.unexpected`` flight-
        recorder event, and attribution onto the request traces packed
        into the triggering dispatch (``request_context``)."""
        traces = [t for t in self._trace_ctx if t]
        incident = {"kind": kind, "bucket": str(bucket), "sig": str(sig),
                    "traces": traces}
        self._steady_incidents.append(incident)
        if len(self._steady_incidents) > self._MAX_STEADY_INCIDENTS:
            del self._steady_incidents[0]
        if rec.enabled:
            rec.instant("compile.unexpected", cat="app", kind=kind,
                        bucket=str(bucket), sig=str(sig), traces=traces)
        tel = self.telemetry
        if tel.enabled:
            tmetrics.steady_state_recompiles_counter(tel).inc(
                kind=kind, bucket=str(bucket))

    def declare_steady_state(self, on: bool = True):
        """Flip the steady-state flag: ``precompile()`` (serving/warmup.py)
        declares it after walking the serving graph ladder; from then on
        every first-seen jit signature is a tracked incident."""
        self._steady_state = bool(on)
        return self

    def request_context(self, traces):
        """Context manager attributing any compile observed inside the
        body to ``traces`` (request trace ids of the dispatch being
        issued). Adapters wrap their ``_run_*`` calls in steady state."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prev = self._trace_ctx
            self._trace_ctx = tuple(traces)
            try:
                yield
            finally:
                self._trace_ctx = prev
        return _ctx()

    def warmup_state(self) -> Dict[str, Any]:
        """JSON-able cold-start account: the precompile report summary,
        the steady-state flag, and every tracked recompile incident —
        served as ``/v1/debug/state["warmup"]``."""
        out: Dict[str, Any] = {
            "steady_state": self._steady_state,
            "graphs_seen": len(self._jit_seen),
            "incidents": list(self._steady_incidents),
        }
        if self._warmup_report is not None:
            out["precompile"] = {
                k: self._warmup_report[k]
                for k in ("n_graphs", "n_compiles", "n_cache_loads",
                          "n_warm_hits", "total_seconds")
                if k in self._warmup_report}
        return out

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    def _default_sampling_params(self, batch: int):
        sc = self.tpu_config.on_device_sampling_config
        if sc is None:
            return None
        return jnp.asarray(prepare_sampling_params(
            batch, sc.top_k, sc.top_p, sc.temperature))

    def _run_prefill(self, input_ids: np.ndarray, seq_lens: np.ndarray,
                     seq_ids: Optional[np.ndarray] = None,
                     sampling_params=None, adapter_ids=None,
                     image_embeds=None, image_mask=None,
                     rope_position_ids=None, deepstack_embeds=None):
        b, s = input_ids.shape
        if seq_ids is None:
            seq_ids = np.arange(b, dtype=np.int32)
        elif (not self.tpu_config.is_continuous_batching
              and not np.array_equal(np.asarray(seq_ids), np.arange(b))):
            # the prefill graph takes the identity fast-path write under
            # this static config (kv_cache.write_prefill_at_layer), which
            # would silently ignore a row permutation — reject at the
            # boundary like _run_decode does
            raise ValueError("non-identity seq_ids require "
                             "is_continuous_batching=True")
        t0 = self._tel_start()
        position_ids = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s))
        fn = self.get_compiled(CONTEXT_ENCODING_MODEL_TAG, s)
        self._note_jit("prefill", s, (b, s))
        if sampling_params is None:
            sampling_params = self._default_sampling_params(b)
        if self.snapshot.enabled:
            self.snapshot.save_step({"input_ids": input_ids,
                                     "position_ids": position_ids,
                                     "seq_ids": seq_ids,
                                     "seq_lens": seq_lens},
                                    weights=self.params)
        if image_mask is not None:
            image_mask = jnp.asarray(np.asarray(image_mask, bool))
        if rope_position_ids is not None:
            rope_position_ids = jnp.asarray(rope_position_ids)
        with self._mesh_ctx():
            out = fn(self.params, self.cache, jnp.asarray(input_ids),
                     jnp.asarray(position_ids), jnp.asarray(seq_ids),
                     jnp.asarray(seq_lens), sampling_params, self._next_rng(),
                     adapter_ids, self.replacements, image_embeds, image_mask,
                     rope_position_ids, deepstack_embeds)
        self.cache = out["cache"]
        self._tel_end("prefill", t0, out, b)
        return out

    def _run_prefill_windowed(self, input_ids: np.ndarray,
                              seq_lens: np.ndarray, window: int,
                              sampling_params=None):
        """Windowed context encoding (reference: models/model_base.py:878-933
        + long-context mode, models/config.py:612-621): walk the prompt in
        fixed windows re-invoking ONE decode-phase multi-token graph with
        growing KV — the (S, S) one-shot prefill attention materialization
        becomes (W, S), which is what makes >=32k contexts feasible.
        Returns {"tokens", "cache"} like _run_prefill."""
        b, s = input_ids.shape
        if self.spec.rolling_window or self.spec.mixed_kv:
            # the windowed-CTE graph addresses cache slot == position; a
            # rolling cache stores slot = pos % W - silently wrong reads
            raise NotImplementedError(
                "windowed_context_encoding is incompatible with rolling / "
                "mixed per-layer KV caches (slot != position)")
        seq_ids = jnp.arange(b, dtype=jnp.int32)
        fn = self.get_compiled("windowed_cte", window)
        if sampling_params is None:
            sampling_params = self._default_sampling_params(b)
        vocab = self.spec.vocab_size
        last_logits = jnp.zeros((b, vocab), jnp.float32)
        lens_d = jnp.asarray(seq_lens.astype(np.int32))
        with self._mesh_ctx():
            for off in range(0, s, window):
                ids_w = jnp.asarray(input_ids[:, off:off + window])
                pos_w = off + jnp.arange(window, dtype=jnp.int32)[None, :]
                pos_w = jnp.broadcast_to(pos_w, (b, window))
                # padded rows past seq_len: positions pushed out of range so
                # their cache writes drop
                pos_w = jnp.where(pos_w < lens_d[:, None], pos_w,
                                  self.tpu_config.seq_len)
                out = fn(self.params, self.cache, ids_w, pos_w, seq_ids)
                self.cache = out["cache"]
                # keep each row's logits at its LAST real position
                idx = jnp.clip(lens_d - 1 - off, 0, window - 1)
                lg = jnp.take_along_axis(
                    out["logits_all"], idx[:, None, None], axis=1)[:, 0]
                hit = (lens_d - 1 >= off) & (lens_d - 1 < off + window)
                last_logits = jnp.where(hit[:, None],
                                        lg.astype(jnp.float32), last_logits)
            tokens = self._sample_logits(last_logits, sampling_params)
        return {"tokens": tokens, "cache": self.cache}

    def _sample_logits(self, logits, sampling_params):
        if "sample_last" not in self._compiled:
            from ..ops import sampling as sampling_ops
            cfg = self.tpu_config

            def fn(lg, sp, rng):
                return sampling_ops.sample_dp(
                    lg, cfg.on_device_sampling_config, sp, rng)
            self._compiled["sample_last"] = jax.jit(fn)
        return self._compiled["sample_last"](logits, sampling_params,
                                             self._next_rng())

    def _run_decode(self, input_ids: np.ndarray, position_ids: np.ndarray,
                    seq_ids: Optional[np.ndarray] = None, sampling_params=None,
                    adapter_ids=None, rope_position_ids=None):
        b = input_ids.shape[0]
        if seq_ids is None:
            seq_ids = np.arange(b, dtype=np.int32)
        elif (not self.tpu_config.is_continuous_batching
              and not np.array_equal(np.asarray(seq_ids), np.arange(b))):
            # the decode graph skips the cache row-gather under this static
            # config (model_base._layer_body), so non-identity seq_ids would
            # silently read the wrong rows — reject at the boundary
            raise ValueError("non-identity seq_ids require "
                             "is_continuous_batching=True")
        t0 = self._tel_start()
        needed = int(np.max(np.asarray(position_ids))) + input_ids.shape[1]
        self._check_decode_fits(needed)
        kv_bucket = self._kv_bucket(needed) or 0
        fn = self.get_compiled(TOKEN_GENERATION_MODEL_TAG, kv_bucket)
        self._note_jit("decode", kv_bucket, input_ids.shape)
        if sampling_params is None:
            sampling_params = self._default_sampling_params(b)
        if self.snapshot.enabled:
            self.snapshot.save_step({"input_ids": input_ids,
                                     "position_ids": position_ids,
                                     "seq_ids": seq_ids})
        if rope_position_ids is not None:
            rope_position_ids = jnp.asarray(rope_position_ids)
        with self._mesh_ctx():
            out = fn(self.params, self.cache, jnp.asarray(input_ids),
                     jnp.asarray(position_ids), jnp.asarray(seq_ids),
                     sampling_params, self._next_rng(), adapter_ids,
                     self.replacements, rope_position_ids)
        self.cache = out["cache"]
        self._tel_end("decode", t0, out, b * input_ids.shape[1])
        return out

    def _run_decode_loop(self, first_tokens: np.ndarray, positions: np.ndarray,
                         num_steps: int, seq_ids: Optional[np.ndarray] = None,
                         sampling_params=None, adapter_ids=None,
                         rope_position_ids=None):
        b = first_tokens.shape[0]
        if seq_ids is None:
            seq_ids = np.arange(b, dtype=np.int32)
        elif (not self.tpu_config.is_continuous_batching
              and not np.array_equal(np.asarray(seq_ids), np.arange(b))):
            # same boundary guard as _run_decode: without continuous
            # batching the scanned decode graph skips the cache row-gather,
            # so non-identity seq_ids would silently read the wrong rows
            raise ValueError("non-identity seq_ids require "
                             "is_continuous_batching=True")
        t0 = self._tel_start()
        needed = int(np.max(np.asarray(positions))) + num_steps
        self._check_decode_fits(needed)
        loop_bucket = (num_steps, self._kv_bucket(needed))
        fn = self.get_compiled("decode_loop", loop_bucket)
        self._note_jit("decode_loop", loop_bucket, first_tokens.shape)
        if sampling_params is None:
            sampling_params = self._default_sampling_params(b)
        if rope_position_ids is not None:
            rope_position_ids = jnp.asarray(rope_position_ids)
        with self._mesh_ctx():
            out = fn(self.params, self.cache, jnp.asarray(first_tokens),
                     jnp.asarray(positions), jnp.asarray(seq_ids),
                     sampling_params, self._next_rng(), num_steps=num_steps,
                     adapter_ids=adapter_ids,
                     rope_position_ids=rope_position_ids)
        self.cache = out["cache"]
        self._tel_end("decode_loop", t0, out, b * num_steps)
        return out

    # ------------------------------------------------------------------
    # generation (reference: utils/hf_adapter.py _sample loop :139-258 +
    # NeuronBaseForCausalLM._get_model_outputs routing :3549-3735)
    # ------------------------------------------------------------------
    def _generate_repadded(self, input_ids: np.ndarray, **kw
                           ) -> Dict[str, Any]:
        """Batch-mismatch host shim (reference: model_wrapper.py
        ``_forward_with_pad`` :574-703 + sub-batching :1315-1440).

        b < batch bucket: pad every batchful input by REPEATING ROW 0 —
        pad rows recompute row 0's data and rewrite its cache rows with
        identical values, so they are harmless (the reference repeats the
        first batchline for exactly this reason); outputs are sliced back.
        b > max compiled batch: split into compiled-batch sub-batches run
        sequentially and re-concatenated. No seq_ids sort is needed: the
        decode graph addresses cache rows BY seq_id (gather), so request
        order is free."""
        b_in = input_ids.shape[0]
        cfg = self.tpu_config
        # explicit per-row kwargs — a shape heuristic would misclassify e.g.
        # a multi-valued eos_token_id list whose length happens to equal b
        per_row = ("attention_mask", "sampling_params", "teacher_tokens",
                   "adapter_ids", "image_mask", "rope_position_ids",
                   "decode_rope_start", "image_embeds")

        def _batchful(k, x):
            return k in per_row and x is not None

        if b_in > cfg.batch_size:
            # sub-batching: compiled-batch chunks (last padded recursively)
            outs = []
            for lo in range(0, b_in, cfg.batch_size):
                hi = min(lo + cfg.batch_size, b_in)
                sub = {k: (np.asarray(v)[lo:hi] if _batchful(k, v) else v)
                       for k, v in kw.items()}
                # deepstack stacks batch on axis 1
                if kw.get("deepstack_embeds") is not None:
                    sub["deepstack_embeds"] = \
                        np.asarray(kw["deepstack_embeds"])[:, lo:hi]
                outs.append(self.generate(input_ids[lo:hi], **sub))

            def _cat(key):
                # chunks may stop at different EOS points: right-pad each
                # chunk to the widest before concatenating (0 = the
                # post-EOS fill convention)
                arrs = [np.asarray(o[key]) for o in outs]
                w = max(a.shape[1] for a in arrs)
                return np.concatenate(
                    [np.pad(a, ((0, 0), (0, w - a.shape[1])))
                     for a in arrs])

            merged = {"sequences": _cat("sequences"),
                      "generated": _cat("generated")}
            if "seq_lens" in outs[0]:
                merged["seq_lens"] = np.concatenate(
                    [np.asarray(o["seq_lens"]) for o in outs])
            for extra in ("ttft_s",):
                if extra in outs[0]:
                    merged[extra] = outs[0][extra]
            if kw.get("return_logits") and "logits" in outs[0]:
                # keep the per-step list contract: step i concatenates all
                # chunks' step-i logits; chunks that stopped early repeat
                # their final step
                n_steps = max(len(o["logits"]) for o in outs)
                merged["logits"] = [
                    np.concatenate([np.asarray(
                        o["logits"][min(si, len(o["logits"]) - 1)])
                        for o in outs], axis=0)
                    for si in range(n_steps)]
            return merged

        pad = autobucketing.get_target_bucket(self.batch_buckets,
                                              b_in, kind="batch") - b_in

        def _pad0(k, x):
            if not _batchful(k, x):
                return x
            a = np.asarray(x)
            return np.concatenate([a, np.repeat(a[:1], pad, axis=0)])

        kw2 = {k: _pad0(k, v) for k, v in kw.items()}
        if kw.get("deepstack_embeds") is not None:
            ds = np.asarray(kw["deepstack_embeds"])
            kw2["deepstack_embeds"] = np.concatenate(
                [ds, np.repeat(ds[:, :1], pad, axis=1)], axis=1)
        padded_ids = np.concatenate(
            [input_ids, np.repeat(input_ids[:1], pad, axis=0)])
        out = self.generate(padded_ids, **kw2)
        res = dict(out)
        res["sequences"] = out["sequences"][:b_in]
        res["generated"] = out["generated"][:b_in]
        if "seq_lens" in out:
            res["seq_lens"] = np.asarray(out["seq_lens"])[:b_in]
        if "logits" in out:
            res["logits"] = [np.asarray(lg)[:b_in] for lg in out["logits"]]
        return res

    def generate(self, input_ids: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None,
                 sampling_params: Optional[np.ndarray] = None,
                 return_logits: bool = False,
                 teacher_tokens: Optional[np.ndarray] = None,
                 adapter_ids: Optional[np.ndarray] = None,
                 image_embeds=None,
                 image_mask: Optional[np.ndarray] = None,
                 deepstack_embeds=None,
                 rope_position_ids: Optional[np.ndarray] = None,
                 decode_rope_start: Optional[np.ndarray] = None
                 ) -> Dict[str, Any]:
        """Greedy/sampled generation. input_ids (B, S) right-padded;
        attention_mask (B, S) marks real tokens. Returns sequences including
        the prompt (HF convention).

        teacher_tokens (B, T): teacher-forcing for logit-matching accuracy —
        feed these instead of the sampled tokens (reference:
        utils/accuracy.py logit flow re-feeds golden tokens).
        adapter_ids (B,): per-request LoRA adapter slot (multi-LoRA serving,
        reference: modules/lora_serving/).
        rope_position_ids (B, S, 3) + decode_rope_start (B, 3): M-RoPE
        3-axis positions for the prompt and the first generated token
        (qwen2-VL; reference: rotary_position_ids plumbing,
        models/model_base.py:566-578). Decode advances all axes by 1/token."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if b not in self.batch_buckets:
            # serving host shim (reference: model_wrapper.py:520-703
            # repeat-first-batchline pad + :1315-1440 sub-batching): pad a
            # short batch to the smallest BATCH bucket by repeating row 0
            # (2-D bucketing: the ladder may hold sizes below the full
            # compiled batch), or split an oversized batch into
            # compiled-batch chunks
            return self._generate_repadded(
                input_ids, attention_mask=attention_mask,
                max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
                sampling_params=sampling_params, return_logits=return_logits,
                teacher_tokens=teacher_tokens, adapter_ids=adapter_ids,
                image_embeds=image_embeds, image_mask=image_mask,
                deepstack_embeds=deepstack_embeds,
                rope_position_ids=rope_position_ids,
                decode_rope_start=decode_rope_start)
        if adapter_ids is not None:
            adapter_ids = jnp.asarray(np.asarray(adapter_ids, np.int32))
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        if self.cache is None:
            self.init_cache()
        if self.params is None:
            raise RuntimeError("load_weights() or init_random_weights() first")
        if sampling_params is not None:
            sampling_params = jnp.asarray(sampling_params)
        if self.snapshot.enabled:
            self.snapshot.on_request()

        if teacher_tokens is not None:
            # teacher forcing can feed at most T tokens, producing T+1 steps
            max_new_tokens = min(max_new_tokens,
                                 np.asarray(teacher_tokens).shape[1] + 1)
        wcte = self.tpu_config.windowed_context_encoding
        if wcte and s > wcte:
            # windowed CTE pads to a window multiple instead of a ctx bucket
            bucket = -(-s // wcte) * wcte
        else:
            wcte = None
            bucket = autobucketing.get_target_bucket(self.ctx_buckets, s,
                                                     kind="ctx")
        padded = np.zeros((b, bucket), input_ids.dtype)
        padded[:, :s] = input_ids
        padded_img_mask = None
        if image_mask is not None:
            padded_img_mask = np.zeros((b, bucket), bool)
            padded_img_mask[:, :s] = np.asarray(image_mask, bool)
        padded_rope = None
        if rope_position_ids is not None:
            padded_rope = np.zeros((b, bucket, 3), np.int32)
            padded_rope[:, :s] = np.asarray(rope_position_ids, np.int32)
        max_total = int(seq_lens.max()) + max_new_tokens
        if max_total > self.tpu_config.seq_len:
            max_new_tokens = self.tpu_config.seq_len - int(seq_lens.max())
            if max_new_tokens <= 0:
                raise ValueError("prompt exceeds seq_len")

        t0 = time.perf_counter()
        if wcte:
            if (image_embeds is not None or adapter_ids is not None
                    or rope_position_ids is not None or return_logits):
                raise NotImplementedError(
                    "windowed context encoding supports plain text prompts "
                    "without logits output")
            out = self._run_prefill_windowed(padded, seq_lens, wcte,
                                             sampling_params=sampling_params)
        else:
            out = self._run_prefill(padded, seq_lens,
                                    sampling_params=sampling_params,
                                    adapter_ids=adapter_ids,
                                    image_embeds=image_embeds,
                                    deepstack_embeds=deepstack_embeds,
                                    image_mask=padded_img_mask,
                                    rope_position_ids=padded_rope)
        first = out["tokens"]                     # device array (B,)
        try:
            first.copy_to_host_async()
        except AttributeError:
            pass
        logits_trace = [np.asarray(out["logits"])] if return_logits and "logits" in out else []

        # eos_token_id: int or list of ints (HF allows multiple stop ids)
        eos_ids = (None if eos_token_id is None
                   else np.atleast_1d(np.asarray(eos_token_id, dtype=np.int64)))
        # tokens stay ON DEVICE through the loop — a device→host fetch costs a
        # full tunnel round trip (~tens of ms on remoted TPUs), so EOS checks
        # run one chunk late on an overlapped async copy instead of a
        # synchronous fetch per step (reference async_execution.py hides the
        # same latency with double-buffering).
        collected = [first[:, None]]
        pending = first[:, None]                  # device tokens not yet eos-checked
        ttft = None
        positions = seq_lens.astype(np.int32)  # position of the token just sampled
        rpos = (np.asarray(decode_rope_start, np.int32)
                if decode_rope_start is not None else None)
        n_generated = 1
        eos_seen = np.zeros((b,), bool) if eos_ids is not None else None
        chunk = max(self.tpu_config.decode_chunk_tokens, 1)
        while n_generated < max_new_tokens:
            remaining = max_new_tokens - n_generated
            # only the full-chunk loop graph is warmed; a partial remainder
            # would trigger a fresh XLA compile mid-request, so finish it with
            # the (already-compiled) single-step graph instead
            n = chunk if remaining >= chunk else 1
            cur = collected[-1][:, -1]
            if teacher_tokens is not None:
                cur = np.asarray(teacher_tokens[:, n_generated - 1],
                                 dtype=np.int32)
                n = 1
            if n == 1 or return_logits:
                o = self._run_decode(
                    cur[:, None], positions[:, None],
                    sampling_params=sampling_params, adapter_ids=adapter_ids,
                    rope_position_ids=(rpos[:, None, :] if rpos is not None
                                       else None))
                new = o["tokens"].reshape(b, 1)
                if return_logits and "logits" in o:
                    logits_trace.append(np.asarray(o["logits"]))
                positions = positions + 1
                if rpos is not None:
                    rpos = rpos + 1
                n_generated += 1
            else:
                o = self._run_decode_loop(cur, positions, n,
                                          sampling_params=sampling_params,
                                          adapter_ids=adapter_ids,
                                          rope_position_ids=rpos)
                new = o["tokens"]
                positions = positions + n
                if rpos is not None:
                    rpos = rpos + n
                n_generated += n
            try:
                new.copy_to_host_async()
            except AttributeError:
                pass
            collected.append(new)
            if ttft is None:
                # first token reached the host while the next chunk computes
                np.asarray(first)
                ttft = time.perf_counter() - t0
            if eos_seen is not None:
                eos_seen |= np.isin(np.asarray(pending), eos_ids).any(axis=1)
                pending = new
                if eos_seen.all():
                    break

        if ttft is None:
            np.asarray(first)
            ttft = time.perf_counter() - t0
        collected = [np.asarray(c) for c in collected]
        result = _finalize_generation(input_ids, collected, eos_ids, ttft,
                                      seq_lens)
        if return_logits:
            result["logits"] = logits_trace
        return result

    def reset(self):
        """Clear KV cache between requests."""
        self.init_cache()
        return self

    # ------------------------------------------------------------------
    # observability (reference: SURVEY §5)
    # ------------------------------------------------------------------
    def load_tensor_replacements(self, source_path: Optional[str] = None):
        """Build the golden-injection arrays from the configured .npz
        (reference: utils/tensor_replacement/registry.py + wiring
        model_wrapper.py:481-518). The npz holds one (L,B,T,H) array per
        target point; ``layers`` restricts which layer indices replace."""
        trc = self.tpu_config.tensor_replacement_config
        path = source_path or (trc.source_path if trc else None)
        if path is None:
            raise ValueError("tensor_replacement_config.source_path required")
        data = np.load(path)
        L = self.spec.num_layers
        layer_on = np.zeros((L,), bool)
        if trc and trc.layers is not None:
            layer_on[np.asarray(trc.layers, int)] = True
        else:
            layer_on[:] = True
        rep: Dict[str, Any] = {}
        targets = (trc.targets if trc and trc.targets else list(data.files))
        for name in targets:
            arr = np.asarray(data[name])
            if arr.shape[0] != L:
                raise ValueError(f"replacement {name!r} leading dim "
                                 f"{arr.shape[0]} != num_layers {L}")
            rep[name] = jnp.asarray(arr)
            rep[name + "_on"] = jnp.asarray(layer_on)
        self.replacements = rep
        return self

    # ------------------------------------------------------------------
    # multi-LoRA serving (reference: modules/lora_serving/)
    # ------------------------------------------------------------------
    def load_lora_adapters(self, ckpt_paths: Optional[Dict[str, str]] = None):
        """Load PEFT adapter checkpoints into slots 1..N (slot 0 stays the
        zero adapter = base model). ckpt_paths {name: dir}; defaults to
        tpu_config.lora_config.lora_ckpt_paths. Returns {name: slot}."""
        from ..modules import lora as lora_mod
        lc = self.tpu_config.lora_config
        if self.spec.lora is None or lc is None:
            raise ValueError("lora_config must be set on the TpuConfig")
        ckpt_paths = ckpt_paths or lc.lora_ckpt_paths or {}
        if self.params is None:
            raise RuntimeError("load_weights() first")
        slots: Dict[str, int] = {}
        for slot, (name, path) in enumerate(ckpt_paths.items(), start=1):
            if slot >= self.spec.lora.max_loras:
                raise ValueError(f"adapter {name!r}: slot {slot} exceeds "
                                 f"max_loras {self.spec.lora.max_loras}")
            self.set_lora_adapter(slot, path)
            slots[name] = slot
        self.lora_slots = slots
        return slots

    def lora_adapter_arrays(self, path: str) -> Dict[str, Any]:
        """Load + shard-transform one PEFT adapter dir into the host-side
        stacked layout: ``{module: (A (L,in,r), B (L,r,out))}`` — the
        same GQA head pad/replicate transforms the base weights get, so
        the arrays are slot-writable as-is (:meth:`write_lora_slot`).
        This is the pure LOAD half of the old ``set_lora_adapter``; the
        serving adapter pool (serving/lora_pool.py) caches these arrays
        host-side for spill/restore without re-reading the checkpoint."""
        from ..modules import lora as lora_mod
        from ..parallel.layers import place_q_weight, replicate_kv_weight
        sd, acfg = lora_mod.load_peft_adapter(path)
        lo = self.spec.lora
        g = self.spec.gqa
        D = self.spec.head_dim
        dims = {
            "q_proj": (self.spec.hidden_size, self.spec.q_size),
            "k_proj": (self.spec.hidden_size, self.spec.kv_size),
            "v_proj": (self.spec.hidden_size, self.spec.kv_size),
            "o_proj": (self.spec.q_size, self.spec.hidden_size),
            "gate_proj": (self.spec.hidden_size, self.spec.intermediate_size),
            "up_proj": (self.spec.hidden_size, self.spec.intermediate_size),
            "down_proj": (self.spec.intermediate_size, self.spec.hidden_size),
        }
        transforms = {
            "q_proj": lambda b: place_q_weight(b, g, D, -1),
            "k_proj": lambda b: replicate_kv_weight(b, g, D, -1),
            "v_proj": lambda b: replicate_kv_weight(b, g, D, -1),
        }
        arrays: Dict[str, Any] = {}
        for mod in lo.target_modules:
            d_in, d_out = dims[mod]
            # o_proj's A consumes the padded head layout on its input side
            in_transform = (lambda a: place_q_weight(a, g, D, 0)) \
                if mod == "o_proj" else None
            arrays[mod] = lora_mod.adapter_layer_arrays(
                sd, acfg, self.spec.num_layers, mod, d_in, d_out, lo.rank,
                out_transform=transforms.get(mod), in_transform=in_transform)
        return arrays

    def write_lora_slot(self, slot: int, arrays: Dict[str, Any]):
        """Write pre-transformed adapter ``arrays`` ({module: (A, B)},
        :meth:`lora_adapter_arrays` layout) into ``slot`` of the stacked
        device params — the pure WRITE half of adapter loading, so a
        caller can make the swap transactional by snapshotting the
        touched leaves first (serving/lora_pool.py does)."""
        from ..modules import lora as lora_mod
        for mod, (a, b) in arrays.items():
            lora_mod.set_adapter_slot(self.params, "layers", slot, mod, a, b)
        return self

    def set_lora_adapter(self, slot: int, path: str):
        """Dynamic multi-LoRA: (re)load one adapter dir into ``slot``
        (reference: host-side adapter swap, models/model_base.py:3349-3356)."""
        return self.write_lora_slot(slot, self.lora_adapter_arrays(path))


def _flatten_tree(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_tree(v, key + "."))
        else:
            out[key] = v
    return out


def _unflatten_tree(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in flat.items():
        parts = k.split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _finalize_generation(input_ids: np.ndarray, collected, eos_ids,
                         ttft: float, seq_lens: np.ndarray) -> Dict[str, Any]:
    """Shared tail of the generation loops: concat steps, trim past the first
    eos per row (tokens after eos are garbage by HF convention), assemble the
    result dict."""
    gen = np.concatenate(collected, axis=1)
    if eos_ids is not None:
        for i in range(gen.shape[0]):
            hits = np.where(np.isin(gen[i], eos_ids))[0]
            if hits.size:
                gen[i, hits[0] + 1:] = eos_ids[0]
    return {"sequences": np.concatenate([input_ids, gen], axis=1),
            "generated": gen, "ttft_s": ttft, "seq_lens": seq_lens}


class PagedCausalLMApplication(CausalLMApplication):
    """Paged-KV (block layout) application with prefix caching
    (reference: BlockKVCacheManager + vLLM-facing surface;
    enabled by ``is_block_kv_layout`` / ``is_prefix_caching``,
    models/config.py:277-317).

    One jitted graph (model_base.paged_forward_step) serves prefill,
    prefix-cached continuation and decode; the host side owns the block
    allocator and tables.
    """

    def init_cache(self):
        from ..modules.block_kv_cache import BlockKVCacheManager, BlockKVSpec
        cfg = self.tpu_config
        bspec = BlockKVSpec(
            num_layers=self.spec.num_layers,
            num_blocks=cfg.pa_num_blocks + 1,    # +1: reserved null block 0
            block_size=cfg.pa_block_size,
            num_kv_heads=self.spec.gqa.num_kv_heads,
            head_dim=self.spec.head_dim,
            dtype=self.spec.kv_dtype,
        )
        self.kv_mgr = BlockKVCacheManager(
            bspec, self.mesh, enable_prefix_caching=cfg.is_prefix_caching)
        # single owner of the live (donated) buffers is the application; the
        # manager keeps allocator + tables only (its .cache would become a
        # stale donated alias after the first step)
        self.cache = self.kv_mgr.cache
        self.kv_mgr.cache = None
        # static block-table width for the jitted graphs
        self.max_blocks = bspec.blocks_for(cfg.seq_len)
        # 2-D prefix x prefill bucketing: per-call block-table widths
        # (reference: autobucketing.py:22-64, selection
        # model_wrapper.py:923-1045)
        self._bt_buckets = autobucketing.block_table_buckets(
            cfg, self.max_blocks)
        return self

    def _jit_paged(self):
        fn = partial(model_base.paged_forward_step, self.spec, self.tpu_config)
        return jax.jit(fn, donate_argnums=(1,))

    # -- positionally coupled sampling (ops/sampling.coupled_sample) -------
    def _coupled_sampling(self) -> bool:
        sc = self.tpu_config.on_device_sampling_config
        return (sc is not None and sc.do_sample
                and sc.stream_seed is not None)

    def _stream_seeds(self, row_seeds, batch: int):
        """Gate the per-row seed input of the paged graph family: None
        unless the coupled stream is on (an absent optional arg is an
        empty pytree, so off-knob graphs stay byte-identical). The
        serving adapters always thread their per-request seeds; this
        gate is what keeps greedy configs on the legacy graphs."""
        if not self._coupled_sampling():
            return None
        if row_seeds is None:
            return jnp.zeros((batch,), jnp.int32)
        return jnp.asarray(row_seeds, jnp.int32)

    def _lora_adapter_ids(self, adapter_ids):
        """Gate the per-row LoRA slot input of the paged graph family:
        None (nothing attached a pool) keeps every graph byte-identical
        to a LoRA-free build — an absent optional arg is an empty pytree,
        exactly the ``_stream_seeds`` off-knob pattern. Negative ids
        clamp to slot 0 (the pinned zero adapter = base model)."""
        if adapter_ids is None:
            return None
        if self.spec.lora is None:
            raise ValueError(
                "adapter_ids passed but the model was built without "
                "lora_config — set TpuConfig.lora_config")
        return jnp.asarray(np.maximum(np.asarray(adapter_ids, np.int32), 0))

    def _jit_paged_loop(self, num_steps: int):
        fn = partial(model_base.paged_decode_loop, self.spec, self.tpu_config,
                     num_steps=num_steps)
        return jax.jit(fn, donate_argnums=(1,))

    def _run_paged_loop(self, first_tokens, positions, block_table,
                        num_steps: int, sampling_params=None,
                        row_seeds=None, adapter_ids=None):
        # horizon guard: the fused loop writes KV at positions
        # [p, p+num_steps); past seq_len the in-graph slot advance would
        # index past the block table (mirrors _run_decode_loop's guard)
        self._check_decode_fits(
            int(np.max(np.asarray(positions))) + num_steps)
        t0 = self._tel_start()
        key = ("paged_loop", num_steps)
        if key not in self._compiled:
            self._compiled[key] = self._jit_paged_loop(num_steps)
        aids = self._lora_adapter_ids(adapter_ids)
        self._note_jit("paged_loop", num_steps,
                       (first_tokens.shape[0], block_table.shape[1],
                        aids is not None))
        if sampling_params is None:
            sampling_params = self._default_sampling_params(
                first_tokens.shape[0])
        seeds = self._stream_seeds(row_seeds, first_tokens.shape[0])
        kw = {"row_seeds": seeds} if seeds is not None else {}
        if aids is not None:
            kw["adapter_ids"] = aids
        with self._mesh_ctx():
            out = self._compiled[key](
                self.params, self.cache, jnp.asarray(first_tokens),
                jnp.asarray(positions), jnp.asarray(block_table),
                sampling_params, self._next_rng(), **kw)
        self.cache = out["cache"]
        self._tel_end("paged_loop", t0, out,
                      first_tokens.shape[0] * num_steps)
        return out

    def get_compiled(self, tag: str, bucket: int = 0):
        if tag == "paged_forward":
            key = (tag, bucket)
            if key not in self._compiled:
                self._compiled[key] = self._jit_paged()
            return self._compiled[key]
        return super().get_compiled(tag, bucket)

    # -- speculative serving graphs (serving/speculation/) -----------------
    def _jit_spec_draft(self, num_steps: int):
        fn = partial(model_base.paged_spec_draft_loop, self.spec,
                     self.tpu_config, num_steps=num_steps)
        return jax.jit(fn, donate_argnums=(1,))

    def _jit_spec_verify(self, want_hidden: bool):
        fn = partial(model_base.paged_spec_verify, self.spec,
                     self.tpu_config, want_hidden=want_hidden)
        return jax.jit(fn, donate_argnums=(1,))

    def _run_spec_draft(self, first_tokens, positions, block_table, widths,
                        num_steps: int, sampling_params=None,
                        row_seeds=None, adapter_ids=None):
        """Masked greedy-k self-draft pass (one fused dispatch; see
        model_base.paged_spec_draft_loop). Frozen rows (width already
        reached) write nothing, so the per-row clamp in ``widths`` bounds
        every KV write."""
        self._check_decode_fits(
            int(np.max(np.asarray(positions) + np.asarray(widths) - 1)))
        t0 = self._tel_start()
        key = ("spec_draft", num_steps)
        if key not in self._compiled:
            self._compiled[key] = self._jit_spec_draft(num_steps)
        aids = self._lora_adapter_ids(adapter_ids)
        self._note_jit("spec_draft", num_steps,
                       (first_tokens.shape[0], block_table.shape[1],
                        aids is not None))
        if sampling_params is None:
            sampling_params = self._default_sampling_params(
                first_tokens.shape[0])
        seeds = self._stream_seeds(row_seeds, first_tokens.shape[0])
        kw = {"row_seeds": seeds} if seeds is not None else {}
        if aids is not None:
            kw["adapter_ids"] = aids
        with self._mesh_ctx():
            out = self._compiled[key](
                self.params, self.cache, jnp.asarray(first_tokens),
                jnp.asarray(positions), jnp.asarray(block_table),
                jnp.asarray(widths), sampling_params, self._next_rng(),
                **kw)
        self.cache = out["cache"]
        self._tel_end("spec_draft", t0, out,
                      first_tokens.shape[0] * num_steps)
        return out

    def _run_spec_verify(self, input_ids, position_ids, slot_mapping,
                         block_table, widths, want_hidden: bool = False,
                         sampling_params=None, row_seeds=None,
                         adapter_ids=None):
        """Speculative verify dispatch: ONE ragged k+1-wide paged forward
        with in-graph exact-match acceptance (model_base.paged_spec_verify
        — greedy argmax, or the coupled sampled draw when the stream-seed
        knob is on). ``input_ids`` may be a device array — drafts never
        round-trip through the host."""
        self._check_decode_fits(
            int(np.max(np.asarray(position_ids)[:, 0]
                       + np.asarray(widths))))
        t0 = self._tel_start()
        key = ("spec_verify", input_ids.shape[1], want_hidden)
        if key not in self._compiled:
            self._compiled[key] = self._jit_spec_verify(want_hidden)
        aids = self._lora_adapter_ids(adapter_ids)
        self._note_jit("spec_verify", input_ids.shape[1],
                       (input_ids.shape, block_table.shape,
                        aids is not None))
        seeds = self._stream_seeds(row_seeds, input_ids.shape[0])
        kw = {}
        if seeds is not None:
            if sampling_params is None:
                sampling_params = self._default_sampling_params(
                    input_ids.shape[0])
            kw = {"sampling_params": sampling_params, "row_seeds": seeds}
        if aids is not None:
            kw["adapter_ids"] = aids
        with self._mesh_ctx():
            out = self._compiled[key](
                self.params, self.cache, jnp.asarray(input_ids),
                jnp.asarray(position_ids), jnp.asarray(slot_mapping),
                jnp.asarray(block_table), jnp.asarray(widths), **kw)
        self.cache = out["cache"]
        self._tel_end("spec_verify", t0, out, input_ids.shape[0])
        return out

    # -- ragged unified dispatch (serving/ragged/) -------------------------
    def _jit_ragged(self, want_hidden: bool):
        fn = partial(model_base.paged_ragged_step, self.spec,
                     self.tpu_config, want_hidden=want_hidden)
        return jax.jit(fn, donate_argnums=(1,))

    def _run_ragged(self, input_ids, position_ids, slot_mapping,
                    block_table, widths, emit_modes,
                    want_hidden: bool = False, sampling_params=None,
                    row_seeds=None, adapter_ids=None):
        """ONE ragged mixed dispatch (model_base.paged_ragged_step): rows
        mix decode steps, prefill chunks and speculative verify windows,
        each at its own offset over its own block table. ``input_ids``
        may be a device array — verify-row drafts never round-trip
        through the host."""
        self._check_decode_fits(
            int(np.max(np.asarray(position_ids)[:, 0]
                       + np.asarray(widths))))
        t0 = self._tel_start()
        key = ("ragged", input_ids.shape[1], want_hidden)
        if key not in self._compiled:
            self._compiled[key] = self._jit_ragged(want_hidden)
        aids = self._lora_adapter_ids(adapter_ids)
        self._note_jit("ragged", input_ids.shape[1],
                       (input_ids.shape, block_table.shape,
                        aids is not None))
        if sampling_params is None:
            sampling_params = self._default_sampling_params(
                input_ids.shape[0])
        seeds = self._stream_seeds(row_seeds, input_ids.shape[0])
        kw = {"row_seeds": seeds} if seeds is not None else {}
        if aids is not None:
            kw["adapter_ids"] = aids
        with self._mesh_ctx():
            out = self._compiled[key](
                self.params, self.cache, jnp.asarray(input_ids),
                jnp.asarray(position_ids), jnp.asarray(slot_mapping),
                jnp.asarray(block_table), jnp.asarray(widths),
                jnp.asarray(emit_modes), sampling_params, self._next_rng(),
                **kw)
        self.cache = out["cache"]
        self._tel_end("ragged", t0, out, input_ids.shape[0])
        return out

    def _bt_width(self, b: int) -> int:
        """Smallest block-table width bucket covering every live row's
        blocks (2-D prefix x prefill bucket selection)."""
        return self._bt_width_for(range(b))

    def _bt_width_for(self, seq_ids) -> int:
        live = max((len(self.kv_mgr.tables.get(i, ())) for i in seq_ids),
                   default=1)
        return autobucketing.get_target_bucket(self._bt_buckets,
                                               max(live, 1),
                                               kind="block_table")

    def _run_paged(self, input_ids, position_ids, slot_mapping, block_table,
                   last_idx, sampling_params=None, row_seeds=None,
                   adapter_ids=None):
        t0 = self._tel_start()
        fn = self.get_compiled("paged_forward")
        aids = self._lora_adapter_ids(adapter_ids)
        # one jitted graph serves every paged call; the shape signature
        # (prefill width x table width) is what distinguishes compiles
        self._note_jit("paged", input_ids.shape[1],
                       (input_ids.shape, block_table.shape,
                        aids is not None))
        if sampling_params is None:
            sampling_params = self._default_sampling_params(input_ids.shape[0])
        seeds = self._stream_seeds(row_seeds, input_ids.shape[0])
        kw = {"row_seeds": seeds} if seeds is not None else {}
        if aids is not None:
            kw["adapter_ids"] = aids
        with self._mesh_ctx():
            out = fn(self.params, self.cache, jnp.asarray(input_ids),
                     jnp.asarray(position_ids), jnp.asarray(slot_mapping),
                     jnp.asarray(block_table), jnp.asarray(last_idx),
                     sampling_params, self._next_rng(), **kw)
        self.cache = out["cache"]
        self._tel_end("paged", t0, out, input_ids.shape[0])
        return out

    def warmup(self):
        """AOT-compile the paged graph at each shape it will run: the prefill
        window (ctx bucket or chunk width) and the T=1 decode step. Dummy
        calls write nothing (all slots negative → dropped)."""
        if self.params is None:
            self.init_random_weights()
        if not hasattr(self, "kv_mgr") or self.cache is None:
            self.init_cache()
        cfg = self.tpu_config
        b = cfg.batch_size
        widths = {1}
        if (cfg.is_chunked_prefill and cfg.chunked_prefill_config is not None):
            widths.add(cfg.chunked_prefill_config.kernel_q_tile_size)
        widths.update(self.ctx_buckets)
        bt = np.zeros((b, self.max_blocks), np.int32)   # null block only
        for w in sorted(widths):
            self._run_paged(np.zeros((b, w), np.int32),
                            np.zeros((b, w), np.int32),
                            np.full((b, w), -1, np.int32), bt,
                            np.zeros((b,), np.int32))
        # 2-D table-width buckets: warm every (prefill width x table
        # width) pair plus the chunked decode loop at every width — the
        # shapes generate() actually runs
        chunk = max(cfg.decode_chunk_tokens, 1)
        for tw in self._bt_buckets[:-1]:
            bt_n = np.zeros((b, tw), np.int32)
            for w in sorted(widths):
                self._run_paged(np.zeros((b, w), np.int32),
                                np.zeros((b, w), np.int32),
                                np.full((b, w), -1, np.int32), bt_n,
                                np.zeros((b,), np.int32))
            if chunk > 1:
                self._run_paged_loop(np.zeros((b,), np.int32),
                                     np.zeros((b,), np.int32), bt_n, chunk)
        if chunk > 1:
            self._run_paged_loop(np.zeros((b,), np.int32),
                                 np.zeros((b,), np.int32), bt, chunk)
        return self

    def generate(self, input_ids: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 128,
                 eos_token_id: Optional[int] = None,
                 sampling_params: Optional[np.ndarray] = None,
                 return_logits: bool = False,
                 teacher_tokens: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Paged generation. Prefix-cached prompt blocks are skipped
        (not recomputed); the rest mirrors CausalLMApplication.generate."""
        from ..modules.block_kv_cache import (cut_cached_at_unwritten,
                                              slots_from_table)
        if teacher_tokens is not None:
            raise NotImplementedError("teacher forcing uses the contiguous app")
        logits_trace: List[np.ndarray] = []
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if b not in self.batch_buckets:
            # batch-mismatch host shim (reference: model_wrapper.py:520-703
            # + sub-batching :1315-1440) — without it a b != compiled-batch
            # request would silently jit a fresh graph mid-request
            return self._generate_repadded(
                input_ids, attention_mask=attention_mask,
                max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
                sampling_params=sampling_params,
                return_logits=return_logits)
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        if self.params is None:
            raise RuntimeError("load_weights() or init_random_weights() first")
        if not hasattr(self, "kv_mgr") or self.cache is None:
            self.init_cache()
        if sampling_params is not None:
            sampling_params = jnp.asarray(sampling_params)
        eos_ids = (None if eos_token_id is None
                   else np.atleast_1d(np.asarray(eos_token_id, dtype=np.int64)))

        # --- allocate blocks; discover cached prefix per row ---
        cfg = self.tpu_config
        chunked = (cfg.is_chunked_prefill
                   and cfg.chunked_prefill_config is not None)
        cached = np.zeros((b,), np.int32)
        bsz = self.kv_mgr.spec.block_size
        batch_fresh: set = set()      # blocks first written by THIS call
        for i in range(b):
            toks = input_ids[i, :seq_lens[i]].tolist()
            blocks, c = self.kv_mgr.begin_sequence(i, toks)
            if chunked:
                # chunked prefill writes sibling rows' blocks chunk by chunk,
                # so a prefix hit on a block allocated earlier in this SAME
                # batch may read slots the sibling hasn't written yet — cut
                # the cached prefix at the first such block (shared helper
                # with the serving adapter's packed-chunk path)
                c = cut_cached_at_unwritten(blocks, c, bsz, batch_fresh)
            batch_fresh.update(blocks[c // bsz:])
            # always recompute >= 1 token so there are logits to sample from
            cached[i] = min(c, seq_lens[i] - 1)
        bt = self.kv_mgr.block_table_array(range(b), self._bt_width(b))

        # --- prefill the uncached suffixes ---
        suffix_lens = seq_lens - cached
        t_max = int(suffix_lens.max())
        chunk_w = (cfg.chunked_prefill_config.kernel_q_tile_size
                   if chunked else 0)

        def _prefill_window(off_w, width, last_idx):
            """One paged-prefill call over window [off, off+width) of each
            row's uncached suffix (off_w: (B,) per-row offsets)."""
            ids_w = np.zeros((b, width), np.int32)
            pos_w = np.zeros((b, width), np.int32)
            for i in range(b):
                lo = cached[i] + off_w[i]
                n = int(np.clip(seq_lens[i] - lo, 0, width))
                ids_w[i, :n] = input_ids[i, lo:lo + n]
                pos_w[i] = lo + np.arange(width, dtype=np.int32)
            valid = (np.arange(width)[None, :]
                     < (seq_lens - cached - off_w)[:, None])
            # padded tail positions: writes dropped via negative slots,
            # outputs never sampled
            slot_pos = np.where(valid, pos_w, -1)
            slots = slots_from_table(bt, slot_pos, self.kv_mgr.spec.block_size)
            return self._run_paged(ids_w, pos_w, slots, bt, last_idx,
                                   sampling_params)

        t0 = time.perf_counter()
        if chunk_w and t_max > chunk_w:
            # chunked prefill (reference: windowed context encoding,
            # model_base.py:878-933 + ChunkedPrefillConfig): walk the suffix
            # in fixed windows re-invoking the same graph with growing KV
            n_chunks = -(-t_max // chunk_w)
            tokens = np.zeros((b, 1), np.int32)
            off = np.zeros((b,), np.int32)
            for c in range(n_chunks):
                last_idx = np.clip(suffix_lens - 1 - off, 0, chunk_w - 1)
                out = _prefill_window(off, chunk_w, last_idx)
                toks = np.asarray(out["tokens"]).reshape(b)
                final_here = ((suffix_lens - 1 >= off)
                              & (suffix_lens - 1 < off + chunk_w))
                tokens[final_here, 0] = toks[final_here]
                off = off + chunk_w
        else:
            # 2-D (prefill width x table width) selection: the table width
            # was already bucketed when bt was built (_bt_width) — this
            # picks the other axis (reference: 2-D prefix-caching bucket
            # selection, model_wrapper.py:923-1045)
            bucket = autobucketing.get_target_bucket(self.ctx_buckets, t_max,
                                                     kind="ctx")
            out = _prefill_window(np.zeros((b,), np.int32), bucket,
                                  np.maximum(suffix_lens - 1, 0))
            tokens = np.asarray(out["tokens"]).reshape(b, 1)
        ttft = time.perf_counter() - t0
        if return_logits and "logits" in out:
            logits_trace.append(np.asarray(out["logits"]))

        collected = [tokens]
        positions = seq_lens.astype(np.int32)
        n_generated = 1
        eos_seen = np.zeros((b,), bool) if eos_ids is not None else None
        if eos_seen is not None:
            eos_seen |= np.isin(tokens[:, 0], eos_ids)
        # fetch-free chunked paged decode: blocks for the whole chunk are
        # pre-allocated on the host, then ``decode_chunk_tokens`` steps run
        # in ONE device call with slot mappings computed in-graph
        # (model_base.paged_decode_loop; reference: in-graph tokengen
        # slot-mapping, block_kv_cache_manager.py:376-430). Zero per-token
        # host fetches; EOS is checked at chunk boundaries.
        # return_logits keeps the single-step path (per-step logits).
        chunk = 1 if return_logits else max(cfg.decode_chunk_tokens, 1)
        while n_generated < max_new_tokens:
            room = self.tpu_config.seq_len - int(positions.max())
            remaining = min(max_new_tokens - n_generated, room)
            # a partial chunk would jit a fresh ('paged_loop', n) graph
            # mid-request — finish remainders with the single-step graph
            steps = chunk if remaining >= chunk else 1
            steps = min(steps, remaining)
            if steps <= 0:
                break
            for i in range(b):
                self.kv_mgr.grow(i, steps)
            bt = self.kv_mgr.block_table_array(range(b), self._bt_width(b))
            cur = collected[-1][:, -1].astype(np.int32)
            if steps == 1:
                pos = positions[:, None]
                slots = slots_from_table(bt, pos,
                                         self.kv_mgr.spec.block_size)
                o = self._run_paged(cur[:, None], pos, slots, bt,
                                    np.zeros((b,), np.int32),
                                    sampling_params)
                new = np.asarray(o["tokens"]).reshape(b, 1)
                if return_logits and "logits" in o:
                    logits_trace.append(np.asarray(o["logits"]))
            else:
                o = self._run_paged_loop(cur, positions, bt, steps,
                                         sampling_params)
                new = np.asarray(o["tokens"])
            collected.append(new)
            positions = positions + steps
            n_generated += steps
            if eos_seen is not None:
                eos_seen |= np.isin(new, eos_ids).any(axis=1)
                if eos_seen.all():
                    break

        result = _finalize_generation(input_ids, collected, eos_ids, ttft,
                                      seq_lens)
        result["cached_tokens"] = cached.copy()
        if return_logits:
            result["logits"] = logits_trace
        return result

    def release(self, seq_ids=None):
        """Return sequences' blocks to the allocator (prefix-cached blocks
        stay resident for reuse)."""
        ids = list(self.kv_mgr.tables) if seq_ids is None else list(seq_ids)
        for sid in ids:
            if sid in self.kv_mgr.tables:
                self.kv_mgr.end_sequence(sid)
        return self

    def reset(self):
        self.release()
        return self
