from .modeling_whisper import (TpuWhisperForConditionalGeneration,
                               WhisperApplication, WhisperInferenceConfig)
