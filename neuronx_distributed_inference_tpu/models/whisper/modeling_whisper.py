"""Whisper encoder-decoder (reference: models/whisper/modeling_whisper.py
:571-678 — encoder + decoder applications with cross-attention KV cache and
separate prefill/decode wrappers; 951 LoC).

TPU design: three jitted pure functions sharing one param tree —
  * ``encoder_forward``  — conv frontend + sinusoidal positions + bidirectional
    self-attention stack (lax.scan)
  * ``compute_cross_kv`` — the per-request cross-attention K/V, computed ONCE
    from the encoder output (the reference caches these in its own
    multimodal KV manager, modules/kvcache/multimodal_kv_cache_manager.py)
  * ``decoder_step``     — causal self-attn over a donated KV cache + static
    cross-attn + mlp; serves both the forced-decoder prefill and the
    autoregressive loop (T>=1)

All LayerNorms carry biases; q/v/out projections have biases, k does not
(matching WhisperAttention). Weights are replicated (whisper-large is ~1.5B;
TP hooks can reuse the decoder ParamSpec machinery later)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.host_loop import greedy_host_loop

from ...config import InferenceConfig, TpuConfig
from ...ops.normalization import layer_norm


@dataclass(frozen=True)
class WhisperSpec:
    d_model: int
    encoder_layers: int
    decoder_layers: int
    encoder_heads: int
    decoder_heads: int
    ffn_dim: int
    vocab_size: int
    num_mel_bins: int
    max_source_positions: int     # encoder positions (1500)
    max_target_positions: int     # decoder positions (448)
    decoder_start_token_id: int
    eos_token_id: int

    @property
    def head_dim(self) -> int:
        return self.d_model // self.decoder_heads


def spec_from_hf_config(cfg) -> WhisperSpec:
    return WhisperSpec(
        d_model=cfg.d_model,
        encoder_layers=cfg.encoder_layers,
        decoder_layers=cfg.decoder_layers,
        encoder_heads=cfg.encoder_attention_heads,
        decoder_heads=cfg.decoder_attention_heads,
        ffn_dim=getattr(cfg, "encoder_ffn_dim", 4 * cfg.d_model),
        vocab_size=cfg.vocab_size,
        num_mel_bins=cfg.num_mel_bins,
        max_source_positions=cfg.max_source_positions,
        max_target_positions=cfg.max_target_positions,
        decoder_start_token_id=cfg.decoder_start_token_id,
        eos_token_id=cfg.eos_token_id,
    )


def _mha(q, k, v, heads: int, mask=None):
    """(B,T,H)x(B,S,H) attention, fp32 softmax; q pre-scaled."""
    b, t, hd = q.shape
    s = k.shape[1]
    d = hd // heads
    qf = q.reshape(b, t, heads, d).astype(jnp.float32)
    kf = k.reshape(b, s, heads, d).astype(jnp.float32)
    vf = v.reshape(b, s, heads, d).astype(jnp.float32)
    scores = jnp.einsum("bthd,bshd->bhts", qf, kf)
    if mask is not None:
        scores = jnp.where(mask[:, None, :, :], scores, -30000.0)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, vf)
    return out.reshape(b, t, hd).astype(q.dtype)


def _attn_proj(lw, prefix, x):
    """q/k/v projections with whisper's bias layout (k has none)."""
    q = x @ lw[f"{prefix}_q_w"] + lw[f"{prefix}_q_b"]
    k = x @ lw[f"{prefix}_k_w"]
    v = x @ lw[f"{prefix}_v_w"] + lw[f"{prefix}_v_b"]
    return q, k, v


def sinusoidal_positions(length: int, dim: int) -> np.ndarray:
    """Whisper's fixed encoder position table."""
    log_timescale = np.log(10000.0) / (dim // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(dim // 2, dtype=np.float32))
    t = np.arange(length, dtype=np.float32)[:, None] * inv[None, :]
    return np.concatenate([np.sin(t), np.cos(t)], axis=1).astype(np.float32)


def encoder_forward(spec: WhisperSpec, params, input_features):
    """mel features (B, n_mels, T) -> encoder states (B, T//2, H)."""
    enc = params["encoder"]
    dn = ("NCH", "OIH", "NCH")
    x = jax.lax.conv_general_dilated(
        input_features, enc["conv1_w"], (1,), [(1, 1)], dimension_numbers=dn)
    x = jax.nn.gelu(x + enc["conv1_b"][None, :, None], approximate=False)
    x = jax.lax.conv_general_dilated(
        x, enc["conv2_w"], (2,), [(1, 1)], dimension_numbers=dn)
    x = jax.nn.gelu(x + enc["conv2_b"][None, :, None], approximate=False)
    x = jnp.transpose(x, (0, 2, 1))                    # (B, S, H)
    x = x + enc["pos"][: x.shape[1]]

    scale = spec.head_dim ** -0.5

    def body(h, lw):
        r = layer_norm(h, lw["ln1_w"], lw["ln1_b"])
        q, k, v = _attn_proj(lw, "self", r)
        a = _mha(q * scale, k, v, spec.encoder_heads)
        h = h + (a @ lw["self_o_w"] + lw["self_o_b"])
        r = layer_norm(h, lw["ln2_w"], lw["ln2_b"])
        m = jax.nn.gelu(r @ lw["fc1_w"] + lw["fc1_b"], approximate=False)
        h = h + (m @ lw["fc2_w"] + lw["fc2_b"])
        return h, None

    x, _ = jax.lax.scan(body, x, enc["layers"])
    return layer_norm(x, enc["ln_f_w"], enc["ln_f_b"])


def compute_cross_kv(spec: WhisperSpec, params, enc_out):
    """Per-request cross-attention K/V: (L, B, S_enc, H) each."""
    dec = params["decoder"]

    def body(_, lw):
        k = enc_out @ lw["cross_k_w"]
        v = enc_out @ lw["cross_v_w"] + lw["cross_v_b"]
        return None, (k, v)

    _, (ks, vs) = jax.lax.scan(body, None, dec["layers"])
    return {"k": ks, "v": vs}


def decoder_step(spec: WhisperSpec, params, cache, cross_kv, tokens,
                 positions):
    """tokens (B, T) at absolute ``positions`` (B, T); self-KV cache
    {'k','v'} (L, B, S_max, H) donated. Returns logits (B, T, V) + cache."""
    dec = params["decoder"]
    b, t = tokens.shape
    x = dec["embed"][tokens] + dec["pos"][positions]
    s_max = cache["k"].shape[2]
    kv_pos = jnp.arange(s_max, dtype=positions.dtype)
    causal = kv_pos[None, None, :] <= positions[:, :, None]   # (B, T, S)
    scale = spec.head_dim ** -0.5
    bidx = jnp.arange(b)

    def body(h, xs):
        lw, kc, vc, ck, cv = xs
        r = layer_norm(h, lw["ln1_w"], lw["ln1_b"])
        q, k, v = _attn_proj(lw, "self", r)
        kc = kc.at[bidx[:, None], positions].set(k, mode="drop")
        vc = vc.at[bidx[:, None], positions].set(v, mode="drop")
        a = _mha(q * scale, kc, vc, spec.decoder_heads, mask=causal)
        h = h + (a @ lw["self_o_w"] + lw["self_o_b"])
        r = layer_norm(h, lw["ln2_w"], lw["ln2_b"])
        q = (r @ lw["cross_q_w"] + lw["cross_q_b"]) * scale
        a = _mha(q, ck, cv, spec.decoder_heads)
        h = h + (a @ lw["cross_o_w"] + lw["cross_o_b"])
        r = layer_norm(h, lw["ln3_w"], lw["ln3_b"])
        m = jax.nn.gelu(r @ lw["fc1_w"] + lw["fc1_b"], approximate=False)
        h = h + (m @ lw["fc2_w"] + lw["fc2_b"])
        return h, (kc, vc)

    x, (nk, nv) = jax.lax.scan(
        body, x, (dec["layers"], cache["k"], cache["v"],
                  cross_kv["k"], cross_kv["v"]))
    x = layer_norm(x, dec["ln_f_w"], dec["ln_f_b"])
    logits = (x @ dec["embed"].T).astype(jnp.float32)   # tied proj_out
    return {"logits": logits, "cache": {"k": nk, "v": nv}}


# ---------------------------------------------------------------------------
# checkpoint conversion (HF WhisperForConditionalGeneration)
# ---------------------------------------------------------------------------

def convert_hf_state_dict(sd: Dict[str, np.ndarray], spec: WhisperSpec
                          ) -> Dict[str, Any]:
    def get(n):
        if n in sd:
            return np.asarray(sd[n], np.float32)
        raise KeyError(f"missing checkpoint tensor {n}")

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def attn(base, prefix, cross=False):
        out = {
            f"{prefix}_q_w": t(get(f"{base}.q_proj.weight")),
            f"{prefix}_q_b": get(f"{base}.q_proj.bias"),
            f"{prefix}_k_w": t(get(f"{base}.k_proj.weight")),
            f"{prefix}_v_w": t(get(f"{base}.v_proj.weight")),
            f"{prefix}_v_b": get(f"{base}.v_proj.bias"),
            f"{prefix}_o_w": t(get(f"{base}.out_proj.weight")),
            f"{prefix}_o_b": get(f"{base}.out_proj.bias"),
        }
        return out

    def enc_layer(i):
        b = f"model.encoder.layers.{i}"
        lw = attn(f"{b}.self_attn", "self")
        lw.update({
            "ln1_w": get(f"{b}.self_attn_layer_norm.weight"),
            "ln1_b": get(f"{b}.self_attn_layer_norm.bias"),
            "ln2_w": get(f"{b}.final_layer_norm.weight"),
            "ln2_b": get(f"{b}.final_layer_norm.bias"),
            "fc1_w": t(get(f"{b}.fc1.weight")), "fc1_b": get(f"{b}.fc1.bias"),
            "fc2_w": t(get(f"{b}.fc2.weight")), "fc2_b": get(f"{b}.fc2.bias"),
        })
        return lw

    def dec_layer(i):
        b = f"model.decoder.layers.{i}"
        lw = attn(f"{b}.self_attn", "self")
        lw.update(attn(f"{b}.encoder_attn", "cross"))
        lw.update({
            "ln1_w": get(f"{b}.self_attn_layer_norm.weight"),
            "ln1_b": get(f"{b}.self_attn_layer_norm.bias"),
            "ln2_w": get(f"{b}.encoder_attn_layer_norm.weight"),
            "ln2_b": get(f"{b}.encoder_attn_layer_norm.bias"),
            "ln3_w": get(f"{b}.final_layer_norm.weight"),
            "ln3_b": get(f"{b}.final_layer_norm.bias"),
            "fc1_w": t(get(f"{b}.fc1.weight")), "fc1_b": get(f"{b}.fc1.bias"),
            "fc2_w": t(get(f"{b}.fc2.weight")), "fc2_b": get(f"{b}.fc2.bias"),
        })
        return lw

    def stack(ls):
        return {k: np.stack([d[k] for d in ls]) for k in ls[0]}

    return {
        "encoder": {
            "conv1_w": get("model.encoder.conv1.weight"),
            "conv1_b": get("model.encoder.conv1.bias"),
            "conv2_w": get("model.encoder.conv2.weight"),
            "conv2_b": get("model.encoder.conv2.bias"),
            "pos": get("model.encoder.embed_positions.weight"),
            "layers": stack([enc_layer(i) for i in range(spec.encoder_layers)]),
            "ln_f_w": get("model.encoder.layer_norm.weight"),
            "ln_f_b": get("model.encoder.layer_norm.bias"),
        },
        "decoder": {
            "embed": get("model.decoder.embed_tokens.weight"),
            "pos": get("model.decoder.embed_positions.weight"),
            "layers": stack([dec_layer(i) for i in range(spec.decoder_layers)]),
            "ln_f_w": get("model.decoder.layer_norm.weight"),
            "ln_f_b": get("model.decoder.layer_norm.bias"),
        },
    }


def whisper_pspec(name: str, ndim: int):
    """TP sharding by weight-name suffix (Column/RowParallelLinear analog
    for the q/k/v/fc1 vs o/fc2 projections; norms/embeddings replicated).
    Works for both bare and layer-stacked (leading L dim) leaves."""
    from jax.sharding import PartitionSpec as P
    from ...parallel.mesh import AXIS_MP
    if name.endswith(("_q_w", "_k_w", "_v_w", "fc1_w")):
        return P(*([None] * (ndim - 1) + [AXIS_MP]))       # (…, in, OUT)
    if name.endswith(("_o_w", "fc2_w")):
        return P(*([None] * (ndim - 2) + [AXIS_MP, None]))  # (…, IN, out)
    if name.endswith(("_q_b", "_v_b", "fc1_b")):
        return P(*([None] * (ndim - 1) + [AXIS_MP]))
    return P()


class WhisperApplication:
    """Encode-once + autoregressive decode (reference: the whisper encoder/
    decoder NeuronApplications with their own prefill/decode ModelWrappers).
    Weights shard tensor-parallel over the mesh's model-parallel axes."""

    def __init__(self, model_path: Optional[str], config: InferenceConfig,
                 mesh=None):
        from ...parallel.mesh import mesh_from_config
        self.config = config
        self.tpu_config = config.tpu_config
        self.spec = spec_from_hf_config(config)
        self.model_path = model_path
        self.mesh = mesh or mesh_from_config(config.tpu_config)
        self.params = None
        self._encode = jax.jit(partial(encoder_forward, self.spec))
        self._cross = jax.jit(partial(compute_cross_kv, self.spec))
        self._step = jax.jit(partial(decoder_step, self.spec),
                             donate_argnums=(1,))

    def load_weights(self, model_path: Optional[str] = None):
        from ...utils import checkpoint as ckpt
        from jax.sharding import NamedSharding
        sd = ckpt.load_state_dict(model_path or self.model_path)
        host = convert_hf_state_dict(sd, self.spec)
        flat, tree = jax.tree_util.tree_flatten_with_path(host)
        leaves = []
        for path, arr in flat:
            name = str(path[-1].key)
            sh = NamedSharding(self.mesh,
                               whisper_pspec(name, np.asarray(arr).ndim))
            leaves.append(jax.device_put(jnp.asarray(arr), sh))
        self.params = jax.tree_util.tree_unflatten(tree, leaves)
        return self

    def init_cache(self, batch: int):
        s = self.spec
        smax = min(self.tpu_config.seq_len, s.max_target_positions)
        return {"k": jnp.zeros((s.decoder_layers, batch, smax, s.d_model)),
                "v": jnp.zeros((s.decoder_layers, batch, smax, s.d_model))}

    def generate(self, input_features: np.ndarray, max_new_tokens: int = 32,
                 decoder_input_ids: Optional[np.ndarray] = None
                 ) -> Dict[str, Any]:
        """Greedy transcription. input_features (B, n_mels, T)."""
        b = input_features.shape[0]
        with jax.sharding.set_mesh(self.mesh):
            enc = self._encode(self.params, jnp.asarray(input_features))
            cross = self._cross(self.params, enc)
        cache = self.init_cache(b)
        if decoder_input_ids is None:
            decoder_input_ids = np.full((b, 1), self.spec.decoder_start_token_id,
                                        np.int32)
        toks = np.asarray(decoder_input_ids, np.int32)
        t0 = toks.shape[1]
        pos = np.broadcast_to(np.arange(t0, dtype=np.int32), (b, t0))
        with jax.sharding.set_mesh(self.mesh):
            out = self._step(self.params, cache, cross, jnp.asarray(toks),
                             jnp.asarray(pos))
        state = {"cache": out["cache"], "pos": t0}
        first = jnp.argmax(out["logits"][:, -1], axis=-1).astype(jnp.int32)

        def step(last):
            p = jnp.full((b, 1), state["pos"], jnp.int32)
            with jax.sharding.set_mesh(self.mesh):
                o = self._step(self.params, state["cache"], cross,
                               last[:, None], p)
            state["cache"] = o["cache"]
            state["pos"] += 1
            return jnp.argmax(o["logits"][:, -1], axis=-1).astype(jnp.int32)

        # shared chunked host loop (utils/host_loop.py): tokens stay on
        # device, EOS checked at chunk boundaries — no per-token fetch
        gen = greedy_host_loop(
            step, first, max_new_tokens,
            eos_ids=np.asarray([self.spec.eos_token_id]))
        return {"sequences": np.concatenate([toks, gen], axis=1),
                "generated": gen, "encoder_states": np.asarray(enc)}


class WhisperInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["d_model", "encoder_layers", "decoder_layers", "vocab_size",
                "num_mel_bins", "max_source_positions",
                "max_target_positions"]


def TpuWhisperForConditionalGeneration(model_path: str,
                                       config: InferenceConfig):
    return WhisperApplication(model_path, config)
