"""Image-to-text application base (reference:
models/image_to_text_model_base.py ``ImageToTextInferenceConfig`` /
``NeuronBaseForImageToText`` :34,118 — two builders (text+vision), separate
compile/load, vision+text forward; 773+309 LoC).

TPU design: a vision tower (models/vision.py ViT), a multimodal projector,
and the standard text CausalLMApplication. The projected image features are
merged into the prefill embeddings at the image-token positions inside the
text graph (model_base.context_encoding_step image_embeds/image_mask);
decode is the plain text decode. Concrete family here: LLaVA-style
(CLIP tower + 2-layer gelu projector + llama text) — the shape shared by
pixtral / llama4's llava-like composition (SURVEY §2.7)."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig, TpuConfig
from ..utils import checkpoint as ckpt
from . import model_base, vision
from .application import CausalLMApplication
from .encoder_base import EncoderApplication
from .family import get_family


class ImageToTextInferenceConfig(InferenceConfig):
    """Holds text_config + vision_config dicts (reference:
    ImageToTextInferenceConfig)."""

    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_index"]

    def get_text_config(self) -> InferenceConfig:
        tc = dict(self.text_config)
        family = get_family(tc.get("model_type", "llama"))
        return family.config_cls(self.tpu_config, **tc)


class ImageToTextApplication:
    """Vision tower + projector + text LM (reference:
    NeuronBaseForImageToText)."""

    def __init__(self, model_path: Optional[str],
                 config: ImageToTextInferenceConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        text_cfg = config.get_text_config()
        self.text = CausalLMApplication(model_path, text_cfg, mesh=mesh)
        feature_layer = int(getattr(config, "vision_feature_layer", -2))
        self.vit_spec = vision.vit_spec_from_hf(dict(config.vision_config),
                                                feature_layer=feature_layer)
        self.select_strategy = getattr(config, "vision_feature_select_strategy",
                                       "default")
        self.image_token_index = int(config.image_token_index)
        self.vision_params = None
        self.projector = None
        self._vit = jax.jit(partial(vision.vit_forward, self.vit_spec))
        self._project = jax.jit(self._project_fn)

    # -- weights --
    def load_weights(self, model_path: Optional[str] = None):
        path = model_path or self.model_path
        sd = ckpt.load_state_dict(path)
        # text weights may sit under model.language_model. / language_model.
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
                continue
            for pre, new in (("model.language_model.", "model."),
                             ("language_model.model.", "model."),
                             ("language_model.", "model.")):
                if k.startswith(pre):
                    text_sd[new + k[len(pre):]] = v
                    break
        self.text.params = None
        host = self.text.family.convert_hf_state_dict(text_sd, self.text.spec)
        self.text._put_params(host)

        vis_prefix = ("model.vision_tower" if any(
            k.startswith("model.vision_tower") for k in sd) else "vision_tower")
        self.vision_params = jax.tree.map(jnp.asarray,
                                          vision.convert_clip_vision_tower(
                                              sd, self.vit_spec, vis_prefix))
        proj_prefix = ("model.multi_modal_projector" if any(
            k.startswith("model.multi_modal_projector") for k in sd)
            else "multi_modal_projector")

        def t(w):
            return jnp.asarray(np.ascontiguousarray(
                np.asarray(w, np.float32).T))

        self.projector = {
            "w1": t(sd[f"{proj_prefix}.linear_1.weight"]),
            "b1": jnp.asarray(np.asarray(
                sd[f"{proj_prefix}.linear_1.bias"], np.float32)),
            "w2": t(sd[f"{proj_prefix}.linear_2.weight"]),
            "b2": jnp.asarray(np.asarray(
                sd[f"{proj_prefix}.linear_2.bias"], np.float32)),
        }
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def _project_fn(self, projector, feats):
        h = feats @ projector["w1"] + projector["b1"]
        h = jax.nn.gelu(h, approximate=False)
        return h @ projector["w2"] + projector["b2"]

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        """pixel_values (N_images, C, H, W) -> projected features
        (N_images, tokens_per_image, H_text)."""
        feats = self._vit(self.vision_params, jnp.asarray(pixel_values))
        if self.select_strategy == "default" and self.vit_spec.use_cls_token:
            feats = feats[:, 1:]                   # drop CLS
        return self._project(self.projector, feats)

    @property
    def tokens_per_image(self) -> int:
        drop = 1 if (self.select_strategy == "default"
                     and self.vit_spec.use_cls_token) else 0
        return self.vit_spec.num_tokens - drop

    def generate(self, input_ids: np.ndarray, pixel_values: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 return_logits: bool = False) -> Dict[str, Any]:
        """input_ids contain ``image_token_index`` placeholders (one per
        image patch token, HF llava convention); pixel_values (B, C, H, W)
        one image per row (multi-image: flatten rows upstream)."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        image_mask = (input_ids == self.image_token_index)
        feats = self.encode_images(pixel_values)       # (B, T_img, H)
        if self.text.cache is None:
            self.text.init_cache()
        # merged prefill runs through the text app with the image args bound
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            return_logits=return_logits,
            image_embeds=feats, image_mask=image_mask)

    def reset(self):
        self.text.reset()
        return self


def TpuLlavaForConditionalGeneration(model_path: str,
                                     config: ImageToTextInferenceConfig):
    return ImageToTextApplication(model_path, config)
