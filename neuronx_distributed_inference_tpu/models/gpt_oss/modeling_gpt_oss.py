"""GPT-OSS family (reference: models/gpt_oss/ — SURVEY §2.7: MXFP4 MoE,
learned sinks, alternating attention, mx layout transform; 2034 LoC).

Deltas vs the base decoder, all expressed as spec knobs:
  * learned per-head attention sinks (``attn_sink``; reference:
    modules/attention/sink.py) — extra softmax-denominator column
  * alternating sliding/full attention via ``layer_pattern`` (1:1 ratio)
  * YaRN rope (ops/rope.py yarn path with attention-factor cos/sin scale)
  * MoE with router bias IN the logits, clamped-swiglu experts with
    per-expert biases (moe.glu_style="oss_clamp")
  * qkv + o projection biases
  * MXFP4 expert weights: loads either the HF bf16 checkpoint (optionally
    re-quantizing to our packed mxfp4 when ``quantized=True,
    quantization_dtype="mxfp4"``) or the native gpt-oss blocks+scales
    layout (``*_blocks`` / ``*_scales`` tensors, decoded by
    quantization.dequant_oai_mxfp4_blocks)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ...modules.quantization import dequant_oai_mxfp4_blocks
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class GptOssInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "num_local_experts", "num_experts_per_tok", "sliding_window"]


@register_family("gpt_oss")
class GptOssFamily(DecoderFamily):
    config_cls = GptOssInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        n_layers = config.num_hidden_layers
        layer_types = getattr(config, "layer_types", None)
        if layer_types is None:
            layer_types = ["sliding_attention" if (i + 1) % 2 else
                           "full_attention" for i in range(n_layers)]
        pattern = tuple(t == "sliding_attention" for t in layer_types)
        moe = MoESpec(
            num_experts=config.num_local_experts,
            top_k=config.num_experts_per_tok,
            intermediate_size=config.intermediate_size,
            normalize_topk=False,
            pre_softmax_topk=True,       # topk on logits, softmax over the k
            has_router_bias=True,
            router_bias_mode="logits",
            expert_bias=True,
            glu_style="oss_clamp",
        )
        return spec_from_config(
            config, tp_degree,
            sliding_window=int(config.sliding_window),
            layer_pattern=pattern,
            attn_sink=True,
            qkv_bias=bool(getattr(config, "attention_bias", True)),
            o_bias=bool(getattr(config, "attention_bias", True)),
            moe=moe,
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec: DecoderSpec
                            ) -> Dict[str, np.ndarray]:
        """gpt-oss expert layout: fused gate_up_proj (E, H, 2I) with gate/up
        INTERLEAVED on the last dim (gate = ::2, up = 1::2), plus per-expert
        biases; stored either as bf16 tensors or as MXFP4 blocks+scales."""
        p = cls.hf_prefix
        L = spec.num_layers

        def expert_tensor(i: int, name: str) -> np.ndarray:
            base = f"{p}.layers.{i}.mlp.experts.{name}"
            try:
                return np.asarray(get(base)).astype(np.float32)
            except KeyError:
                # native mxfp4 checkpoint layout: <name>_blocks + _scales,
                # value axis LAST (E, rows, K/32, 16) -> (E, rows, K)
                blocks = np.asarray(get(base + "_blocks"))
                scales = np.asarray(get(base + "_scales"))
                deq = dequant_oai_mxfp4_blocks(blocks, scales)
                # stored row-major (E, out_rows, K): transpose to (E, K, out)
                return np.ascontiguousarray(np.swapaxes(deq, -1, -2))

        gate, up, down = [], [], []
        gate_b, up_b, down_b = [], [], []
        routers, router_biases = [], []
        for i in range(L):
            gu = expert_tensor(i, "gate_up_proj")            # (E, H, 2I)
            gate.append(np.ascontiguousarray(gu[..., 0::2]))
            up.append(np.ascontiguousarray(gu[..., 1::2]))
            down.append(expert_tensor(i, "down_proj"))       # (E, I, H)
            gub = np.asarray(get(f"{p}.layers.{i}.mlp.experts.gate_up_proj_bias"))
            gate_b.append(np.ascontiguousarray(gub[..., 0::2]))
            up_b.append(np.ascontiguousarray(gub[..., 1::2]))
            down_b.append(np.asarray(
                get(f"{p}.layers.{i}.mlp.experts.down_proj_bias")))
            routers.append(np.ascontiguousarray(np.asarray(
                get(f"{p}.layers.{i}.mlp.router.weight")).T.astype(np.float32)))
            router_biases.append(np.asarray(
                get(f"{p}.layers.{i}.mlp.router.bias")).astype(np.float32))
        return {
            "router": np.stack(routers),
            "router_bias": np.stack(router_biases),
            "expert_gate": np.stack(gate),
            "expert_up": np.stack(up),
            "expert_down": np.stack(down),
            "expert_gate_bias": np.stack(gate_b),
            "expert_up_bias": np.stack(up_b),
            "expert_down_bias": np.stack(down_b),
        }

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec: DecoderSpec
                                    ) -> Dict[str, np.ndarray]:
        from ...parallel.layers import place_q_weight
        p = cls.hf_prefix

        def sink_t(s):
            # per-q-head param: place into padded slots like a q bias
            return place_q_weight(np.asarray(s).astype(np.float32), spec.gqa,
                                  1)

        return {"sink": layer_stack(p + ".layers.{i}.self_attn.sinks", sink_t)}


def TpuGptOssForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, GptOssFamily)
