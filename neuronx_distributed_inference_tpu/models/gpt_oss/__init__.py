from .modeling_gpt_oss import (GptOssFamily, GptOssInferenceConfig,
                               TpuGptOssForCausalLM)
