"""Pixtral family — llava-composed mistral text + Pixtral ViT
(reference: models/pixtral/ — modeling_pixtral_vision.py RMSNorm tower with
2-D rope + gated MLP, modeling_pixtral.py llava-style merge; 1109 LoC).

The text side is the registered mistral family (ImageToTextInferenceConfig
routes by text_config.model_type); this module adds the Pixtral vision
tower: patch conv -> RMSNorm pre-norm -> layers of (RMSNorm, rope'd
bidirectional attention, gated silu MLP, RMSNorm) -> llava projector.
Rope angles come from the (h*max_w + w)-indexed frequency table
(interleaved h/w frequency slots — HF PixtralRotaryEmbedding semantics)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.normalization import rms_norm
from ..image_to_text import ImageToTextApplication, ImageToTextInferenceConfig


@dataclass(frozen=True)
class PixtralVisionSpec:
    num_layers: int
    hidden_size: int
    num_heads: int
    intermediate_size: int
    patch_size: int
    image_size: int
    rope_theta: float = 10000.0
    eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def max_side(self) -> int:
        return self.image_size // self.patch_size


def pixtral_vision_spec(vc: Dict[str, Any]) -> PixtralVisionSpec:
    return PixtralVisionSpec(
        num_layers=int(vc["num_hidden_layers"]),
        hidden_size=int(vc["hidden_size"]),
        num_heads=int(vc["num_attention_heads"]),
        intermediate_size=int(vc["intermediate_size"]),
        patch_size=int(vc["patch_size"]),
        image_size=int(vc["image_size"]),
        rope_theta=float(vc.get("rope_theta", 10000.0)),
    )


def pixtral_rope_table(spec: PixtralVisionSpec) -> np.ndarray:
    """(max_side^2, head_dim/2) angle table; row h*max_w + w holds the
    interleaved h/w frequencies (HF PixtralRotaryEmbedding)."""
    d = spec.head_dim
    freqs = 1.0 / (spec.rope_theta
                   ** (np.arange(0, d, 2, dtype=np.float32) / d))
    side = spec.max_side
    h = np.arange(side, dtype=np.float32)
    fh = np.outer(h, freqs[0::2])                       # (side, d/4)
    fw = np.outer(h, freqs[1::2])
    table = np.concatenate([
        np.repeat(fh[:, None, :], side, axis=1),
        np.repeat(fw[None, :, :], side, axis=0)], axis=-1)
    return table.reshape(side * side, d // 2).astype(np.float32)


def pixtral_vision_forward(spec: PixtralVisionSpec, params, pixel_values,
                           cos, sin, block_mask):
    """pixel_values (B, C, H, W) same-size images; cos/sin (N, head_dim/2)
    rope angles for the flattened patch sequence of ONE image (tiled by the
    caller for B > 1 after flattening); block_mask (N, N) attend-within-image.
    Returns (B, patches_per_image, hidden)."""
    b, c, hh, ww = pixel_values.shape
    p = spec.patch_size
    gh, gw = hh // p, ww // p
    nh, hd = spec.num_heads, spec.head_dim
    # patch conv (stride == kernel) == linear over the flat patch
    x = pixel_values.reshape(b, c, gh, p, gw, p).transpose(0, 2, 4, 1, 3, 5)
    x = x.reshape(b, gh * gw, c * p * p) @ params["patch_proj"]
    x = rms_norm(x, params["ln_pre"], spec.eps)
    n = gh * gw

    def rope(t):                                       # (B, N, nh, hd)
        tf = t.astype(jnp.float32)
        d2 = cos.shape[-1]
        t1, t2 = tf[..., :d2], tf[..., d2:]
        cc, ss = cos[None, :, None, :], sin[None, :, None, :]
        return jnp.concatenate([t1 * cc - t2 * ss, t2 * cc + t1 * ss],
                               axis=-1).astype(t.dtype)

    def body(h, lw):
        r = rms_norm(h, lw["attn_norm"], spec.eps)
        q = rope((r @ lw["q"]).reshape(b, n, nh, hd))
        k = rope((r @ lw["k"]).reshape(b, n, nh, hd))
        v = (r @ lw["v"]).reshape(b, n, nh, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(block_mask[None, None], s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhqk,bkhd->bqhd", pr, v.astype(jnp.float32))
        h = h + a.reshape(b, n, -1).astype(h.dtype) @ lw["o"]
        r = rms_norm(h, lw["ffn_norm"], spec.eps)
        h = h + (jax.nn.silu(r @ lw["gate"]) * (r @ lw["up"])) @ lw["down"]
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def convert_pixtral_tower(sd: Dict[str, np.ndarray], spec: PixtralVisionSpec,
                          prefix: str) -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"transformer.layers.{i}"
        return {
            "attn_norm": get(f"{b}.attention_norm.weight"),
            "q": t(get(f"{b}.attention.q_proj.weight")),
            "k": t(get(f"{b}.attention.k_proj.weight")),
            "v": t(get(f"{b}.attention.v_proj.weight")),
            "o": t(get(f"{b}.attention.o_proj.weight")),
            "ffn_norm": get(f"{b}.ffn_norm.weight"),
            "gate": t(get(f"{b}.feed_forward.gate_proj.weight")),
            "up": t(get(f"{b}.feed_forward.up_proj.weight")),
            "down": t(get(f"{b}.feed_forward.down_proj.weight")),
        }

    layers = [lw(i) for i in range(spec.num_layers)]
    return {
        "patch_proj": t(get("patch_conv.weight").reshape(
            spec.hidden_size, -1)),
        "ln_pre": get("ln_pre.weight"),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
    }


class PixtralInferenceConfig(ImageToTextInferenceConfig):
    pass


class PixtralApplication(ImageToTextApplication):
    """Pixtral tower + mistral text (reference: models/pixtral/)."""

    def __init__(self, model_path: Optional[str],
                 config: PixtralInferenceConfig, mesh=None):
        # no super().__init__: the parent builds a CLIP tower; here only the
        # text app + projector plumbing are shared
        from ..application import CausalLMApplication
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        mesh=mesh)
        self.image_token_index = int(config.image_token_index)
        self.vision_params = None
        self.projector = None
        self._project = jax.jit(self._project_fn)
        self.pix_spec = pixtral_vision_spec(dict(config.vision_config))
        self._pix_fn = jax.jit(partial(pixtral_vision_forward, self.pix_spec))
        self._rope_table = pixtral_rope_table(self.pix_spec)
        self._image_hw = None   # (H, W) of the images served, set at encode

    def load_weights(self, model_path: Optional[str] = None):
        from ...utils import checkpoint as ckpt
        path = model_path or self.model_path
        sd = ckpt.load_state_dict(path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
                continue
            for pre, new in (("model.language_model.", "model."),
                             ("language_model.model.", "model."),
                             ("language_model.", "model.")):
                if k.startswith(pre):
                    text_sd[new + k[len(pre):]] = v
                    break
        host = self.text.family.convert_hf_state_dict(text_sd, self.text.spec)
        self.text._put_params(host)
        vis_prefix = ("model.vision_tower" if any(
            k.startswith("model.vision_tower") for k in sd)
            else "vision_tower")
        self.vision_params = jax.tree.map(
            jnp.asarray, convert_pixtral_tower(sd, self.pix_spec, vis_prefix))
        proj_prefix = ("model.multi_modal_projector" if any(
            k.startswith("model.multi_modal_projector") for k in sd)
            else "multi_modal_projector")

        def t(w):
            return jnp.asarray(np.ascontiguousarray(
                np.asarray(w, np.float32).T))

        self.projector = {
            "w1": t(sd[f"{proj_prefix}.linear_1.weight"]),
            "w2": t(sd[f"{proj_prefix}.linear_2.weight"]),
        }
        for nm, key in (("linear_1.bias", "b1"), ("linear_2.bias", "b2")):
            full = f"{proj_prefix}.{nm}"
            if full in sd:
                self.projector[key] = jnp.asarray(
                    np.asarray(sd[full], np.float32))
        return self

    def _project_fn(self, projector, feats):
        h = feats @ projector["w1"]
        if "b1" in projector:
            h = h + projector["b1"]
        h = jax.nn.gelu(h, approximate=False)
        h = h @ projector["w2"]
        if "b2" in projector:
            h = h + projector["b2"]
        return h

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        pv = np.asarray(pixel_values, np.float32)
        b, c, hh, ww = pv.shape
        p = self.pix_spec.patch_size
        gh, gw = hh // p, ww // p
        # rope angles for this grid via the (h*max_w + w) table
        pos = (np.arange(gh)[:, None] * self.pix_spec.max_side
               + np.arange(gw)[None, :]).ravel()
        ang = self._rope_table[pos]
        mask = np.ones((gh * gw, gh * gw), bool)   # one image per row: full
        feats = self._pix_fn(self.vision_params, jnp.asarray(pv),
                             jnp.asarray(np.cos(ang)),
                             jnp.asarray(np.sin(ang)), jnp.asarray(mask))
        self._image_hw = (gh, gw)
        return self._project(self.projector, feats)

    @property
    def tokens_per_image(self) -> int:
        if self._image_hw is None:
            raise RuntimeError("encode_images first")
        return self._image_hw[0] * self._image_hw[1]
