"""Llama4 (Scout/Maverick) family — text decoder with chunked attention +
NoPE layers + interleaved dense/MoE stacks, and the vision encoder
(reference: models/llama4/modeling_llama4_text.py :1-770,
modeling_llama4_vision.py :1-1214, modeling_llama4.py — SURVEY §2.7,
~2994 LoC; named in BASELINE.json).

All text deltas are DecoderSpec knobs (model_base.py), not a separate layer
implementation:
  * chunked attention on RoPE layers (``attn_chunk`` block-diagonal mask;
    reference: chunked-attention CTE, attention_base.py:916-948)
  * NoPE global layers every ``no_rope_layer_interval`` (``nope_global`` —
    identity rotation) with attention temperature tuning (``attn_temp``)
  * weightless L2 q/k norm after rope on rope layers (``qk_l2_norm``)
  * interleaved dense/MoE (``moe_pattern`` from HF ``moe_layers``) with
    llama4 routing: sigmoid(top-1 logit) scales the expert INPUT, plus an
    always-on shared expert (modules/moe.py ``input_scaled``)

The vision side is a functional ViT with 2-D rope + pixel-shuffle adapter +
multimodal projector feeding ``image_embeds`` into the shared multimodal
prefill merge (model_base.context_encoding_step image_embeds/image_mask).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config
from ...parallel.layers import place_q_weight, replicate_kv_weight


class Llama4InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "intermediate_size_mlp", "num_local_experts",
                "num_experts_per_tok"]

    def get_text_config(self):
        return self


@register_family("llama4_text", "llama4")
class Llama4Family(DecoderFamily):
    config_cls = Llama4InferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig,
                   tp_degree: Optional[int] = None) -> DecoderSpec:
        L = config.num_hidden_layers
        no_rope = getattr(config, "no_rope_layers", None)
        if not no_rope:
            interval = getattr(config, "no_rope_layer_interval", 4)
            no_rope = [int((i + 1) % interval != 0) for i in range(L)]
        # local (pattern True) = rope + chunked attention; global = NoPE full
        pattern = tuple(bool(r) for r in no_rope)
        moe_layers = getattr(config, "moe_layers", None)
        if moe_layers is None:
            step = getattr(config, "interleave_moe_layer_step", 1)
            moe_layers = list(range(step - 1, L, step))
        moe_set = set(int(i) for i in moe_layers)
        moe = MoESpec(
            num_experts=config.num_local_experts,
            top_k=config.num_experts_per_tok,
            intermediate_size=config.intermediate_size,
            normalize_topk=False,
            router_act="sigmoid",
            input_scaled=True,
            shared_intermediate=config.intermediate_size,
            act=getattr(config, "hidden_act", "silu"),
        ) if moe_set else None
        temp = ((float(getattr(config, "floor_scale", 8192)),
                 float(getattr(config, "attn_scale", 0.1)))
                if getattr(config, "attn_temperature_tuning", True) else None)
        chunk = int(getattr(config, "attention_chunk_size", 8192) or 0)
        return spec_from_config(
            config, tp_degree,
            intermediate_size=config.intermediate_size_mlp,
            layer_pattern=pattern,
            attn_chunk=chunk,
            nope_global=True,
            qk_l2_norm=bool(getattr(config, "use_qk_norm", True)),
            attn_temp=temp,
            rope_interleaved=True,
            moe=moe,
            moe_pattern=tuple(i in moe_set for i in range(L)) if moe_set
            else None,
            qkv_bias=bool(getattr(config, "attention_bias", False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray],
                              spec: DecoderSpec) -> Dict[str, Any]:
        """Two stacks: dense layers ("layers") and MoE layers ("moe_layers"),
        each in order of appearance (reference: llama4 conversion scripts,
        models/llama4/conversion_script/)."""
        p = cls.hf_prefix
        g, D = spec.gqa, spec.head_dim
        L = spec.num_layers
        pat = spec.moe_pattern or (False,) * L
        moe_ids = [i for i in range(L) if pat[i]]
        dense_ids = [i for i in range(L) if not pat[i]]

        def get(name):
            return np.asarray(sd[name])

        def q_t(w):
            return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

        def kv_t(w):
            return replicate_kv_weight(np.ascontiguousarray(w.T), g, D,
                                       axis=-1)

        def o_t(w):
            return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=0)

        def t(w):
            return np.ascontiguousarray(w.T)

        def stack(ids, fmt, tr):
            return np.stack([tr(get(fmt.format(i=i))) for i in ids])

        def attn_stack(ids):
            return {
                "input_norm": stack(ids, p + ".layers.{i}.input_layernorm.weight",
                                    np.asarray),
                "q_proj": stack(ids, p + ".layers.{i}.self_attn.q_proj.weight", q_t),
                "k_proj": stack(ids, p + ".layers.{i}.self_attn.k_proj.weight", kv_t),
                "v_proj": stack(ids, p + ".layers.{i}.self_attn.v_proj.weight", kv_t),
                "o_proj": stack(ids, p + ".layers.{i}.self_attn.o_proj.weight", o_t),
                "post_norm": stack(
                    ids, p + ".layers.{i}.post_attention_layernorm.weight",
                    np.asarray),
            }

        out: Dict[str, Any] = {
            "embed": _vpad(get(p + ".embed_tokens.weight"), spec.padded_vocab),
            "final_norm": get(p + ".norm.weight"),
        }
        if not spec.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(
                _vpad(get("lm_head.weight"), spec.padded_vocab).T)

        if dense_ids:
            dense = attn_stack(dense_ids)
            dense.update({
                "gate_proj": stack(dense_ids,
                                   p + ".layers.{i}.feed_forward.gate_proj.weight", t),
                "up_proj": stack(dense_ids,
                                 p + ".layers.{i}.feed_forward.up_proj.weight", t),
                "down_proj": stack(dense_ids,
                                   p + ".layers.{i}.feed_forward.down_proj.weight", t),
            })
            out["layers"] = dense

        if moe_ids:
            moe = attn_stack(moe_ids)
            # HF stores experts FUSED: gate_up_proj (E, H, 2I) and
            # down_proj (E, I, H) as parameters (already (in, out))
            gate_up = np.stack([get(
                p + f".layers.{i}.feed_forward.experts.gate_up_proj")
                for i in moe_ids])                        # (Lm, E, H, 2I)
            I = spec.moe.intermediate_size
            moe.update({
                "router": np.stack([t(get(
                    p + f".layers.{i}.feed_forward.router.weight")).astype(
                    np.float32) for i in moe_ids]),
                "expert_gate": np.ascontiguousarray(gate_up[..., :I]),
                "expert_up": np.ascontiguousarray(gate_up[..., I:]),
                "expert_down": np.stack([get(
                    p + f".layers.{i}.feed_forward.experts.down_proj")
                    for i in moe_ids]),
                "shared_gate": stack(
                    moe_ids,
                    p + ".layers.{i}.feed_forward.shared_expert.gate_proj.weight", t),
                "shared_up": stack(
                    moe_ids,
                    p + ".layers.{i}.feed_forward.shared_expert.up_proj.weight", t),
                "shared_down": stack(
                    moe_ids,
                    p + ".layers.{i}.feed_forward.shared_expert.down_proj.weight", t),
            })
            out["moe_layers"] = moe
        return out

    @classmethod
    def load_hf_model(cls, model_path: str):
        from transformers import Llama4ForCausalLM
        return Llama4ForCausalLM.from_pretrained(model_path)


def _vpad(w: np.ndarray, padded: int) -> np.ndarray:
    if w.shape[0] < padded:
        w = np.pad(w, [(0, padded - w.shape[0])] + [(0, 0)] * (w.ndim - 1))
    return w


# ---------------------------------------------------------------------------
# Vision tower (reference: models/llama4/modeling_llama4_vision.py, 1214 LoC
# — unfold-conv patch embed, 2-D rope over the patch grid + a zero-angle CLS
# slot appended LAST, pre/post LayerNorm ViT, pixel-shuffle adapter) and the
# multimodal projector feeding image_embeds into the shared prefill merge.
# ---------------------------------------------------------------------------

def llama4_vision_rope_tables(image_size: int, patch_size: int,
                              hidden: int, heads: int,
                              theta: float = 10000.0):
    """cos/sin (P+1, head_dim/2) for the 2-D vision rope (HF
    Llama4VisionRotaryEmbedding semantics: interleaved x/y frequency slots,
    angles zeroed on the CLS slot)."""
    idx = image_size // patch_size
    img_idx = np.arange(idx * idx, dtype=np.int32).reshape(-1, 1)
    img_idx = np.concatenate([img_idx, img_idx[:1]], axis=0)
    img_idx[-1, -1] = -2                      # CLS sentinel
    fx = img_idx % idx
    fy = img_idx // idx
    freq_dim = hidden // heads // 2
    rope_freq = 1.0 / (theta ** (np.arange(0, freq_dim, 2)[: freq_dim // 2]
                                 .astype(np.float32) / freq_dim))
    freqs_x = np.repeat((fx + 1)[..., None] * rope_freq[None, None, :], 2,
                        axis=-1)
    freqs_y = np.repeat((fy + 1)[..., None] * rope_freq[None, None, :], 2,
                        axis=-1)
    freqs = np.concatenate([freqs_x, freqs_y], axis=-1)[..., ::2]
    freqs = np.where(img_idx.reshape(-1, 1, 1) < 0, 0.0, freqs)[:, 0, :]
    return np.cos(freqs), np.sin(freqs)       # (P+1, head_dim/2)


def _vision_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
                 ) -> jnp.ndarray:
    """Interleaved-pair rotation (view_as_complex convention): x (B,N,H,D),
    cos/sin (N, D/2)."""
    xf = x.astype(jnp.float32)
    x0, x1 = xf[..., 0::2], xf[..., 1::2]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    out = jnp.stack([x0 * c - x1 * s, x0 * s + x1 * c], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _vis_ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def _pixel_shuffle(x: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """HF llama4 pixel_shuffle: (B, P, C) -> (B, P*r*r, C/(r*r))."""
    b, p, c = x.shape
    side = int(math.isqrt(p))
    x = x.reshape(b, side, side, c)
    x = x.reshape(b, side, int(side * ratio), int(c / ratio))
    x = jnp.transpose(x, (0, 2, 1, 3))
    x = x.reshape(b, int(side * ratio), int(side * ratio),
                  int(c / (ratio * ratio)))
    x = jnp.transpose(x, (0, 2, 1, 3))
    return x.reshape(b, -1, x.shape[-1])


def llama4_vision_forward(vcfg: Dict[str, Any], params: Dict[str, Any],
                          pixel_values: jnp.ndarray) -> jnp.ndarray:
    """pixel_values (B, C, H, W) -> per-image features
    (B, P*ratio^2, projector_output_dim) — HF Llama4VisionModel.forward."""
    b = pixel_values.shape[0]
    p = vcfg["patch_size"]
    hidden = vcfg["hidden_size"]
    # unfold-conv patch embed: (B,C,H,W) -> (B, P, C*p*p) @ W
    x = pixel_values.reshape(b, -1, vcfg["image_size"] // p, p,
                             vcfg["image_size"] // p, p)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(
        b, (vcfg["image_size"] // p) ** 2, -1)
    x = x @ params["patch_proj"]
    # CLS appended LAST (HF cat([patches, class_embedding]))
    cls = jnp.broadcast_to(params["class_embedding"][None, None, :],
                           (b, 1, hidden))
    x = jnp.concatenate([x, cls], axis=1)
    x = x + params["pos_embed"]
    x = _vis_ln(x, params["ln_pre_w"], params["ln_pre_b"])

    nh = vcfg["num_heads"]
    hd = hidden // nh
    cos, sin = params["rope_cos"], params["rope_sin"]

    def body(h, lw):
        r = _vis_ln(h, lw["ln1_w"], lw["ln1_b"])
        n = h.shape[1]
        q = (r @ lw["q"] + lw["q_b"]).reshape(b, n, nh, hd)
        k = (r @ lw["k"] + lw["k_b"]).reshape(b, n, nh, hd)
        v = (r @ lw["v"] + lw["v_b"]).reshape(b, n, nh, hd)
        q = _vision_rope(q, cos, sin)
        k = _vision_rope(k, cos, sin)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhqk,bkhd->bqhd", pr, v.astype(jnp.float32))
        h = h + (a.reshape(b, n, -1).astype(h.dtype) @ lw["o"] + lw["o_b"])
        r = _vis_ln(h, lw["ln2_w"], lw["ln2_b"])
        m = jax.nn.gelu(r @ lw["fc1"] + lw["fc1_b"], approximate=False)
        h = h + (m @ lw["fc2"] + lw["fc2_b"])
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _vis_ln(x, params["ln_post_w"], params["ln_post_b"])
    x = x[:, :-1, :]                          # drop CLS
    # pixel-shuffle adapter (Llama4VisionPixelShuffleMLP + MLP2: gelu after
    # BOTH projections)
    x = _pixel_shuffle(x, vcfg["pixel_shuffle_ratio"])
    x = jax.nn.gelu(x @ params["adapter_fc1"], approximate=False)
    x = jax.nn.gelu(x @ params["adapter_fc2"], approximate=False)
    return x


def convert_llama4_vision(sd: Dict[str, np.ndarray], vcfg: Dict[str, Any],
                          prefix: str = "vision_model") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    L = vcfg["num_layers"]

    def lw(i):
        b = f"model.layers.{i}"
        return {
            "ln1_w": get(f"{b}.input_layernorm.weight"),
            "ln1_b": get(f"{b}.input_layernorm.bias"),
            "ln2_w": get(f"{b}.post_attention_layernorm.weight"),
            "ln2_b": get(f"{b}.post_attention_layernorm.bias"),
            "q": t(get(f"{b}.self_attn.q_proj.weight")),
            "q_b": get(f"{b}.self_attn.q_proj.bias"),
            "k": t(get(f"{b}.self_attn.k_proj.weight")),
            "k_b": get(f"{b}.self_attn.k_proj.bias"),
            "v": t(get(f"{b}.self_attn.v_proj.weight")),
            "v_b": get(f"{b}.self_attn.v_proj.bias"),
            "o": t(get(f"{b}.self_attn.o_proj.weight")),
            "o_b": get(f"{b}.self_attn.o_proj.bias"),
            "fc1": t(get(f"{b}.mlp.fc1.weight")),
            "fc1_b": get(f"{b}.mlp.fc1.bias"),
            "fc2": t(get(f"{b}.mlp.fc2.weight")),
            "fc2_b": get(f"{b}.mlp.fc2.bias"),
        }

    layers = [lw(i) for i in range(L)]
    cos, sin = llama4_vision_rope_tables(
        vcfg["image_size"], vcfg["patch_size"], vcfg["hidden_size"],
        vcfg["num_heads"], vcfg.get("rope_theta", 10000.0))
    return {
        "patch_proj": t(get("patch_embedding.linear.weight")),
        "class_embedding": get("class_embedding"),
        "pos_embed": get("positional_embedding_vlm"),
        "ln_pre_w": get("layernorm_pre.weight"),
        "ln_pre_b": get("layernorm_pre.bias"),
        "ln_post_w": get("layernorm_post.weight"),
        "ln_post_b": get("layernorm_post.bias"),
        "adapter_fc1": t(get("vision_adapter.mlp.fc1.weight")),
        "adapter_fc2": t(get("vision_adapter.mlp.fc2.weight")),
        "rope_cos": np.asarray(cos, np.float32),
        "rope_sin": np.asarray(sin, np.float32),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
    }


class Llama4VLApplication:
    """Image-to-text llama4 (reference: Llama4ForConditionalGeneration /
    modeling_llama4.py + the image-to-text base,
    models/image_to_text_model_base.py): vision tower + linear projector +
    the shared multimodal prefill merge of CausalLMApplication."""

    def __init__(self, model_path: Optional[str], config, mesh=None):
        from ..application import CausalLMApplication
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        family=Llama4Family, mesh=mesh)
        self.image_token_index = int(getattr(config, "image_token_index",
                                             getattr(config, "image_token_id",
                                                     0)))
        vc = dict(config.vision_config)
        self.vcfg = {
            "image_size": int(vc["image_size"]),
            "patch_size": int(vc["patch_size"]),
            "hidden_size": int(vc["hidden_size"]),
            "num_heads": int(vc["num_attention_heads"]),
            "num_layers": int(vc["num_hidden_layers"]),
            "pixel_shuffle_ratio": float(vc.get("pixel_shuffle_ratio", 0.5)),
            "rope_theta": float(vc.get("rope_theta", 10000.0)),
        }
        self.vision_params = None
        self.projector = None
        self._vis_fn = jax.jit(partial(llama4_vision_forward, self.vcfg))

    def load_weights(self):
        from ...utils import checkpoint as ckpt
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
                continue
            for pre in ("model.language_model.", "language_model.model.",
                        "language_model."):
                if k.startswith(pre):
                    text_sd["model." + k[len(pre):]] = v
                    break
        host = Llama4Family.convert_hf_state_dict(text_sd, self.text.spec)
        self.text._put_params(host)
        vis_prefix = ("model.vision_model" if any(
            k.startswith("model.vision_model") for k in sd)
            else "vision_model")
        self.vision_params = jax.tree.map(
            jnp.asarray, convert_llama4_vision(sd, self.vcfg, vis_prefix))
        proj = ("model.multi_modal_projector" if any(
            k.startswith("model.multi_modal_projector") for k in sd)
            else "multi_modal_projector")
        self.projector = jnp.asarray(np.ascontiguousarray(
            np.asarray(sd[f"{proj}.linear_1.weight"], np.float32).T))
        self.text.init_cache()
        return self

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        """(N_img, C, H, W) -> (N_img, tokens_per_image, H_text)."""
        feats = self._vis_fn(self.vision_params,
                             jnp.asarray(pixel_values, jnp.float32))
        return feats @ self.projector

    def generate(self, input_ids: np.ndarray, pixel_values: np.ndarray,
                 max_new_tokens: int = 16, **kw):
        """input_ids contain image_token_index placeholders (one per image
        feature position, HF processor layout)."""
        input_ids = np.asarray(input_ids)
        feats = self.encode_images(pixel_values)
        n_img, tpi, hdim = feats.shape
        image_mask = input_ids == self.image_token_index
        embeds = feats.reshape(1, n_img * tpi, hdim)
        embeds = jnp.broadcast_to(embeds, (input_ids.shape[0],) + embeds.shape[1:])
        return self.text.generate(input_ids.astype(np.int32),
                                  image_embeds=embeds,
                                  image_mask=image_mask,
                                  max_new_tokens=max_new_tokens, **kw)
