from .modeling_llama4 import (Llama4Family, Llama4InferenceConfig,  # noqa: F401
                              Llama4VLApplication)
