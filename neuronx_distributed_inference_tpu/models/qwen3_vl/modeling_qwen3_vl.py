"""Qwen3-VL family (reference: models/qwen3_vl/ — 2318 LoC; SURVEY §2.7):
qwen3 text (per-head q/k RMSNorm) with INTERLEAVED M-RoPE, a ViT vision
tower with bilinearly-interpolated learned position embeddings, and
DEEPSTACK — visual features tapped at several vision depths, merged with
post-shuffle norms, and injected into the first K text layers' hidden
states at the image-token positions (reference: models/model_base.py:
1374-1387 deepstack embeds; vision side modeling_qwen3_vl.py).

The text stack runs entirely on the shared DecoderSpec machinery
(model_base.py deepstack/deepstack_mask threading); the vision tower is a
functional ViT in the qwen2_vl style."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig
from ...ops.normalization import layer_norm
from ..family import register_family
from ..qwen3.modeling_qwen3 import Qwen3Family, Qwen3InferenceConfig
from ..qwen2_vl.modeling_qwen2_vl import get_rope_index, vision_rot_angles


@dataclass(frozen=True)
class Qwen3VLVisionSpec:
    depth: int
    embed_dim: int
    num_heads: int
    mlp_hidden: int
    patch_input: int
    spatial_merge: int
    out_hidden: int
    num_pos: int                       # learned pos-embed table size
    deepstack_indexes: Tuple[int, ...]
    act: str = "gelu_pytorch_tanh"
    eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def grid_side(self) -> int:
        return int(self.num_pos ** 0.5)


def qwen3vl_vision_spec(vc: Dict[str, Any]) -> Qwen3VLVisionSpec:
    embed = int(vc["hidden_size"])
    return Qwen3VLVisionSpec(
        depth=int(vc["depth"]),
        embed_dim=embed,
        num_heads=int(vc["num_heads"]),
        mlp_hidden=int(vc.get("intermediate_size", embed * 4)),
        patch_input=(int(vc.get("in_channels", 3))
                     * int(vc.get("temporal_patch_size", 2))
                     * int(vc["patch_size"]) ** 2),
        spatial_merge=int(vc.get("spatial_merge_size", 2)),
        out_hidden=int(vc["out_hidden_size"]),
        num_pos=int(vc["num_position_embeddings"]),
        deepstack_indexes=tuple(int(i)
                                for i in vc["deepstack_visual_indexes"]),
        act=str(vc.get("hidden_act", "gelu_pytorch_tanh")),
    )


def interp_pos_embed(spec: Qwen3VLVisionSpec, table: np.ndarray,
                     grid_thw: np.ndarray) -> np.ndarray:
    """Bilinear interpolation of the learned pos-embed table onto each
    image's (h, w) grid, in the merge-block-permuted patch order (HF
    fast_pos_embed_interpolate parity)."""
    side = spec.grid_side
    m = spec.spatial_merge
    out = []
    for t, h, w in np.asarray(grid_thw):
        hi = np.linspace(0, side - 1, h)
        wi = np.linspace(0, side - 1, w)
        hf, wf = hi.astype(np.int64), wi.astype(np.int64)
        hc = np.clip(hf + 1, None, side - 1)
        wc = np.clip(wf + 1, None, side - 1)
        dh, dw = hi - hf, wi - wf
        e = (table[(hf[:, None] * side + wf[None, :])] *
             ((1 - dh)[:, None, None] * (1 - dw)[None, :, None])
             + table[(hf[:, None] * side + wc[None, :])] *
             ((1 - dh)[:, None, None] * dw[None, :, None])
             + table[(hc[:, None] * side + wf[None, :])] *
             (dh[:, None, None] * (1 - dw)[None, :, None])
             + table[(hc[:, None] * side + wc[None, :])] *
             (dh[:, None, None] * dw[None, :, None]))        # (h, w, E)
        e = np.tile(e.reshape(1, h, w, -1), (t, 1, 1, 1))
        # merge-block permutation (same order the processor emits patches)
        e = e.reshape(t, h // m, m, w // m, m, -1).transpose(0, 1, 3, 2, 4, 5)
        out.append(e.reshape(t * h * w, -1))
    return np.concatenate(out, axis=0).astype(np.float32)


_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "silu": jax.nn.silu,
}


def qwen3vl_vision_forward(spec: Qwen3VLVisionSpec, params: Dict[str, Any],
                           patches: jnp.ndarray, pos_embeds: jnp.ndarray,
                           cos: jnp.ndarray, sin: jnp.ndarray,
                           image_ids: jnp.ndarray
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """patches (N, patch_input); pos_embeds (N, E) interpolated; cos/sin
    (N, head_dim/2); image_ids (N,). Returns (merged (N/m^2, out_hidden),
    deepstack (K, N/m^2, out_hidden))."""
    n = patches.shape[0]
    nh, hd = spec.num_heads, spec.head_dim
    act = _ACTS.get(spec.act, _ACTS["gelu_pytorch_tanh"])
    x = patches @ params["patch_proj"] + params["patch_bias"]
    x = x + pos_embeds.astype(x.dtype)
    block_mask = (image_ids[:, None] == image_ids[None, :])

    def rope(t):
        tf = t.astype(jnp.float32)
        d2 = cos.shape[-1]
        t1, t2 = tf[..., :d2], tf[..., d2:]
        c, s = cos[:, None, :], sin[:, None, :]
        return jnp.concatenate([t1 * c - t2 * s, t2 * c + t1 * s],
                               axis=-1).astype(t.dtype)

    def block(h, lw):
        r = layer_norm(h, lw["ln1_w"], lw["ln1_b"], spec.eps)
        qkv = r @ lw["qkv_w"] + lw["qkv_b"]
        q, k, v = jnp.split(qkv.reshape(n, 3, nh, hd), 3, axis=1)
        q, k, v = rope(q[:, 0]), rope(k[:, 0]), v[:, 0]
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(block_mask[None], s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("hqk,khd->qhd", pr, v.astype(jnp.float32))
        h = h + (a.reshape(n, -1).astype(h.dtype) @ lw["proj_w"]
                 + lw["proj_b"])
        r = layer_norm(h, lw["ln2_w"], lw["ln2_b"], spec.eps)
        m = act(r @ lw["fc1_w"] + lw["fc1_b"])
        return h + (m @ lw["fc2_w"] + lw["fc2_b"])

    def merger(h, mw, postshuffle):
        m2 = spec.spatial_merge ** 2
        if postshuffle:
            h = h.reshape(n // m2, -1)
            h = layer_norm(h, mw["norm_w"], mw["norm_b"], spec.eps)
        else:
            h = layer_norm(h, mw["norm_w"], mw["norm_b"], spec.eps)
            h = h.reshape(n // m2, -1)
        h = jax.nn.gelu(h @ mw["fc1_w"] + mw["fc1_b"], approximate=False)
        return h @ mw["fc2_w"] + mw["fc2_b"]

    deepstack = []
    for i in range(spec.depth):
        lw = jax.tree.map(lambda a, i=i: a[i], params["layers"])
        x = block(x, lw)
        if i in spec.deepstack_indexes:
            k = spec.deepstack_indexes.index(i)
            mw = jax.tree.map(lambda a, k=k: a[k], params["deepstack_mergers"])
            deepstack.append(merger(x, mw, postshuffle=True))
    out = merger(x, params["merger"], postshuffle=False)
    return out, jnp.stack(deepstack)


def convert_qwen3vl_vision(sd: Dict[str, np.ndarray],
                           spec: Qwen3VLVisionSpec,
                           prefix: str = "visual") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"blocks.{i}"
        return {
            "ln1_w": get(f"{b}.norm1.weight"), "ln1_b": get(f"{b}.norm1.bias"),
            "qkv_w": t(get(f"{b}.attn.qkv.weight")),
            "qkv_b": get(f"{b}.attn.qkv.bias"),
            "proj_w": t(get(f"{b}.attn.proj.weight")),
            "proj_b": get(f"{b}.attn.proj.bias"),
            "ln2_w": get(f"{b}.norm2.weight"), "ln2_b": get(f"{b}.norm2.bias"),
            "fc1_w": t(get(f"{b}.mlp.linear_fc1.weight")),
            "fc1_b": get(f"{b}.mlp.linear_fc1.bias"),
            "fc2_w": t(get(f"{b}.mlp.linear_fc2.weight")),
            "fc2_b": get(f"{b}.mlp.linear_fc2.bias"),
        }

    def merger(base):
        return {
            "norm_w": get(f"{base}.norm.weight"),
            "norm_b": get(f"{base}.norm.bias"),
            "fc1_w": t(get(f"{base}.linear_fc1.weight")),
            "fc1_b": get(f"{base}.linear_fc1.bias"),
            "fc2_w": t(get(f"{base}.linear_fc2.weight")),
            "fc2_b": get(f"{base}.linear_fc2.bias"),
        }

    layers = [lw(i) for i in range(spec.depth)]
    mergers = [merger(f"deepstack_merger_list.{k}")
               for k in range(len(spec.deepstack_indexes))]
    return {
        "patch_proj": t(get("patch_embed.proj.weight").reshape(
            spec.embed_dim, -1)),
        "patch_bias": get("patch_embed.proj.bias"),
        "pos_table": get("pos_embed.weight"),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
        "merger": merger("merger"),
        "deepstack_mergers": {k: np.stack([d[k] for d in mergers])
                              for k in mergers[0]},
    }


class Qwen3VLInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_id"]

    def get_text_config(self) -> InferenceConfig:
        tc = dict(self.text_config)
        tc.setdefault("model_type", "qwen3")
        return Qwen3VLTextConfig(self.tpu_config, **tc)


class Qwen3VLTextConfig(Qwen3InferenceConfig):
    pass


@register_family("qwen3_vl_text")
class Qwen3VLTextFamily(Qwen3Family):
    """Text decoder = qwen3 + interleaved mrope (set via rope_scaling)."""
    config_cls = Qwen3VLTextConfig


class Qwen3VLApplication:
    """Vision tower + deepstack + interleaved-M-RoPE text LM (reference:
    models/qwen3_vl/ model set)."""

    family = Qwen3VLTextFamily

    def __init__(self, model_path: Optional[str],
                 config: Qwen3VLInferenceConfig, mesh=None):
        from ..application import CausalLMApplication
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        Qwen3VLTextFamily, mesh=mesh)
        assert self.text.spec.rope.mrope_interleaved or \
            self.text.spec.rope.mrope_section is None
        self.vision_spec = qwen3vl_vision_spec(dict(config.vision_config))
        self.image_token_id = int(config.image_token_id)
        self.spatial_merge = self.vision_spec.spatial_merge
        self.vision_params = None
        self._vis_fn = jax.jit(partial(qwen3vl_vision_forward,
                                       self.vision_spec))

    def load_weights(self):
        from ...utils import checkpoint as ckpt
        sd = ckpt.load_state_dict(self.model_path)
        remap = {}
        for k, v in sd.items():
            k2 = k.replace("model.language_model.", "model.")
            k2 = k2.replace("model.visual.", "visual.")
            remap[k2] = v
        host = self.family.convert_hf_state_dict(remap, self.text.spec)
        self.text._put_params(host)
        self.vision_params = jax.tree.map(
            jnp.asarray, convert_qwen3vl_vision(remap, self.vision_spec))
        self._pos_table = np.asarray(self.vision_params["pos_table"])
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def encode_images(self, pixel_patches: np.ndarray, grid_thw: np.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(N, patch_input) + (n_imgs, 3) -> (merged (N/m^2, H_text),
        deepstack (K, N/m^2, H_text))."""
        ang = vision_rot_angles(grid_thw, self.vision_spec)
        pos = interp_pos_embed(self.vision_spec, self._pos_table, grid_thw)
        ids = np.repeat(np.arange(len(grid_thw)),
                        [int(t * h * w) for t, h, w in np.asarray(grid_thw)])
        return self._vis_fn(self.vision_params, jnp.asarray(pixel_patches),
                            jnp.asarray(pos),
                            jnp.asarray(np.cos(ang)), jnp.asarray(np.sin(ang)),
                            jnp.asarray(ids))

    def generate(self, input_ids: np.ndarray,
                 pixel_patches: Optional[np.ndarray] = None,
                 image_grid_thw: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32, **kw) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        image_embeds = image_mask = deepstack = None
        rope_pos = decode_start = None
        if pixel_patches is not None:
            feats, ds = self.encode_images(pixel_patches, image_grid_thw)
            image_mask = input_ids == self.image_token_id
            per_row = image_mask.sum(axis=1)
            if not (per_row == per_row[0]).all():
                raise ValueError("rows must hold equal image-token counts")
            image_embeds = np.asarray(feats).reshape(b, per_row[0], -1)
            deepstack = np.asarray(ds).reshape(ds.shape[0], b, per_row[0], -1)
            rope_pos, decode_start = get_rope_index(
                input_ids, image_grid_thw, self.image_token_id,
                self.spatial_merge, attention_mask)
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens, image_embeds=image_embeds,
            image_mask=image_mask, deepstack_embeds=deepstack,
            rope_position_ids=rope_pos, decode_rope_start=decode_start, **kw)

    def reset(self):
        self.text.reset()
        return self
