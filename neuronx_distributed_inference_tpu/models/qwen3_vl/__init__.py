from .modeling_qwen3_vl import (Qwen3VLApplication,  # noqa: F401
                                Qwen3VLInferenceConfig)
