"""Qwen3 family (reference: models/qwen3/modeling_qwen3.py
``NeuronQwen3ForCausalLM``). Llama-shaped with per-head Q/K RMSNorm and an
explicit head_dim decoupled from hidden_size/num_heads."""

from __future__ import annotations

from typing import List, Optional

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class Qwen3InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "head_dim"]


@register_family("qwen3")
class Qwen3Family(DecoderFamily):
    config_cls = Qwen3InferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        return spec_from_config(config, tp_degree, qk_norm=True)
