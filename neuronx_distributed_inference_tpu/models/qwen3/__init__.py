"""qwen3 family."""
from .modeling_qwen3 import *  # noqa: F401,F403
