"""Contrib model hub, wave 2 (reference: contrib/models/ — SURVEY §2.7).
Each family is a thin DecoderSpec mapping + checkpoint conversion over the
shared layer machinery, like wave 1 (contrib.py).

Families: gptj, gpt_neo, gpt_bigcode, opt, xglm, biogpt, helium, ernie4_5,
seed_oss, arcee, nemotron, smollm3, cohere2 (command-r7b), exaone4,
hunyuan_v1_dense, granitemoe."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..config import InferenceConfig
from .contrib import (_SimpleConfig, _ident, _split_interleaved_qkv, _t, _vpad, _vpad1)
from .family import DecoderFamily, register_family
from .model_base import DecoderSpec, spec_from_config
from ..modules.moe import MoESpec
from ..parallel.layers import place_q_weight, replicate_kv_weight


# ---------------------------------------------------------------------------
# GPT-J (reference: contrib/models/gpt-j)
# ---------------------------------------------------------------------------

@register_family("gptj")
class GPTJFamily(DecoderFamily):
    """Parallel-shared residual (single ln_1), partial INTERLEAVED rotary
    (rotate_every_two), plain gelu MLP, biased untied lm_head."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.n_embd
        nh = config.n_head
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layer,
            hidden_size=H, num_q_heads=nh, num_kv_heads=nh,
            head_dim=H // nh,
            intermediate_size=getattr(config, "n_inner", None) or 4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act=getattr(config, "activation_function", "gelu_new"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            rotary_dim=int(getattr(config, "rotary_dim", None)
                           or (H // nh)),
            rope_interleaved=True,
            block_style="parallel_shared",
            lm_head_bias=True,
            tie_word_embeddings=False,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        L, H = spec.num_layers, spec.hidden_size
        layers = {
            "input_norm": stack(p + ".h.{i}.ln_1.weight", _ident),
            "input_norm_b": stack(p + ".h.{i}.ln_1.bias", _ident),
            # parallel_shared: post_norm unused
            "post_norm": np.ones((L, H), np.float32),
            "post_norm_b": np.zeros((L, H), np.float32),
            "q_proj": stack(p + ".h.{i}.attn.q_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=-1)),
            "k_proj": stack(p + ".h.{i}.attn.k_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "v_proj": stack(p + ".h.{i}.attn.v_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "o_proj": stack(p + ".h.{i}.attn.out_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "gate_proj": stack(p + ".h.{i}.mlp.fc_in.weight", _t),
            "gate_bias": stack(p + ".h.{i}.mlp.fc_in.bias", _ident),
            "down_proj": stack(p + ".h.{i}.mlp.fc_out.weight", _t),
            "down_bias": stack(p + ".h.{i}.mlp.fc_out.bias", _ident),
        }
        layers["qkv_proj"] = np.concatenate(
            [layers.pop("q_proj"), layers.pop("k_proj"),
             layers.pop("v_proj")], axis=-1)
        return {
            "embed": _vpad(get(p + ".wte.weight"), spec.padded_vocab),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
            "lm_head": _t(_vpad(get("lm_head.weight"), spec.padded_vocab)),
            "lm_head_b": _vpad1(get("lm_head.bias"), spec.padded_vocab),
        }


# ---------------------------------------------------------------------------
# GPT-Neo (reference: contrib/models/gpt-neo)
# ---------------------------------------------------------------------------

@register_family("gpt_neo")
class GPTNeoFamily(DecoderFamily):
    """Alternating global/local (sliding-window) attention, learned
    positions, no rope, plain gelu MLP, LN+bias. Attention projections have
    no bias; output projection does."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_heads
        pattern = tuple(t == "local" for t in config.attention_layers)
        return spec_from_config(
            config, tp_degree,
            num_layers=config.num_layers,
            hidden_size=H, num_q_heads=nh, num_kv_heads=nh,
            head_dim=H // nh,
            intermediate_size=getattr(config, "intermediate_size", None)
            or 4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act=getattr(config, "activation_function", "gelu_new"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            o_bias=True,
            no_rope=True,
            learned_pos=int(getattr(config, "max_position_embeddings", 2048)),
            layer_pattern=pattern if any(pattern) else None,
            sliding_window=int(getattr(config, "window_size", 256)),
            # gpt-neo attention has NO 1/sqrt(d) scaling
            attn_scale=1.0,
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        a = ".attn.attention"
        layers = {
            "input_norm": stack(p + ".h.{i}.ln_1.weight", _ident),
            "input_norm_b": stack(p + ".h.{i}.ln_1.bias", _ident),
            "post_norm": stack(p + ".h.{i}.ln_2.weight", _ident),
            "post_norm_b": stack(p + ".h.{i}.ln_2.bias", _ident),
            "q_proj": stack(p + ".h.{i}" + a + ".q_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=-1)),
            "k_proj": stack(p + ".h.{i}" + a + ".k_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "v_proj": stack(p + ".h.{i}" + a + ".v_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "o_proj": stack(p + ".h.{i}" + a + ".out_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(p + ".h.{i}" + a + ".out_proj.bias", _ident),
            "gate_proj": stack(p + ".h.{i}.mlp.c_fc.weight", _t),
            "gate_bias": stack(p + ".h.{i}.mlp.c_fc.bias", _ident),
            "down_proj": stack(p + ".h.{i}.mlp.c_proj.weight", _t),
            "down_bias": stack(p + ".h.{i}.mlp.c_proj.bias", _ident),
        }
        layers["qkv_proj"] = np.concatenate(
            [layers.pop("q_proj"), layers.pop("k_proj"),
             layers.pop("v_proj")], axis=-1)
        return {
            "embed": _vpad(get(p + ".wte.weight"), spec.padded_vocab),
            "pos_embed": get(p + ".wpe.weight"),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
        }


# ---------------------------------------------------------------------------
# GPT-BigCode / StarCoder v1 (reference: contrib/models/gpt_bigcode)
# ---------------------------------------------------------------------------

@register_family("gpt_bigcode")
class GPTBigCodeFamily(DecoderFamily):
    """Multi-query attention (1 kv head) with a fused c_attn, learned
    positions, plain gelu MLP, LN+bias."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.n_embd
        nh = config.n_head
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layer,
            hidden_size=H, num_q_heads=nh,
            num_kv_heads=1 if getattr(config, "multi_query", True) else nh,
            head_dim=H // nh,
            intermediate_size=getattr(config, "n_inner", None) or 4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act=getattr(config, "activation_function", "gelu_pytorch_tanh"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            no_rope=True,
            learned_pos=int(getattr(config, "n_positions", 2048)),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        H = spec.hidden_size
        kvd = spec.num_kv_heads * D
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        qs, ks, vs, qb, kb, vb = [], [], [], [], [], []
        for i in range(spec.num_layers):
            w = get(f"{p}.h.{i}.attn.c_attn.weight")     # (H+2*kvd, H)
            b = get(f"{p}.h.{i}.attn.c_attn.bias")
            qs.append(place_q_weight(_t(w[:H]), g, D, axis=-1))
            ks.append(replicate_kv_weight(_t(w[H:H + kvd]), g, D, axis=-1))
            vs.append(replicate_kv_weight(_t(w[H + kvd:]), g, D, axis=-1))
            qb.append(place_q_weight(b[:H], g, D))
            kb.append(replicate_kv_weight(b[H:H + kvd], g, D))
            vb.append(replicate_kv_weight(b[H + kvd:], g, D))
        layers = {
            "input_norm": stack(p + ".h.{i}.ln_1.weight", _ident),
            "input_norm_b": stack(p + ".h.{i}.ln_1.bias", _ident),
            "post_norm": stack(p + ".h.{i}.ln_2.weight", _ident),
            "post_norm_b": stack(p + ".h.{i}.ln_2.bias", _ident),
            "qkv_proj": np.concatenate(
                [np.stack(qs), np.stack(ks), np.stack(vs)], axis=-1),
            "qkv_bias": np.concatenate(
                [np.stack(qb), np.stack(kb), np.stack(vb)], axis=-1),
            "o_proj": stack(p + ".h.{i}.attn.c_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(p + ".h.{i}.attn.c_proj.bias", _ident),
            "gate_proj": stack(p + ".h.{i}.mlp.c_fc.weight", _t),
            "gate_bias": stack(p + ".h.{i}.mlp.c_fc.bias", _ident),
            "down_proj": stack(p + ".h.{i}.mlp.c_proj.weight", _t),
            "down_bias": stack(p + ".h.{i}.mlp.c_proj.bias", _ident),
        }
        return {
            "embed": _vpad(get(p + ".wte.weight"), spec.padded_vocab),
            "pos_embed": get(p + ".wpe.weight"),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
        }


# ---------------------------------------------------------------------------
# OPT / BioGPT / XGLM — fairseq-style decoders (learned/sinusoidal positions
# with a +2 offset, pre-LN, biased projections)
# ---------------------------------------------------------------------------

class _FairseqStyleFamily(DecoderFamily):
    """Shared conversion for OPT-shaped decoders: self_attn.{q,k,v,out}_proj
    (+bias), fc1/fc2, self_attn_layer_norm / final_layer_norm per layer.
    Position table handling differs per family (offset-2 learned table for
    OPT/BioGPT, synthesized sinusoidal for XGLM)."""
    config_cls = _SimpleConfig
    layers_fmt = "model.decoder.layers.{i}"

    @classmethod
    def _convert_layers(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        f = cls.layers_fmt
        layers = {
            "input_norm": stack(f + ".self_attn_layer_norm.weight", _ident),
            "input_norm_b": stack(f + ".self_attn_layer_norm.bias", _ident),
            "post_norm": stack(f + ".final_layer_norm.weight", _ident),
            "post_norm_b": stack(f + ".final_layer_norm.bias", _ident),
            "q_proj": stack(f + ".self_attn.q_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=-1)),
            "k_proj": stack(f + ".self_attn.k_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "v_proj": stack(f + ".self_attn.v_proj.weight",
                            lambda w: replicate_kv_weight(_t(w), g, D,
                                                          axis=-1)),
            "o_proj": stack(f + ".self_attn.out_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(f + ".self_attn.out_proj.bias", _ident),
            "gate_proj": stack(f + ".fc1.weight", _t),
            "gate_bias": stack(f + ".fc1.bias", _ident),
            "down_proj": stack(f + ".fc2.weight", _t),
            "down_bias": stack(f + ".fc2.bias", _ident),
            "q_bias": stack(f + ".self_attn.q_proj.bias",
                            lambda b: place_q_weight(b, g, D)),
            "k_bias": stack(f + ".self_attn.k_proj.bias",
                            lambda b: replicate_kv_weight(b, g, D)),
            "v_bias": stack(f + ".self_attn.v_proj.bias",
                            lambda b: replicate_kv_weight(b, g, D)),
        }
        layers["qkv_proj"] = np.concatenate(
            [layers.pop("q_proj"), layers.pop("k_proj"),
             layers.pop("v_proj")], axis=-1)
        layers["qkv_bias"] = np.concatenate(
            [layers.pop("q_bias"), layers.pop("k_bias"),
             layers.pop("v_bias")], axis=-1)
        return layers


@register_family("opt")
class OPTFamily(_FairseqStyleFamily):
    @classmethod
    def build_spec(cls, config, tp_degree=None):
        if getattr(config, "word_embed_proj_dim",
                   config.hidden_size) != config.hidden_size:
            raise NotImplementedError(
                "OPT word_embed_proj_dim != hidden_size (350m-style "
                "embedding projections) is not supported")
        if not getattr(config, "do_layer_norm_before", True):
            raise NotImplementedError("OPT post-norm variant not supported")
        return spec_from_config(
            config, tp_degree,
            num_kv_heads=config.num_attention_heads,
            rms_eps=1e-5,
            act=getattr(config, "activation_function", "relu"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            intermediate_size=config.ffn_dim,
            no_rope=True,
            learned_pos=int(config.max_position_embeddings),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        layers = cls._convert_layers(sd, spec)
        return {
            "embed": _vpad(np.asarray(sd["model.decoder.embed_tokens.weight"]),
                           spec.padded_vocab),
            # OPT's learned position table is indexed position+2
            "pos_embed": np.asarray(
                sd["model.decoder.embed_positions.weight"])[2:],
            "layers": layers,
            "final_norm": np.asarray(
                sd["model.decoder.final_layer_norm.weight"]),
            "final_norm_b": np.asarray(
                sd["model.decoder.final_layer_norm.bias"]),
        }


@register_family("biogpt")
class BioGptFamily(_FairseqStyleFamily):
    layers_fmt = "biogpt.layers.{i}"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        return spec_from_config(
            config, tp_degree,
            num_kv_heads=config.num_attention_heads,
            rms_eps=1e-5,
            act=getattr(config, "hidden_act", "gelu"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            intermediate_size=config.intermediate_size,
            no_rope=True,
            embed_scale=(math.sqrt(H)
                         if getattr(config, "scale_embedding", True)
                         else None),
            learned_pos=int(config.max_position_embeddings),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        layers = cls._convert_layers(sd, spec)
        return {
            "embed": _vpad(np.asarray(sd["biogpt.embed_tokens.weight"]),
                           spec.padded_vocab),
            "pos_embed": np.asarray(sd["biogpt.embed_positions.weight"])[2:],
            "layers": layers,
            "final_norm": np.asarray(sd["biogpt.layer_norm.weight"]),
            "final_norm_b": np.asarray(sd["biogpt.layer_norm.bias"]),
        }


def _sinusoidal_table(n_pos: int, dim: int, padding_idx: int = 1
                      ) -> np.ndarray:
    """fairseq/XGLM sinusoidal position table ([sin | cos], padding row
    zeroed) — XGLM registers it as a non-persistent buffer, so the
    checkpoint may not carry it."""
    half = dim // 2
    emb = math.log(10000.0) / (half - 1)
    freqs = np.exp(np.arange(half, dtype=np.float64) * -emb)
    args = np.arange(n_pos, dtype=np.float64)[:, None] * freqs[None, :]
    table = np.concatenate([np.sin(args), np.cos(args)], axis=1)
    if dim % 2 == 1:
        table = np.pad(table, [(0, 0), (0, 1)])
    table[padding_idx] = 0.0
    return table.astype(np.float32)


@register_family("xglm")
class XGLMFamily(_FairseqStyleFamily):
    layers_fmt = "model.layers.{i}"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.d_model
        return spec_from_config(
            config, tp_degree,
            hidden_size=H,
            num_q_heads=config.attention_heads,
            num_kv_heads=config.attention_heads,
            head_dim=H // config.attention_heads,
            num_layers=config.num_layers,
            intermediate_size=config.ffn_dim,
            rms_eps=1e-5,
            act=getattr(config, "activation_function", "gelu"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            no_rope=True,
            embed_scale=(math.sqrt(H)
                         if getattr(config, "scale_embedding", True)
                         else None),
            learned_pos=int(config.max_position_embeddings),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        layers = cls._convert_layers(sd, spec)
        key = "model.embed_positions.weights"
        if key in sd:
            table = np.asarray(sd[key])[2:]
        else:
            table = _sinusoidal_table(spec.learned_pos + 2,
                                      spec.hidden_size)[2:]
        return {
            "embed": _vpad(np.asarray(sd["model.embed_tokens.weight"]),
                           spec.padded_vocab),
            "pos_embed": table,
            "layers": layers,
            "final_norm": np.asarray(sd["model.layer_norm.weight"]),
            "final_norm_b": np.asarray(sd["model.layer_norm.bias"]),
        }


# ---------------------------------------------------------------------------
# Llama-shaped quick wins
# ---------------------------------------------------------------------------

@register_family("helium")
class HeliumFamily(DecoderFamily):
    """kyutai Helium — llama-shaped (rms, rope, bias-free GLU)."""
    config_cls = _SimpleConfig


@register_family("ernie4_5")
class Ernie45Family(DecoderFamily):
    """Baidu ERNIE 4.5 dense — llama-shaped."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        # Ernie4_5Config serializes its tie_word_embeddings=True default as
        # null; None must mean tied here
        tie = getattr(config, "tie_word_embeddings", None)
        return spec_from_config(config, tp_degree,
                                tie_word_embeddings=tie is not False)


@register_family("seed_oss")
class SeedOssFamily(DecoderFamily):
    """ByteDance Seed-OSS — llama + attention biases + explicit head_dim."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        bias = bool(getattr(config, "attention_bias", True))
        return spec_from_config(config, tp_degree, qkv_bias=bias,
                                o_bias=bias)

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        if spec.o_bias and "model.layers.0.self_attn.o_proj.bias" not in sd:
            # seed-oss ships q/k/v biases but a bias-free o_proj
            sd = dict(sd)
            for i in range(spec.num_layers):
                sd[f"model.layers.{i}.self_attn.o_proj.bias"] = np.zeros(
                    (spec.hidden_size,), np.float32)
        return super().convert_hf_state_dict(sd, spec)


@register_family("arcee")
class ArceeFamily(DecoderFamily):
    """Arcee AFM — llama attention + plain ReLU^2 MLP (up/down, no gate)."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            mlp_glu=False,
            act=getattr(config, "hidden_act", "relu2"),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.up_proj.weight",
                                     _t),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.down_proj.weight",
                                     _t),
        }


@register_family("nemotron")
class NemotronFamily(DecoderFamily):
    """NVIDIA Nemotron — LayerNorm1P (zero-centered gamma, folded to w+1 at
    conversion), partial rotary, plain ReLU^2 MLP."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = getattr(config, "head_dim", None) or H // nh
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            rms_eps=float(getattr(config, "norm_eps", 1e-5)),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False,
            mlp_bias=bool(getattr(config, "mlp_bias", False)),
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=bool(getattr(config, "attention_bias", False)),
            act=getattr(config, "hidden_act", "relu2"),
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.5)),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        out = {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.up_proj.weight",
                                     _t),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.down_proj.weight",
                                     _t),
        }
        if spec.mlp_bias:
            out["gate_bias"] = layer_stack(
                p + ".layers.{i}.mlp.up_proj.bias", _ident)
            out["down_bias"] = layer_stack(
                p + ".layers.{i}.mlp.down_proj.bias", _ident)
        return out

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix

        def plus1(w):   # LayerNorm1P: norm uses (1 + gamma)
            return np.asarray(w) + 1.0

        return {
            "input_norm": layer_stack(
                p + ".layers.{i}.input_layernorm.weight", plus1),
            "input_norm_b": layer_stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.weight", plus1),
            "post_norm_b": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.bias", _ident),
        }

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        out = super().convert_hf_state_dict(sd, spec)
        out["final_norm"] = np.asarray(sd["model.norm.weight"]) + 1.0
        out["final_norm_b"] = np.asarray(sd["model.norm.bias"])
        return out


@register_family("smollm3")
class SmolLM3Family(DecoderFamily):
    """SmolLM3 — llama + NoPE on every no_rope_layers[i]==0 layer."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        rope_on = [bool(x) for x in getattr(config, "no_rope_layers", [])]
        pattern = tuple(rope_on) if rope_on and not all(rope_on) else None
        # SmolLM3Config serializes its tie_word_embeddings=True default as
        # null; None must mean tied here
        tie = getattr(config, "tie_word_embeddings", None)
        return spec_from_config(
            config, tp_degree,
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            # pattern: "local" layers keep rope; global layers are NoPE
            layer_pattern=pattern,
            nope_global=pattern is not None,
            tie_word_embeddings=tie is not False,
        )


@register_family("cohere2")
class Cohere2Family(DecoderFamily):
    """Command-R7B — cohere v1 (parallel-shared residual, bias-free
    LayerNorm, logit scaling, tied embeddings) + alternating sliding/global
    layers where the global layers are NoPE."""
    config_cls = _SimpleConfig
    post_norm_src = "input_layernorm"   # parallel_shared: post_norm unused

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        scale = float(getattr(config, "logit_scale", 1.0))
        types = list(getattr(config, "layer_types", []) or [])
        pattern = tuple(t == "sliding_attention" for t in types)
        return spec_from_config(
            config, tp_degree,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            norm_type="layernorm",
            block_style="parallel_shared",
            logits_divide=1.0 / scale if scale else None,
            layer_pattern=pattern if any(pattern) else None,
            sliding_window=int(getattr(config, "sliding_window", 0) or 0),
            nope_global=any(pattern),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        L, H = spec.num_layers, spec.hidden_size
        return {"post_norm": np.ones((L, H), np.float32)}


@register_family("exaone4")
class Exaone4Family(DecoderFamily):
    """EXAONE 4.0 — POST-norm blocks (norms on the outputs, olmo2-style),
    per-head q/k RMSNorm, optional hybrid sliding/global layers with NoPE
    global layers."""
    config_cls = _SimpleConfig
    post_norm_src = "post_attention_layernorm"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        types = list(getattr(config, "layer_types", []) or [])
        pattern = tuple(t == "sliding_attention" for t in types)
        hybrid = any(pattern)
        return spec_from_config(
            config, tp_degree,
            norm_position="post",
            sandwich_norm=True,
            qk_norm=True,
            layer_pattern=pattern if hybrid else None,
            sliding_window=int(getattr(config, "sliding_window", 0) or 0)
            if hybrid else 0,
            nope_global=hybrid,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        aug = dict(sd)
        H = spec.hidden_size
        for i in range(spec.num_layers):   # unused pre-norm slots load ones
            aug[f"model.layers.{i}.input_layernorm.weight"] = np.ones(
                (H,), np.float32)
        return super().convert_hf_state_dict(aug, spec)

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "post_attn_norm": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.weight", _ident),
            "post_ff_norm": layer_stack(
                p + ".layers.{i}.post_feedforward_layernorm.weight", _ident),
        }


@register_family("hunyuan_v1_dense")
class HunYuanDenseFamily(DecoderFamily):
    """Tencent HunYuan dense — llama + per-head q/k RMSNorm applied AFTER
    rope (query/key_layernorm)."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            qk_norm=True, qk_norm_after_rope=True,
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=bool(getattr(config, "attention_bias", False)),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        sd = dict(sd)
        # the base converter's qk_norm branch reads q_norm/k_norm names;
        # alias hunyuan's query/key_layernorm onto them
        for i in range(spec.num_layers):
            sd[f"model.layers.{i}.self_attn.q_norm.weight"] = np.asarray(
                sd[f"model.layers.{i}.self_attn.query_layernorm.weight"])
            sd[f"model.layers.{i}.self_attn.k_norm.weight"] = np.asarray(
                sd[f"model.layers.{i}.self_attn.key_layernorm.weight"])
        return super().convert_hf_state_dict(sd, spec)


# ---------------------------------------------------------------------------
# GraniteMoE (reference: contrib MoE families)
# ---------------------------------------------------------------------------

@register_family("granitemoe")
class GraniteMoeFamily(DecoderFamily):
    """IBM Granite MoE — granite multipliers + MoE MLP with fused
    input_linear (gate|up stacked per expert)."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            attn_scale=float(getattr(config, "attention_multiplier", 1.0)),
            embed_scale=float(getattr(config, "embedding_multiplier", 1.0)),
            residual_multiplier=float(getattr(config, "residual_multiplier",
                                              1.0)),
            logits_divide=float(getattr(config, "logits_scaling", 1.0)),
            moe=MoESpec(
                num_experts=int(config.num_local_experts),
                top_k=int(config.num_experts_per_tok),
                intermediate_size=int(config.intermediate_size),
                # granitemoe gating: top-k on raw logits, softmax over the k
                pre_softmax_topk=True,
            ),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        L, E = spec.num_layers, spec.moe.num_experts
        I = spec.moe.intermediate_size
        p = cls.hf_prefix
        gates, ups, downs, routers = [], [], [], []
        for i in range(L):
            w_in = np.asarray(get(
                f"{p}.layers.{i}.block_sparse_moe.input_linear.weight"))
            w_out = np.asarray(get(
                f"{p}.layers.{i}.block_sparse_moe.output_linear.weight"))
            # input_linear (E, 2I, H): rows [0:I] gate, [I:2I] up
            gates.append(np.stack([_t(w_in[e, :I]) for e in range(E)]))
            ups.append(np.stack([_t(w_in[e, I:]) for e in range(E)]))
            downs.append(np.stack([_t(w_out[e]) for e in range(E)]))
            routers.append(_t(np.asarray(get(
                f"{p}.layers.{i}.block_sparse_moe.router.layer.weight"))
                .astype(np.float32)))
        return {
            "router": np.stack(routers),
            "expert_gate": np.stack(gates),
            "expert_up": np.stack(ups),
            "expert_down": np.stack(downs),
        }


# ---------------------------------------------------------------------------
# OLMoE (reference: contrib MoE families)
# ---------------------------------------------------------------------------

@register_family("olmoe")
class OlmoeFamily(DecoderFamily):
    """AllenAI OLMoE — llama attention + full-width q/k RMSNorm (olmo2
    style) + softmax-all-then-topk MoE without renormalization."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            qk_norm_full=True,
            moe=MoESpec(
                num_experts=int(config.num_experts),
                top_k=int(config.num_experts_per_tok),
                intermediate_size=int(config.intermediate_size),
                normalize_topk=bool(getattr(config, "norm_topk_prob",
                                            False)),
            ),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return cls.convert_moe_weights(
            get, spec,
            router_name=p + ".layers.{i}.mlp.gate.weight",
            expert_fmt=p + ".layers.{i}.mlp.experts.{e}.{name}.weight",
            gate="gate_proj", up="up_proj", down="down_proj")

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        g, D = spec.gqa, spec.head_dim
        p = cls.hf_prefix
        return {
            "q_norm": layer_stack(
                p + ".layers.{i}.self_attn.q_norm.weight",
                lambda w: place_q_weight(np.asarray(w), g, D)),
            "k_norm": layer_stack(
                p + ".layers.{i}.self_attn.k_norm.weight",
                lambda w: replicate_kv_weight(np.asarray(w), g, D)),
        }


# ---------------------------------------------------------------------------
# GLM-4.5 / GLM-4-MoE (reference: contrib MoE families)
# ---------------------------------------------------------------------------

@register_family("glm4_moe")
class Glm4MoeFamily(DecoderFamily):
    """Zhipu GLM-4-MoE — GQA attention (partial rotary, optional per-head
    qk-norm, qkv bias) + DeepSeek-V3-style MoE: sigmoid router with
    e_score_correction_bias (selection only), shared experts, leading
    dense layers."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = getattr(config, "head_dim", None) or H // nh
        moe = MoESpec(
            num_experts=int(config.n_routed_experts),
            top_k=int(config.num_experts_per_tok),
            intermediate_size=int(config.moe_intermediate_size),
            normalize_topk=bool(getattr(config, "norm_topk_prob", True)),
            routed_scaling=float(getattr(config, "routed_scaling_factor",
                                         1.0)),
            router_act="sigmoid",
            has_router_bias=True,
            router_bias_mode="select",
            shared_intermediate=(int(config.moe_intermediate_size)
                                 * int(getattr(config, "n_shared_experts",
                                               0) or 0)),
            n_group=int(getattr(config, "n_group", 1) or 1),
            topk_group=int(getattr(config, "topk_group", 1) or 1),
        )
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            moe=moe,
            first_dense=int(getattr(config, "first_k_dense_replace", 0)),
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            qk_norm=bool(getattr(config, "use_qk_norm", False)),
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.5)),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        p = cls.hf_prefix
        L = spec.num_layers
        nd = spec.first_dense

        def get(n):
            return np.asarray(sd[n])

        def attn_layer(i):
            base = f"{p}.layers.{i}.self_attn"
            out = {
                "input_norm": _ident(get(
                    f"{p}.layers.{i}.input_layernorm.weight")),
                "post_norm": _ident(get(
                    f"{p}.layers.{i}.post_attention_layernorm.weight")),
                "q_proj": place_q_weight(_t(get(f"{base}.q_proj.weight")),
                                         g, D, axis=-1),
                "k_proj": replicate_kv_weight(
                    _t(get(f"{base}.k_proj.weight")), g, D, axis=-1),
                "v_proj": replicate_kv_weight(
                    _t(get(f"{base}.v_proj.weight")), g, D, axis=-1),
                "o_proj": place_q_weight(_t(get(f"{base}.o_proj.weight")),
                                         g, D, axis=0),
            }
            if spec.qkv_bias:
                out["q_bias"] = place_q_weight(get(f"{base}.q_proj.bias"),
                                               g, D)
                out["k_bias"] = replicate_kv_weight(
                    get(f"{base}.k_proj.bias"), g, D)
                out["v_bias"] = replicate_kv_weight(
                    get(f"{base}.v_proj.bias"), g, D)
            if spec.qk_norm:
                out["q_norm"] = _ident(get(f"{base}.q_norm.weight"))
                out["k_norm"] = _ident(get(f"{base}.k_norm.weight"))
            return out

        def dense_layer(i):
            out = attn_layer(i)
            for k in ("gate_proj", "up_proj", "down_proj"):
                out[k] = _t(get(f"{p}.layers.{i}.mlp.{k}.weight"))
            return out

        def moe_layer(i):
            from .deepseek.modeling_deepseek import deepseek_style_moe_weights
            out = attn_layer(i)
            out.update(deepseek_style_moe_weights(get, p, i, spec, _t))
            return out

        def stack_dicts(dicts):
            return {k: np.stack([d[k] for d in dicts]) for k in dicts[0]}

        out = {
            "embed": _vpad(get(p + ".embed_tokens.weight"),
                           spec.padded_vocab),
            "final_norm": _ident(get(p + ".norm.weight")),
        }
        if nd > 0:
            out["layers"] = stack_dicts([dense_layer(i) for i in range(nd)])
            out["moe_layers"] = stack_dicts([moe_layer(i)
                                             for i in range(nd, L)])
        else:
            out["layers"] = stack_dicts([moe_layer(i) for i in range(L)])
        if not spec.tie_word_embeddings:
            out["lm_head"] = _t(_vpad(get("lm_head.weight"),
                                      spec.padded_vocab))
        return out


# ---------------------------------------------------------------------------
# BLOOM / MPT — ALiBi decoders (no rope, additive per-head position bias)
# ---------------------------------------------------------------------------

@register_family("bloom")
class BloomFamily(DecoderFamily):
    """BigScience BLOOM — ALiBi, per-head-interleaved fused QKV, embedding
    LayerNorm, plain gelu MLP, LN+bias."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.n_head
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layer,
            hidden_size=H, num_q_heads=nh, num_kv_heads=nh,
            head_dim=H // nh,
            intermediate_size=4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act="gelu_pytorch_tanh",    # bloom_gelu_forward is the tanh form
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            no_rope=True, alibi=True, embed_norm=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        nh = spec.num_q_heads
        p = cls.hf_prefix
        from ..ops.attention import alibi_slopes

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        fused = _split_interleaved_qkv(
            get, p + ".h.{i}.self_attention.query_key_value",
            spec.num_layers, nh, g, D)
        slopes = place_q_weight(alibi_slopes(nh, "bloom"), g, 1)
        layers = {
            "input_norm": stack(p + ".h.{i}.input_layernorm.weight", _ident),
            "input_norm_b": stack(p + ".h.{i}.input_layernorm.bias", _ident),
            "post_norm": stack(
                p + ".h.{i}.post_attention_layernorm.weight", _ident),
            "post_norm_b": stack(
                p + ".h.{i}.post_attention_layernorm.bias", _ident),
            **fused,
            "o_proj": stack(p + ".h.{i}.self_attention.dense.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(p + ".h.{i}.self_attention.dense.bias", _ident),
            "gate_proj": stack(p + ".h.{i}.mlp.dense_h_to_4h.weight", _t),
            "gate_bias": stack(p + ".h.{i}.mlp.dense_h_to_4h.bias", _ident),
            "down_proj": stack(p + ".h.{i}.mlp.dense_4h_to_h.weight", _t),
            "down_bias": stack(p + ".h.{i}.mlp.dense_4h_to_h.bias", _ident),
            "alibi_slopes": np.broadcast_to(
                slopes, (spec.num_layers,) + slopes.shape).copy(),
        }
        return {
            "embed": _vpad(get(p + ".word_embeddings.weight"),
                           spec.padded_vocab),
            "embed_norm": get(p + ".word_embeddings_layernorm.weight"),
            "embed_norm_b": get(p + ".word_embeddings_layernorm.bias"),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
        }


@register_family("mpt")
class MptFamily(DecoderFamily):
    """MosaicML MPT — ALiBi, fused third-split Wqkv, bias-free everything,
    plain gelu MLP."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.d_model
        nh = config.n_heads
        ac = getattr(config, "attn_config", None) or {}
        if not isinstance(ac, dict):      # MptConfig object vs raw JSON dict
            ac = {k: getattr(ac, k) for k in
                  ("alibi", "alibi_bias_max", "qk_ln", "clip_qkv")
                  if hasattr(ac, k)}
        if not ac.get("alibi", True):
            raise NotImplementedError("MPT without ALiBi (learned "
                                      "positions) is not supported")
        if ac.get("alibi_bias_max", 8) != 8:
            raise NotImplementedError("MPT alibi_bias_max != 8")
        if not getattr(config, "no_bias", True):
            raise NotImplementedError("MPT with biases is not supported")
        if ac.get("qk_ln", False) or ac.get("clip_qkv", None):
            raise NotImplementedError("MPT qk_ln / clip_qkv variants")
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layers,
            hidden_size=H, num_q_heads=nh, num_kv_heads=nh,
            head_dim=H // nh,
            intermediate_size=int(H * getattr(config, "expansion_ratio", 4)),
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act="gelu",
            norm_type="layernorm", norm_bias=False,
            mlp_glu=False, mlp_bias=False,
            no_rope=True, alibi=True,
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        H = spec.hidden_size
        p = cls.hf_prefix
        from ..ops.attention import alibi_slopes

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        qs, ks, vs = [], [], []
        for i in range(spec.num_layers):
            w = get(f"{p}.blocks.{i}.attn.Wqkv.weight")    # (3H, H) thirds
            qs.append(place_q_weight(_t(w[:H]), g, D, axis=-1))
            ks.append(replicate_kv_weight(_t(w[H:2 * H]), g, D, axis=-1))
            vs.append(replicate_kv_weight(_t(w[2 * H:]), g, D, axis=-1))
        slopes = place_q_weight(alibi_slopes(spec.num_q_heads, "mpt"), g, 1)
        layers = {
            "input_norm": stack(p + ".blocks.{i}.norm_1.weight", _ident),
            "post_norm": stack(p + ".blocks.{i}.norm_2.weight", _ident),
            "qkv_proj": np.concatenate(
                [np.stack(qs), np.stack(ks), np.stack(vs)], axis=-1),
            "o_proj": stack(p + ".blocks.{i}.attn.out_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "gate_proj": stack(p + ".blocks.{i}.ffn.up_proj.weight", _t),
            "down_proj": stack(p + ".blocks.{i}.ffn.down_proj.weight", _t),
            "alibi_slopes": np.broadcast_to(
                slopes, (spec.num_layers,) + slopes.shape).copy(),
        }
        return {
            "embed": _vpad(get(p + ".wte.weight"), spec.padded_vocab),
            "layers": layers,
            "final_norm": get(p + ".norm_f.weight"),
        }


# ---------------------------------------------------------------------------
# Persimmon (reference: contrib/models/persimmon)
# ---------------------------------------------------------------------------

@register_family("persimmon")
class PersimmonFamily(DecoderFamily):
    """Adept Persimmon — per-head-interleaved fused QKV with bias, per-head
    q/k LayerNorm (with bias), partial rotary, ReLU^2 MLP, LN+bias."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = H // nh
        return spec_from_config(
            config, tp_degree,
            num_kv_heads=nh,
            head_dim=hd,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            act=getattr(config, "hidden_act", "relu2"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            qk_norm=bool(getattr(config, "qk_layernorm", True)),
            qk_norm_type="layernorm",
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.5)),
            tie_word_embeddings=False,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        nh = spec.num_q_heads
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        fused = _split_interleaved_qkv(
            get, p + ".layers.{i}.self_attn.query_key_value",
            spec.num_layers, nh, g, D)
        layers = {
            "input_norm": stack(
                p + ".layers.{i}.input_layernorm.weight", _ident),
            "input_norm_b": stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm": stack(
                p + ".layers.{i}.post_attention_layernorm.weight", _ident),
            "post_norm_b": stack(
                p + ".layers.{i}.post_attention_layernorm.bias", _ident),
            **fused,
            "o_proj": stack(p + ".layers.{i}.self_attn.dense.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(p + ".layers.{i}.self_attn.dense.bias", _ident),
            "gate_proj": stack(
                p + ".layers.{i}.mlp.dense_h_to_4h.weight", _t),
            "gate_bias": stack(
                p + ".layers.{i}.mlp.dense_h_to_4h.bias", _ident),
            "down_proj": stack(
                p + ".layers.{i}.mlp.dense_4h_to_h.weight", _t),
            "down_bias": stack(
                p + ".layers.{i}.mlp.dense_4h_to_h.bias", _ident),
        }
        if spec.qk_norm:
            layers["q_norm"] = stack(
                p + ".layers.{i}.self_attn.q_layernorm.weight", _ident)
            layers["q_norm_b"] = stack(
                p + ".layers.{i}.self_attn.q_layernorm.bias", _ident)
            layers["k_norm"] = stack(
                p + ".layers.{i}.self_attn.k_layernorm.weight", _ident)
            layers["k_norm_b"] = stack(
                p + ".layers.{i}.self_attn.k_layernorm.bias", _ident)
        return {
            "embed": _vpad(get(p + ".embed_tokens.weight"),
                           spec.padded_vocab),
            "layers": layers,
            "final_norm": get(p + ".final_layernorm.weight"),
            "final_norm_b": get(p + ".final_layernorm.bias"),
            "lm_head": _t(_vpad(get("lm_head.weight"), spec.padded_vocab)),
        }


# ---------------------------------------------------------------------------
# dots.llm1 (rednote) — GLM-4-MoE-shaped with full rotary + per-head qk RMS
# ---------------------------------------------------------------------------

@register_family("dots1")
class Dots1Family(Glm4MoeFamily):
    """rednote dots.llm1 — DeepSeek-V3-style MoE (sigmoid router +
    e_score_correction_bias, shared experts, leading dense layers) with
    standard-GQA attention, FULL rotary and per-head q/k RMSNorm."""

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = getattr(config, "head_dim", None) or H // nh
        moe = MoESpec(
            num_experts=int(config.n_routed_experts),
            top_k=int(config.num_experts_per_tok),
            intermediate_size=int(config.moe_intermediate_size),
            normalize_topk=bool(getattr(config, "norm_topk_prob", True)),
            routed_scaling=float(getattr(config, "routed_scaling_factor",
                                         1.0)),
            router_act="sigmoid",
            has_router_bias=True,
            router_bias_mode="select",
            shared_intermediate=(int(config.moe_intermediate_size)
                                 * int(getattr(config, "n_shared_experts",
                                               0) or 0)),
            n_group=int(getattr(config, "n_group", 1) or 1),
            topk_group=int(getattr(config, "topk_group", 1) or 1),
        )
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            moe=moe,
            first_dense=int(getattr(config, "first_k_dense_replace", 0)),
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            qk_norm=True,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )


# ---------------------------------------------------------------------------
# CodeGen (Salesforce) — GPT-J sibling with mp_num-blocked fused QKV
# ---------------------------------------------------------------------------

@register_family("codegen")
class CodeGenFamily(GPTJFamily):
    """CodeGen — GPT-J architecture (parallel-shared residual, interleaved
    partial rotary, gelu MLP, biased untied lm_head) with the fused
    qkv_proj laid out as mp_num=4 blocks of [q | v | k] head groups."""

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        # de-block the mp_num=4 fused qkv into the synthetic per-projection
        # names GPT-J uses, then delegate to its converter
        nh = spec.num_q_heads
        D = spec.head_dim
        p = cls.hf_prefix
        mp_num = 4
        local = nh * D // mp_num
        sd = dict(sd)
        for i in range(spec.num_layers):
            w = np.asarray(sd[f"{p}.h.{i}.attn.qkv_proj.weight"])
            w = w.reshape(mp_num, 3 * local, -1)
            sd[f"{p}.h.{i}.attn.q_proj.weight"] = \
                w[:, :local].reshape(nh * D, -1)
            sd[f"{p}.h.{i}.attn.v_proj.weight"] = \
                w[:, local:2 * local].reshape(nh * D, -1)
            sd[f"{p}.h.{i}.attn.k_proj.weight"] = \
                w[:, 2 * local:].reshape(nh * D, -1)
        return super().convert_hf_state_dict(sd, spec)
