"""Gemma3 multimodal — SigLIP tower + avg-pool projector + bidirectional
image-span attention on the gemma3 text stack (reference:
contrib/models/gemma3-vision; HF Gemma3ForConditionalGeneration).

TPU mapping: the SigLIP encoder rides the shared ViT base
(models/vision.py — patch-bias + post-layernorm flags), the projector is
rms-norm → 2-D average pool → a single (C_vis, H_text) matmul, and the
image-block bidirectional attention is an in-graph mask overlay on the
prefill masks (model_base.context_encoding_step, spec.bidir_image_attn) —
no reference to HF's vmapped mask closures."""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..ops.normalization import rms_norm
from ..utils import checkpoint as ckpt
from . import vision
from .application import CausalLMApplication
from .family import register_family
from .gemma3.modeling_gemma3 import Gemma3Family


class Gemma3VLInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "mm_tokens_per_image"]

    def get_text_config(self) -> InferenceConfig:
        tc = dict(self.text_config)
        tc.setdefault("model_type", "gemma3_text")
        return Gemma3VLTextFamily.config_cls(self.tpu_config, **tc)


@register_family("gemma3_vl_text")
class Gemma3VLTextFamily(Gemma3Family):
    """gemma3 text + the bidirectional image-span attention overlay."""

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        from dataclasses import replace
        return replace(super().build_spec(config, tp_degree),
                       bidir_image_attn=True)


class Gemma3VLApplication:
    """SigLIP tower + projector + gemma3 text LM."""

    def __init__(self, model_path: Optional[str],
                 config: Gemma3VLInferenceConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        Gemma3VLTextFamily, mesh=mesh)
        vc = dict(config.vision_config)
        self.vit_spec = vision.vit_spec_from_hf(vc, feature_layer=-1)
        # SigLIP: no CLS, no pre-LN, biased patch conv, final post-LN
        from dataclasses import replace
        self.vit_spec = replace(
            self.vit_spec, use_cls_token=False, pre_layernorm=False,
            patch_bias=True, post_layernorm=True,
            act=vc.get("hidden_act", "gelu_pytorch_tanh"))
        self.image_token_id = int(
            getattr(config, "image_token_index",
                    getattr(config, "image_token_id", 262144)))
        self.mm_tokens = int(config.mm_tokens_per_image)
        self.vision_params = None
        self.projector = None
        self._vit = jax.jit(partial(vision.vit_forward, self.vit_spec))
        self._project = jax.jit(self._project_fn)

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
                continue
            for pre, new in (("model.language_model.", "model."),
                             ("language_model.model.", "model."),
                             ("language_model.", "model.")):
                if k.startswith(pre):
                    text_sd[new + k[len(pre):]] = v
                    break
        host = self.text.family.convert_hf_state_dict(text_sd,
                                                      self.text.spec)
        self.text._put_params(host)

        vis_prefix = ("model.vision_tower" if any(
            k.startswith("model.vision_tower") for k in sd)
            else "vision_tower")
        self.vision_params = jax.tree.map(
            jnp.asarray,
            vision.convert_clip_vision_tower(sd, self.vit_spec, vis_prefix))
        pp = ("model.multi_modal_projector" if any(
            k.startswith("model.multi_modal_projector") for k in sd)
            else "multi_modal_projector")
        self.projector = {
            "mm_w": jnp.asarray(np.asarray(
                sd[f"{pp}.mm_input_projection_weight"], np.float32)),
            "norm_w": jnp.asarray(np.asarray(
                sd[f"{pp}.mm_soft_emb_norm.weight"], np.float32)),
        }
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def _project_fn(self, projector, feats):
        """(B, P, C) SigLIP features -> (B, mm_tokens, H_text): avg-pool the
        patch grid to tokens_per_side^2, gemma (1+w) rms-norm, project
        (reference: HF Gemma3MultiModalProjector.forward)."""
        b, p, c = feats.shape
        side = int(math.isqrt(p))
        tside = int(math.isqrt(self.mm_tokens))
        k = side // tside
        x = feats.reshape(b, side, side, c)
        x = x.reshape(b, tside, k, tside, k, c).mean(axis=(2, 4))
        x = x.reshape(b, tside * tside, c)
        x = rms_norm(x, projector["norm_w"],
                     float(dict(self.config.vision_config).get(
                         "layer_norm_eps", 1e-6)), offset=1.0)
        return x @ projector["mm_w"]

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        feats = self._vit(self.vision_params, jnp.asarray(pixel_values))
        return self._project(self.projector, feats)

    def generate(self, input_ids: np.ndarray, pixel_values: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32, **kw) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        image_mask = (input_ids == self.image_token_id)
        feats = np.asarray(self.encode_images(pixel_values))
        per_row = image_mask.sum(axis=1)
        if not (per_row == per_row[0]).all():
            raise ValueError("rows must hold equal image-token counts")
        n_feat = feats.shape[0] * feats.shape[1]
        if n_feat != b * per_row[0]:
            raise ValueError(
                f"prompt holds {per_row[0]} image tokens per row "
                f"({b * per_row[0]} total over batch {b}) but the projector "
                f"emitted {n_feat} mm tokens (check mm_tokens_per_image vs "
                "the prompt's image-token span)")
        image_embeds = feats.reshape(b, per_row[0], -1)
        if self.text.cache is None:
            self.text.init_cache()
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens,
            image_embeds=image_embeds, image_mask=image_mask, **kw)

    def reset(self):
        self.text.reset()
        return self
