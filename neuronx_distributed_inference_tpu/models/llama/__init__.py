"""Llama model family."""
from .modeling_llama import LlamaFamily, LlamaInferenceConfig, TpuLlamaForCausalLM  # noqa: F401
