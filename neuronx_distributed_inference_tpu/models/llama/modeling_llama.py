"""Llama family (2 / 3.x) — the flagship
(reference: models/llama/modeling_llama.py ``NeuronLlamaForCausalLM``:1192).

Llama3.1 scaled RoPE (reference :805) is handled generically by
ops/rope.py's "llama3" scaling type, selected from the HF rope_scaling dict.
"""

from __future__ import annotations

from typing import List

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family


class LlamaInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size",
                "intermediate_size", "rms_norm_eps"]


@register_family("llama")
class LlamaFamily(DecoderFamily):
    config_cls = LlamaInferenceConfig


# Application-level alias matching the reference entry-class naming.
def TpuLlamaForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, LlamaFamily)
