"""Qwen2-VL family — M-RoPE text decoder + windowless ViT vision tower
(reference: models/qwen2_vl/ — modeling_qwen2_vl_text.py M-RoPE attention
:52-136, modeling_qwen2_vl_vision.py vision tower, rotary_position_ids
plumbing models/model_base.py:566-578; 1350 LoC).

TPU design:
  * Text side: the standard qwen2 DecoderSpec with ``rope.mrope_section``
    set; 3-axis rope positions flow through the ``rope_position_ids``
    graph input (ops/rope.py M-RoPE slot selection).
  * Vision side: a functional patch-transformer — patch-linear embed
    (= HF's stride-equal Conv3d), 2-D rotary over (h, w) patch coordinates,
    full bidirectional attention per image (block mask from patch→image
    ids), and the 2x2 spatial PatchMerger. Runs as one jitted call over all
    images' patches.
  * get_rope_index (host): faithful numpy port of the HF 3-axis position
    walk for image inputs.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...config import InferenceConfig, TpuConfig
from ...ops.normalization import layer_norm
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config
from ..qwen2.modeling_qwen2 import Qwen2Family, Qwen2InferenceConfig


# ---------------------------------------------------------------------------
# Vision tower
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Qwen2VLVisionSpec:
    depth: int
    embed_dim: int
    num_heads: int
    mlp_hidden: int
    patch_input: int          # in_channels * temporal_patch * patch * patch
    spatial_merge: int
    out_hidden: int
    act: str = "quick_gelu"
    eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def vision_spec_from_hf(vc: Dict[str, Any]) -> Qwen2VLVisionSpec:
    embed = int(vc.get("embed_dim", vc.get("hidden_size")))
    return Qwen2VLVisionSpec(
        depth=int(vc["depth"]),
        embed_dim=embed,
        num_heads=int(vc["num_heads"]),
        mlp_hidden=int(embed * float(vc.get("mlp_ratio", 4.0))),
        patch_input=(int(vc.get("in_channels", vc.get("in_chans", 3)))
                     * int(vc.get("temporal_patch_size", 2))
                     * int(vc["patch_size"]) ** 2),
        spatial_merge=int(vc.get("spatial_merge_size", 2)),
        out_hidden=int(vc["hidden_size"]),
        act=str(vc.get("hidden_act", "quick_gelu")),
    )


_V_ACTS = {
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "silu": jax.nn.silu,
}


def vision_forward(spec: Qwen2VLVisionSpec, params: Dict[str, Any],
                   patches: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
                   image_ids: jnp.ndarray) -> jnp.ndarray:
    """patches (N, patch_input); cos/sin (N, head_dim/2) precomputed 2-D
    rotary angles; image_ids (N,) patch->image id (attention stays within an
    image — HF's cu_seqlens block mask). Returns merged features
    (N / merge^2, out_hidden)."""
    n = patches.shape[0]
    nh, hd = spec.num_heads, spec.head_dim
    act = _V_ACTS[spec.act]
    x = patches @ params["patch_proj"]                      # (N, E)
    block_mask = (image_ids[:, None] == image_ids[None, :])  # (N, N)

    def rope2d(t):                                          # t (N, nh, hd)
        tf = t.astype(jnp.float32)
        d2 = cos.shape[-1]
        t1, t2 = tf[..., :d2], tf[..., d2:]
        c, s = cos[:, None, :], sin[:, None, :]
        return jnp.concatenate([t1 * c - t2 * s, t2 * c + t1 * s],
                               axis=-1).astype(t.dtype)

    def body(h, lw):
        r = layer_norm(h, lw["ln1_w"], lw["ln1_b"], spec.eps)
        qkv = r @ lw["qkv_w"] + lw["qkv_b"]                 # (N, 3E)
        q, k, v = jnp.split(qkv.reshape(n, 3, nh, hd), 3, axis=1)
        q = rope2d(q[:, 0])
        k = rope2d(k[:, 0])
        v = v[:, 0]
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(block_mask[None], s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("hqk,khd->qhd", pr, v.astype(jnp.float32))
        h = h + (a.reshape(n, -1).astype(h.dtype) @ lw["proj_w"]
                 + lw["proj_b"])
        r = layer_norm(h, lw["ln2_w"], lw["ln2_b"], spec.eps)
        m = act(r @ lw["fc1_w"] + lw["fc1_b"])
        h = h + m @ lw["fc2_w"] + lw["fc2_b"]
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    # PatchMerger: LN then group merge^2 spatially-adjacent patches (the
    # rot_pos_emb permutation makes them contiguous) through a 2-layer MLP
    x = layer_norm(x, params["ln_q_w"], params["ln_q_b"], spec.eps)
    x = x.reshape(n // spec.spatial_merge ** 2, -1)
    x = jax.nn.gelu(x @ params["mlp0_w"] + params["mlp0_b"],
                    approximate=False)
    return x @ params["mlp2_w"] + params["mlp2_b"]


def convert_vision_tower(sd: Dict[str, np.ndarray], spec: Qwen2VLVisionSpec,
                         prefix: str = "visual") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"blocks.{i}"
        return {
            "ln1_w": get(f"{b}.norm1.weight"), "ln1_b": get(f"{b}.norm1.bias"),
            "qkv_w": t(get(f"{b}.attn.qkv.weight")),
            "qkv_b": get(f"{b}.attn.qkv.bias"),
            "proj_w": t(get(f"{b}.attn.proj.weight")),
            "proj_b": get(f"{b}.attn.proj.bias"),
            "ln2_w": get(f"{b}.norm2.weight"), "ln2_b": get(f"{b}.norm2.bias"),
            "fc1_w": t(get(f"{b}.mlp.fc1.weight")),
            "fc1_b": get(f"{b}.mlp.fc1.bias"),
            "fc2_w": t(get(f"{b}.mlp.fc2.weight")),
            "fc2_b": get(f"{b}.mlp.fc2.bias"),
        }

    layers = [lw(i) for i in range(spec.depth)]
    return {
        # Conv3d with stride == kernel == one flat linear over the patch
        "patch_proj": t(get("patch_embed.proj.weight").reshape(
            spec.embed_dim, -1)),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
        "ln_q_w": get("merger.ln_q.weight"), "ln_q_b": get("merger.ln_q.bias"),
        "mlp0_w": t(get("merger.mlp.0.weight")),
        "mlp0_b": get("merger.mlp.0.bias"),
        "mlp2_w": t(get("merger.mlp.2.weight")),
        "mlp2_b": get("merger.mlp.2.bias"),
    }


def vision_rot_angles(grid_thw: np.ndarray, spec: Qwen2VLVisionSpec
                      ) -> np.ndarray:
    """Per-patch (h, w) rotary angles in HF's merge-group-permuted patch
    order (reference: modeling_qwen2_vl_vision.py ``rot_pos_emb``).
    Returns (N, head_dim/2) fp32 angles (first half h-freqs, second half w)."""
    m = spec.spatial_merge
    dim = spec.head_dim // 2          # rotary dim (h + w halves)
    inv = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float32) / dim))
    out = []
    for t, h, w in np.asarray(grid_thw):
        hp = np.arange(h)[:, None] * np.ones((1, w), np.int64)
        wp = np.ones((h, 1), np.int64) * np.arange(w)[None, :]

        def perm(x):
            return x.reshape(h // m, m, w // m, m).transpose(0, 2, 1, 3).ravel()

        hh, ww = perm(hp), perm(wp)
        ang = np.concatenate([hh[:, None] * inv[None, :],
                              ww[:, None] * inv[None, :]], axis=1)
        out.append(np.tile(ang, (t, 1)))
    return np.concatenate(out, axis=0).astype(np.float32)


# ---------------------------------------------------------------------------
# Host M-RoPE index computation (reference: HF get_rope_index semantics,
# plumbed as rotary_position_ids in the reference runtime)
# ---------------------------------------------------------------------------

def get_rope_index(input_ids: np.ndarray, image_grid_thw: np.ndarray,
                   image_token_id: int, spatial_merge: int,
                   attention_mask: Optional[np.ndarray] = None
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """3-axis positions for image+text prompts.

    Returns (positions (B, S, 3), decode_start (B, 3)) — text tokens count
    sequentially on all axes; an image span holds t constant and counts its
    (h, w) grid; the next text position resumes at max+1."""
    ids = np.asarray(input_ids)
    b, s = ids.shape
    if attention_mask is None:
        attention_mask = np.ones_like(ids)
    pos = np.zeros((b, s, 3), np.int64)
    decode_start = np.zeros((b, 3), np.int64)
    img_idx = 0
    for i in range(b):
        row = ids[i][attention_mask[i] == 1]
        out: List[np.ndarray] = []
        st = 0          # index into row
        st_pos = 0      # next sequential position value
        while st < len(row):
            if row[st] == image_token_id:
                t, h, w = (int(x) for x in image_grid_thw[img_idx])
                lh, lw_ = h // spatial_merge, w // spatial_merge
                n = t * lh * lw_
                ti = np.repeat(np.arange(t), lh * lw_) * 0 + st_pos
                hi = np.tile(np.repeat(np.arange(lh), lw_), t) + st_pos
                wi = np.tile(np.arange(lw_), t * lh) + st_pos
                out.append(np.stack([ti, hi, wi], axis=1))
                st += n
                st_pos = st_pos + max(t, lh, lw_)
                img_idx += 1
            else:
                ed = st
                while ed < len(row) and row[ed] != image_token_id:
                    ed += 1
                n = ed - st
                seq = np.arange(n) + st_pos
                out.append(np.stack([seq] * 3, axis=1))
                st = ed
                st_pos += n
        full = np.concatenate(out, axis=0)
        pos[i, :len(full)] = full
        decode_start[i] = full.max() + 1
    return pos.astype(np.int32), decode_start.astype(np.int32)


# ---------------------------------------------------------------------------
# Config + family + application
# ---------------------------------------------------------------------------

class Qwen2VLInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_id"]

    def get_text_config(self) -> InferenceConfig:
        tc = dict(self.text_config)
        tc.setdefault("model_type", "qwen2")
        return Qwen2VLTextConfig(self.tpu_config, **tc)


class Qwen2VLTextConfig(Qwen2InferenceConfig):
    pass


@register_family("qwen2_vl_text")
class Qwen2VLTextFamily(Qwen2Family):
    """Text decoder = qwen2 + mrope sections (set via rope_scaling)."""
    config_cls = Qwen2VLTextConfig


class Qwen2VLApplication:
    """Vision tower + M-RoPE text LM (reference: the qwen2_vl model set —
    text wrapper modeling_qwen2_vl_text.py:189-339 + vision tower)."""

    family = Qwen2VLTextFamily

    def __init__(self, model_path: Optional[str],
                 config: Qwen2VLInferenceConfig, mesh=None):
        from ..application import CausalLMApplication
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        Qwen2VLTextFamily, mesh=mesh)
        self.vision_spec = vision_spec_from_hf(dict(config.vision_config))
        self.image_token_id = int(config.image_token_id)
        self.spatial_merge = self.vision_spec.spatial_merge
        self.vision_params = None
        self._vis_fn = jax.jit(
            lambda p, patches, cos, sin, ids: vision_forward(
                self.vision_spec, p, patches, cos, sin, ids))

    def load_weights(self):
        from ...utils import checkpoint as ckpt
        sd = ckpt.load_state_dict(self.model_path)
        # text weights live under model.language_model.* (new HF layout) or
        # model.* (old); normalize to model.*
        remap = {}
        for k, v in sd.items():
            k2 = k.replace("model.language_model.", "model.")
            k2 = k2.replace("model.visual.", "visual.")
            remap[k2] = v
        host = self.family.convert_hf_state_dict(remap, self.text.spec)
        self.text._put_params(host)
        self.vision_params = jax.tree.map(
            jnp.asarray, convert_vision_tower(remap, self.vision_spec))
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def encode_images(self, pixel_patches: np.ndarray, grid_thw: np.ndarray
                      ) -> jnp.ndarray:
        """(N, patch_input) patches + (n_imgs, 3) grids -> merged features
        (N/merge^2, text_hidden)."""
        ang = vision_rot_angles(grid_thw, self.vision_spec)
        ids = np.repeat(np.arange(len(grid_thw)),
                        [int(t * h * w) for t, h, w in np.asarray(grid_thw)])
        return self._vis_fn(self.vision_params, jnp.asarray(pixel_patches),
                            jnp.asarray(np.cos(ang)), jnp.asarray(np.sin(ang)),
                            jnp.asarray(ids))

    def generate(self, input_ids: np.ndarray,
                 pixel_patches: Optional[np.ndarray] = None,
                 image_grid_thw: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32, **kw) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        image_embeds = image_mask = None
        rope_pos = decode_start = None
        if pixel_patches is not None:
            feats = self.encode_images(pixel_patches, image_grid_thw)
            image_mask = input_ids == self.image_token_id
            per_row = image_mask.sum(axis=1)
            if not (per_row == per_row[0]).all():
                raise ValueError("rows must hold equal image-token counts "
                                 "(pad with extra rows otherwise)")
            image_embeds = np.asarray(feats).reshape(b, per_row[0], -1)
            rope_pos, decode_start = get_rope_index(
                input_ids, image_grid_thw, self.image_token_id,
                self.spatial_merge, attention_mask)
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens, image_embeds=image_embeds,
            image_mask=image_mask, rope_position_ids=rope_pos,
            decode_rope_start=decode_start, **kw)

    def reset(self):
        self.text.reset()
        return self
