from .modeling_qwen2_vl import (Qwen2VLApplication, Qwen2VLInferenceConfig,
                                Qwen2VLTextFamily)
