"""Generic encoder application base (reference: models/encoder_base.py
``NeuronEncoderBase`` / ``NeuronEncoderApplication`` :16,24 — a non-LM app
holding a list of jitted submodels sharing one weight set).

An encoder submodel here is (name, pure function, donate spec); the app jits
each on first use and routes calls by name — the compile/load lifecycle
mirrors CausalLMApplication without the generation loop."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np


class EncoderApplication:
    """Holds params + named jitted forwards (vision towers, audio encoders,
    T5-style encoders...)."""

    def __init__(self, params: Any, submodels: Dict[str, Callable],
                 mesh=None):
        self.params = params
        self.mesh = mesh
        self._fns = dict(submodels)
        self._compiled: Dict[str, Any] = {}

    def add_submodel(self, name: str, fn: Callable):
        self._fns[name] = fn
        self._compiled.pop(name, None)

    def get_compiled(self, name: str):
        if name not in self._compiled:
            self._compiled[name] = jax.jit(self._fns[name])
        return self._compiled[name]

    def run(self, name: str, *args, **kwargs):
        return self.get_compiled(name)(self.params, *args, **kwargs)

    def warmup(self, example_inputs: Dict[str, Tuple]):
        for name, args in example_inputs.items():
            jax.block_until_ready(self.run(name, *args))
        return self
