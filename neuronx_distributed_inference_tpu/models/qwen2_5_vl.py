"""Qwen2.5-VL — windowed vision attention on the qwen2-vl base
(reference: contrib/models/Qwen2.5-VL-3B-Instruct/src/
modeling_qwen2_5_vl.py and contrib/models/Qwen2.5-VL-32B-Instruct).

Vision deltas vs qwen2-vl: RMSNorm blocks (no bias), SiLU-GLU MLP with
biases, RMSNorm patch merger, and WINDOWED attention — every block except
``fullatt_block_indexes`` attends only within a ``window_size``-pixel
window of its image. The HF implementation reorders patches so windows are
contiguous (flash-attn cu_seqlens); attention is permutation-invariant
under the right mask, so here patches stay in the merge-group order and
window layers just use a per-patch window-id equality mask — no reorder,
no un-reorder, and the merger sees the same groups. The text decoder is
qwen2 + M-RoPE, unchanged from qwen2-vl."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..ops.normalization import rms_norm
from .family import register_family
from .qwen2_vl.modeling_qwen2_vl import (Qwen2VLApplication,
                                         Qwen2VLInferenceConfig,
                                         Qwen2VLTextFamily)


@dataclass(frozen=True)
class Qwen25VisionSpec:
    depth: int
    embed_dim: int
    num_heads: int
    intermediate_size: int
    patch_input: int
    patch_size: int
    spatial_merge: int
    out_hidden: int
    window_size: int
    fullatt_idx: Tuple[int, ...]
    act: str = "silu"
    eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def vision_spec_from_hf_25(vc: Dict[str, Any]) -> Qwen25VisionSpec:
    return Qwen25VisionSpec(
        depth=int(vc["depth"]),
        embed_dim=int(vc["hidden_size"]),
        num_heads=int(vc["num_heads"]),
        intermediate_size=int(vc["intermediate_size"]),
        patch_input=(int(vc.get("in_channels", 3))
                     * int(vc.get("temporal_patch_size", 2))
                     * int(vc["patch_size"]) ** 2),
        patch_size=int(vc["patch_size"]),
        spatial_merge=int(vc.get("spatial_merge_size", 2)),
        out_hidden=int(vc["out_hidden_size"]),
        window_size=int(vc.get("window_size", 0)),
        fullatt_idx=tuple(int(i) for i in
                          vc.get("fullatt_block_indexes", ())),
        act=str(vc.get("hidden_act", "silu")),
    )


def vision_forward_25(spec: Qwen25VisionSpec, params: Dict[str, Any],
                      patches: jnp.ndarray, cos: jnp.ndarray,
                      sin: jnp.ndarray, image_ids: jnp.ndarray,
                      window_ids: jnp.ndarray) -> jnp.ndarray:
    """patches (N, patch_input) in merge-group order; window_ids (N,)
    per-patch window id (globally unique across images). Returns merged
    features (N/merge^2, out_hidden)."""
    n = patches.shape[0]
    nh, hd = spec.num_heads, spec.head_dim
    act = jax.nn.silu
    x = patches @ params["patch_proj"]
    img_mask = (image_ids[:, None] == image_ids[None, :])
    win_mask = jnp.logical_and(
        img_mask, window_ids[:, None] == window_ids[None, :])

    def rope2d(t):
        tf = t.astype(jnp.float32)
        d2 = cos.shape[-1]
        t1, t2 = tf[..., :d2], tf[..., d2:]
        c, s = cos[:, None, :], sin[:, None, :]
        return jnp.concatenate([t1 * c - t2 * s, t2 * c + t1 * s],
                               axis=-1).astype(t.dtype)

    for i in range(spec.depth):
        lw = jax.tree.map(lambda a: a[i], params["layers"])
        mask = img_mask if (i in spec.fullatt_idx
                            or spec.window_size == 0) else win_mask
        r = rms_norm(x, lw["ln1_w"], spec.eps)
        qkv = r @ lw["qkv_w"] + lw["qkv_b"]
        q, k, v = jnp.split(qkv.reshape(n, 3, nh, hd), 3, axis=1)
        q = rope2d(q[:, 0])
        k = rope2d(k[:, 0])
        v = v[:, 0]
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(mask[None], s, -1e30)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("hqk,khd->qhd", pr, v.astype(jnp.float32))
        x = x + (a.reshape(n, -1).astype(x.dtype) @ lw["proj_w"]
                 + lw["proj_b"])
        r = rms_norm(x, lw["ln2_w"], spec.eps)
        m = act(r @ lw["gate_w"] + lw["gate_b"]) * (r @ lw["up_w"]
                                                    + lw["up_b"])
        x = x + m @ lw["down_w"] + lw["down_b"]

    x = rms_norm(x, params["ln_q_w"], spec.eps)
    x = x.reshape(n // spec.spatial_merge ** 2, -1)
    x = jax.nn.gelu(x @ params["mlp0_w"] + params["mlp0_b"],
                    approximate=False)
    return x @ params["mlp2_w"] + params["mlp2_b"]


def convert_vision_tower_25(sd: Dict[str, np.ndarray],
                            spec: Qwen25VisionSpec,
                            prefix: str = "visual") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"blocks.{i}"
        return {
            "ln1_w": get(f"{b}.norm1.weight"),
            "ln2_w": get(f"{b}.norm2.weight"),
            "qkv_w": t(get(f"{b}.attn.qkv.weight")),
            "qkv_b": get(f"{b}.attn.qkv.bias"),
            "proj_w": t(get(f"{b}.attn.proj.weight")),
            "proj_b": get(f"{b}.attn.proj.bias"),
            "gate_w": t(get(f"{b}.mlp.gate_proj.weight")),
            "gate_b": get(f"{b}.mlp.gate_proj.bias"),
            "up_w": t(get(f"{b}.mlp.up_proj.weight")),
            "up_b": get(f"{b}.mlp.up_proj.bias"),
            "down_w": t(get(f"{b}.mlp.down_proj.weight")),
            "down_b": get(f"{b}.mlp.down_proj.bias"),
        }

    layers = [lw(i) for i in range(spec.depth)]
    return {
        "patch_proj": t(get("patch_embed.proj.weight").reshape(
            spec.embed_dim, -1)),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
        "ln_q_w": get("merger.ln_q.weight"),
        "mlp0_w": t(get("merger.mlp.0.weight")),
        "mlp0_b": get("merger.mlp.0.bias"),
        "mlp2_w": t(get("merger.mlp.2.weight")),
        "mlp2_b": get("merger.mlp.2.bias"),
    }


def vision_window_ids(grid_thw: np.ndarray, spec: Qwen25VisionSpec
                      ) -> np.ndarray:
    """Per-patch window id in the merge-group-permuted order (the order
    vision_rot_angles emits). Window extent = window_size pixels =
    window_size / patch_size / merge positions of the MERGED grid
    (reference: get_window_index vit_merger_window_size)."""
    m = spec.spatial_merge
    vw = max(spec.window_size // m // spec.patch_size, 1)
    out = []
    base = 0
    for t, h, w in np.asarray(grid_thw):
        hp = np.arange(h)[:, None] * np.ones((1, w), np.int64)
        wp = np.ones((h, 1), np.int64) * np.arange(w)[None, :]

        def perm(x):
            return x.reshape(h // m, m, w // m, m).transpose(
                0, 2, 1, 3).ravel()

        lh = perm(hp) // m          # merged-grid coords per patch
        lw_ = perm(wp) // m
        nww = -(-(w // m) // vw)
        wid = (lh // vw) * nww + (lw_ // vw)
        n_win = nww * (-(-(h // m) // vw))
        for ti in range(int(t)):
            out.append(wid + base)
            base += n_win
    return np.concatenate(out, axis=0).astype(np.int32)


class Qwen25VLInferenceConfig(Qwen2VLInferenceConfig):
    pass


@register_family("qwen2_5_vl_text")
class Qwen25VLTextFamily(Qwen2VLTextFamily):
    pass


class Qwen25VLApplication(Qwen2VLApplication):
    """Qwen2.5-VL: windowed vision tower + the qwen2-vl text stack."""

    family = Qwen25VLTextFamily

    def __init__(self, model_path: Optional[str],
                 config: Qwen25VLInferenceConfig, mesh=None):
        super().__init__(model_path, config, mesh=mesh)
        self.vision_spec = vision_spec_from_hf_25(dict(config.vision_config))
        self.spatial_merge = self.vision_spec.spatial_merge
        self._vis_fn = jax.jit(
            lambda p, patches, cos, sin, ids, wids: vision_forward_25(
                self.vision_spec, p, patches, cos, sin, ids, wids))

    def load_weights(self):
        from ..utils import checkpoint as ckpt
        sd = ckpt.load_state_dict(self.model_path)
        remap = {}
        for k, v in sd.items():
            k2 = k.replace("model.language_model.", "model.")
            k2 = k2.replace("model.visual.", "visual.")
            remap[k2] = v
        host = self.family.convert_hf_state_dict(remap, self.text.spec)
        self.text._put_params(host)
        self.vision_params = jax.tree.map(
            jnp.asarray, convert_vision_tower_25(remap, self.vision_spec))
        return self

    def encode_images(self, pixel_patches: np.ndarray,
                      grid_thw: np.ndarray) -> jnp.ndarray:
        from .qwen2_vl.modeling_qwen2_vl import vision_rot_angles
        ang = vision_rot_angles(grid_thw, self.vision_spec)
        ids = np.repeat(np.arange(len(grid_thw)),
                        [int(t * h * w) for t, h, w in np.asarray(grid_thw)])
        wids = vision_window_ids(grid_thw, self.vision_spec)
        return self._vis_fn(self.vision_params, jnp.asarray(pixel_patches),
                            jnp.asarray(np.cos(ang)),
                            jnp.asarray(np.sin(ang)),
                            jnp.asarray(ids), jnp.asarray(wids))
