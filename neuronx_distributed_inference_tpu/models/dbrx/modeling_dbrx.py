"""DBRX family (reference: models/dbrx/modeling_dbrx.py
``NeuronDbrxForCausalLM`` — SURVEY §2.7: MoE, 308 LoC).

DBRX deltas: bias-free LayerNorm (not RMSNorm), fused Wqkv with clip_qkv
clamping, 16-expert MoE with fused expert tensors (w1/v1/w2), softmax-then-
topk router with optional L1 weight normalization."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config
from ...parallel.layers import place_q_weight, replicate_kv_weight


class DbrxInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["d_model", "n_heads", "n_layers", "vocab_size"]

    def add_derived_config(self):
        # map DBRX's naming onto the HF-standard attributes the base
        # spec resolution expects (reference: dbrx setup_attr_for_model)
        if hasattr(self, "d_model"):
            self.hidden_size = self.d_model
            self.num_attention_heads = self.n_heads
            self.num_hidden_layers = self.n_layers
            attn = getattr(self, "attn_config", {}) or {}
            if not isinstance(attn, dict):
                attn = attn.__dict__
            self.num_key_value_heads = attn.get("kv_n_heads", self.n_heads)
            self.rope_theta = attn.get("rope_theta", 10000.0)
            self.clip_qkv = attn.get("clip_qkv")
            ffn = getattr(self, "ffn_config", {}) or {}
            if not isinstance(ffn, dict):
                ffn = ffn.__dict__
            self.intermediate_size = ffn.get("ffn_hidden_size", 4 * self.d_model)
            self.moe_num_experts = ffn.get("moe_num_experts", 16)
            self.moe_top_k = ffn.get("moe_top_k", 4)
            self.moe_normalize_expert_weights = ffn.get(
                "moe_normalize_expert_weights", 1)


@register_family("dbrx")
class DbrxFamily(DecoderFamily):
    config_cls = DbrxInferenceConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        moe = MoESpec(
            num_experts=config.moe_num_experts,
            top_k=config.moe_top_k,
            intermediate_size=config.intermediate_size,
            # moe_normalize_expert_weights=1 is an L1 normalization of the
            # top-k weights — same as sum-normalize for positive softmax vals
            normalize_topk=bool(config.moe_normalize_expert_weights),
        )
        return spec_from_config(
            config, tp_degree,
            moe=moe,
            norm_type="layernorm",
            qkv_clip=(float(config.clip_qkv)
                      if getattr(config, "clip_qkv", None) else None),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray], spec: DecoderSpec
                              ) -> Dict[str, Any]:
        p = cls.hf_prefix
        L = spec.num_layers
        g = spec.gqa
        D = spec.head_dim
        E, I = spec.moe.num_experts, spec.moe.intermediate_size
        H = spec.hidden_size

        def get(name):
            if name in sd:
                return np.asarray(sd[name])
            raise KeyError(f"missing checkpoint tensor {name}")

        def layer(i: int) -> Dict[str, np.ndarray]:
            base = f"{p}.blocks.{i}"
            wqkv = get(f"{base}.norm_attn_norm.attn.Wqkv.weight")  # (out, H)
            nq = g.orig_q_heads * D
            nkv = g.orig_kv_heads * D
            qw, kw, vw = (wqkv[:nq], wqkv[nq:nq + nkv],
                          wqkv[nq + nkv:nq + 2 * nkv])
            # experts fused (E*I, H) for w1/v1 and (E*I, H) for w2
            w1 = get(f"{base}.ffn.experts.mlp.w1").reshape(E, I, H)
            v1 = get(f"{base}.ffn.experts.mlp.v1").reshape(E, I, H)
            w2 = get(f"{base}.ffn.experts.mlp.w2").reshape(E, I, H)
            return {
                "input_norm": get(f"{base}.norm_attn_norm.norm_1.weight"),
                "post_norm": get(f"{base}.norm_attn_norm.norm_2.weight"),
                "q_proj": place_q_weight(np.ascontiguousarray(qw.T), g, D, -1),
                "k_proj": replicate_kv_weight(np.ascontiguousarray(kw.T), g, D, -1),
                "v_proj": replicate_kv_weight(np.ascontiguousarray(vw.T), g, D, -1),
                "o_proj": place_q_weight(np.ascontiguousarray(
                    get(f"{base}.norm_attn_norm.attn.out_proj.weight").T),
                    g, D, 0),
                "router": np.ascontiguousarray(
                    get(f"{base}.ffn.router.layer.weight").T).astype(np.float32),
                "expert_gate": np.ascontiguousarray(np.swapaxes(w1, 1, 2)),
                "expert_up": np.ascontiguousarray(np.swapaxes(v1, 1, 2)),
                "expert_down": np.ascontiguousarray(w2),  # (E, I, H) already
            }

        layers = [layer(i) for i in range(L)]
        stacked = {k: np.stack([d[k] for d in layers]) for k in layers[0]}

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0])] +
                           [(0, 0)] * (w.ndim - 1))
            return w

        return {
            "embed": vpad(get(p + ".wte.weight")),
            "layers": stacked,
            "final_norm": get(p + ".norm_f.weight"),
            "lm_head": np.ascontiguousarray(vpad(get("lm_head.weight")).T),
        }


def TpuDbrxForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, DbrxFamily)
