from .modeling_dbrx import DbrxFamily, DbrxInferenceConfig, TpuDbrxForCausalLM
