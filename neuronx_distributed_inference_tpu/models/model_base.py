"""Functional decoder model base — the traced-graph layer of the framework
(reference: models/model_base.py ``NeuronBaseModel``:70-1596).

TPU-first redesign:
  * The reference builds an nn.Module and traces it per (submodel, bucket).
    Here the model IS a pure function ``(params, cache, inputs) -> outputs``;
    ``jax.jit`` + AOT lowering replaces ModelBuilder.trace.
  * The per-layer Python loop (reference: get_model_output :1216-1469) becomes
    ``lax.scan`` over stacked layer weights — one compiled layer body,
    O(1) compile time in depth, XLA-pipelined.
  * KV-cache persistence via donated buffers (reference used I/O aliasing,
    model_wrapper.py:1578-1627).
  * On-device sampling (reference: :1151-1185) runs at the end of the graph.

Two step graphs per model, mirroring the reference submodel tags
(model_wrapper.py:37-42): ``context_encoding`` (prefill) and
``token_generation`` (decode). Speculation graphs live in
models/speculation.py; both reuse the layer stack here.

Everything the jitted entry points here reach is a TRACED REGION: the
``recompile-hazard`` pass of ``scripts/nxdi_lint.py`` derives it from
the ``jax.jit``/``partial`` sites and flags host concretization
(``.item()``/``float()``/host numpy on traced values), unordered
set/dict iteration and mutated-closure captures — each one a silent
bucket-ladder jit-cache miss (or a tracing crash) in production.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import InferenceConfig, TpuConfig
from ..ops import attention as attn_ops
from ..ops import decode_attention
from ..ops import flash_attention
from ..ops import sampling as sampling_ops
from ..ops.normalization import layer_norm, rms_norm
from ..ops.rope import RopeConfig, apply_rope, rope_cos_sin
from ..parallel.layers import (GQASharding, ParamSpec, column_parallel,
                               expert_column_parallel, expert_row_parallel,
                               replicated_param, resolve_gqa_sharding,
                               row_parallel, row_parallel_output,
                               vocab_parallel_embedding)
from ..parallel.mesh import (AXIS_CP, AXIS_DP, AXIS_EP, AXIS_MP, AXIS_TP,
                             shard_constraint as _shard)
from ..modules import kv_cache as kv
from ..modules import low_rank as low_rank_mod
from ..modules import ssm as ssm_mod
from ..modules.moe import MoESpec, moe_block
from ..modules.lora import (LoraSpec, apply_lora, lora_spec_from_config)
from ..modules.quantization import (QuantSpec, qlinear,
                                    quant_spec_from_config)

import logging
logger = logging.getLogger("nxdi_tpu")

ACT_FNS = {
    "silu": jax.nn.silu,
    "gelu": partial(jax.nn.gelu, approximate=False),
    "gelu_new": partial(jax.nn.gelu, approximate=True),
    "gelu_pytorch_tanh": partial(jax.nn.gelu, approximate=True),
    "relu": jax.nn.relu,
    # squared ReLU (nemotron / arcee plain MLPs)
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
}


@dataclass(frozen=True)
class MLASpec:
    """Multi-head Latent Attention geometry (reference: models/deepseek/
    modeling_deepseek.py MLA attention — SURVEY §2.7).

    KV is compressed to ``kv_lora_rank`` + a shared rope head; Q optionally
    through ``q_lora_rank``. K heads are [nope | rope], V heads are
    ``v_head_dim`` wide."""

    kv_lora_rank: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    q_lora_rank: Optional[int] = None

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim


@dataclass(frozen=True)
class DecoderSpec:
    """Static architecture description, resolved from an InferenceConfig.

    This is the single source of truth the traced functions close over —
    everything here must be hashable/static for jit.
    """

    num_layers: int
    hidden_size: int
    num_q_heads: int          # original HF head count
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    vocab_size: int
    padded_vocab: int
    rms_eps: float
    rope: RopeConfig
    act: str = "silu"
    gqa: GQASharding = None   # resolved for the mesh tp degree
    qkv_bias: bool = False
    o_bias: bool = False
    mlp_bias: bool = False
    qk_norm: bool = False     # qwen3-style per-head q/k RMSNorm
    # olmo2-style FULL-width q/k RMSNorm (over nq*D / nkv*D, pre head-split)
    qk_norm_full: bool = False
    # "pre" (llama default) or "post" (olmo2: norms on the block OUTPUTS via
    # the sandwich weights, no pre-norms)
    norm_position: str = "pre"
    # granite multipliers: residual_multiplier scales each block output
    # before the residual add; logits_divide divides the lm-head logits
    residual_multiplier: float = 1.0
    logits_divide: Optional[float] = None
    tie_word_embeddings: bool = False
    sliding_window: int = 0   # 0 = full attention
    logits_soft_cap: Optional[float] = None
    attn_soft_cap: Optional[float] = None
    attn_scale: Optional[float] = None   # None => head_dim ** -0.5
    embed_scale: Optional[float] = None  # gemma multiplies embeddings
    # --- per-layer attention variation (reference: gemma3 alternating
    # local/global layers; gpt_oss alternating sliding/full — SURVEY §2.7).
    # layer_pattern[i] True = layer i is LOCAL: sliding_window + local_rope.
    # None = uniform (sliding_window, if set, applies to every layer).
    layer_pattern: Optional[Tuple[bool, ...]] = None
    local_rope: Optional[RopeConfig] = None   # rope for local layers
    # rolling sliding-window KV (reference: kv_cache_manager.py:605-606):
    # the cache holds only ``sliding_window`` slots, written pos %% w with a
    # position-mapping decode mask — cache bytes scale with w, not seq_len
    rolling_window: bool = False
    # MIXED per-layer cache sizes (reference: gpt-oss per-layer KV,
    # modules/kvcache/gpt_oss_kv_cache_manager.py + the per-layer
    # cache-size map of kv_cache_manager.py): with an alternating
    # local/global layer_pattern, local layers get ROLLING window-sized
    # cache rows (W slots) while global layers keep full-seq rows —
    # roughly halving decode KV bytes for gpt-oss-shaped stacks. The cache
    # pytree then carries {"k","v"} (global layers) + {"k_l","v_l"}
    # (local layers); decode selects per layer statically (unrolled).
    mixed_kv: bool = False
    # llama4 attention variations (reference: models/llama4/
    # modeling_llama4_text.py — chunked attention + NoPE layers):
    # local layers use CHUNKED attention (block-diagonal causal over
    # attention_chunk_size) instead of a sliding window
    attn_chunk: int = 0
    # global layers are NoPE: no rotary applied (no_rope_layers)
    nope_global: bool = False
    # weightless L2 q/k norm AFTER rope, on rope (local) layers only
    qk_l2_norm: bool = False
    # attention temperature tuning on NoPE layers (floor_scale, attn_scale):
    # q *= log1p(floor((pos+1)/floor_scale)) * attn_scale + 1
    attn_temp: Optional[Tuple[float, float]] = None
    # interleaved dense/MoE stacks (llama4 interleave_moe_layer_step):
    # pattern[i] True = layer i is MoE; params then hold a "layers" dense
    # stack and a "moe_layers" stack, walked in contiguous runs
    moe_pattern: Optional[Tuple[bool, ...]] = None
    # gemma3 sandwich norms: post_attn_norm on attention output and
    # post_ff_norm on MLP output, in addition to the two pre-norms
    sandwich_norm: bool = False
    # RMSNorm weight offset: 1.0 gives the gemma (1+w) convention
    norm_offset: float = 0.0
    # learned per-head softmax sinks (reference: modules/attention/sink.py,
    # gpt-oss); adds a (L, Hq) "sink" param
    attn_sink: bool = False
    # ALiBi positional biases (bloom / mpt): score += slope_h * kv_pos —
    # softmax shift-invariance makes the absolute form equal to the
    # relative slope_h*(kpos-qpos); adds a (L, Hq) "alibi_slopes" param
    # (per-layer rows are identical; stacking keeps the layer scan uniform)
    alibi: bool = False
    # LayerNorm over the token embeddings (bloom
    # word_embeddings_layernorm); adds embed_norm(+_b) params
    embed_norm: bool = False
    dtype: Any = jnp.bfloat16
    kv_dtype: Any = jnp.bfloat16
    # flash-kernel strategy (reference analog: FlashAttentionStrategy,
    # attention_base.py:90-96): True = use the Pallas flash kernel for
    # prefill when ops/flash_attention.supports() holds; XLA path otherwise
    flash_prefill: bool = False
    # fused Pallas decode attention (reference analog: attention_block_tkg
    # TKG kernel, attention_base.py:1186-1382). Tri-state: None = auto
    # (cost-model admission in _layer_body — on for window/sink geometries),
    # True = always when supports() holds, False = never.
    decode_kernel: Optional[bool] = None
    # MoE: when set, the MLP block is a routed mixture of experts
    # (reference: modules/moe_v2.py; intermediate_size then refers to the
    # per-expert intermediate)
    moe: Optional[MoESpec] = None
    # MLA attention (deepseek); head_dim then = mla.qk_head_dim
    mla: Optional[MLASpec] = None
    # leading dense-MLP layers before the MoE stack (deepseek
    # first_k_dense_replace); only meaningful with moe set
    first_dense: int = 0
    # "rms" | "layernorm" (dbrx uses bias-free LayerNorm)
    norm_type: str = "rms"
    # no final pre-lm-head norm (GPT-1: the post-LN blocks already end
    # normed; reference: contrib/models/openai-gpt)
    skip_final_norm: bool = False
    # gemma3 multimodal: image-token spans attend BIDIRECTIONALLY within
    # their own contiguous image block, overriding causality AND the
    # sliding window (reference: contrib/models/gemma3-vision; HF
    # token_type_ids_mask_function or-mask)
    bidir_image_attn: bool = False
    # LayerNorm with learned bias (gpt2/falcon/starcoder2/phi/neox)
    norm_bias: bool = False
    # GLU MLP (act(gate)*up @ down, llama-shaped) vs plain 2-layer MLP
    # (act(x@fc1) @ fc2 — gpt2/falcon/starcoder2/phi/neox); plain reuses
    # the gate_proj/down_proj param slots as fc1/fc2
    mlp_glu: bool = True
    # skip rotary entirely (gpt2 learned positions; cos=1/sin=0)
    no_rope: bool = False
    # learned absolute position embeddings: adds a (max_positions, H)
    # "pos_embed" param gathered at position_ids and added to the token
    # embedding (gpt2 wpe)
    learned_pos: int = 0          # 0 = none, else table size
    # lm_head bias (phi-1/2)
    lm_head_bias: bool = False
    # vocab-parallel embedding: shard the (V, H) table on V over the
    # model-parallel axes (reference: ParallelEmbedding vocab_parallel,
    # models/config.py:142); False = replicated table
    vocab_parallel: bool = True
    # residual block style: "sequential" (llama), "parallel_shared" (one
    # norm feeds both attn and MLP — falcon parallel_attn / phi), or
    # "parallel_dual" (separate norms, both from the block INPUT — gpt-neox
    # use_parallel_residual)
    block_style: str = "sequential"
    # clamp q/k/v projections to ±qkv_clip (dbrx clip_qkv)
    qkv_clip: Optional[float] = None
    # interleaved (GPT-NeoX pair) rope convention (deepseek rope_interleave)
    rope_interleaved: bool = False
    # apply the per-head q/k RMSNorm AFTER rope instead of before
    # (hunyuan-dense query/key_layernorm ordering)
    qk_norm_after_rope: bool = False
    # per-head q/k norm flavor: "rms" (qwen3 et al) or "layernorm" with
    # bias (persimmon q/k_layernorm)
    qk_norm_type: str = "rms"
    # Medusa speculation heads on the target model (reference:
    # medusa_speculation, model_base.py / models/config.py:243-274):
    # head j = ResBlock(H->H) + its own lm head, predicting position +j+2
    medusa_heads: int = 0
    # multi-LoRA serving (reference: modules/lora_serving/): stacked
    # per-adapter A/B weights selected by per-request adapter_ids
    lora: Optional[LoraSpec] = None
    # intermediate-tensor capture points appended to graph outputs
    # (reference: models/model_base.py:1076-1149 tensor capture)
    capture: Optional[Tuple[str, ...]] = None
    # --- scale-out (reference: SURVEY §2.8 parallelism inventory) ---
    # SP: shard prefill activations on seq over the "cp" axis between blocks
    # (reference: sequence_parallel_enabled, model_base.py:1482-1517)
    seq_parallel: bool = False
    # CP prefill: Q stays seq-sharded over "cp", KV replicated on seq so XLA
    # inserts the all-gather — the reference's all-gather-KV CP strategy
    # (attention_base.py:548-563), not ring attention
    cp_prefill: bool = False
    # flash decoding: KV cache seq dim sharded over "cp"; decode scores and
    # softmax are computed distributed over the seq shards (reference:
    # modules/flashdecode/utils.py decode-time S-sharding)
    flash_decoding: bool = False
    # weight-only quantization (reference: models/config.py:216-241); the
    # param tree then carries {"qweight","scale"} leaf-groups for the
    # converted weights (modules/quantization.py)
    quant: Optional[QuantSpec] = None
    # scaled KV quantization: values are stored as x/kv_scale in kv_dtype and
    # rescaled on read (reference: kv_cache_manager.py:636-692 scaled fp8
    # mode; None = direct cast)
    kv_scale: Optional[float] = None
    # quantized decode collectives (parallel/collectives.py, EQuARX-style):
    # wire dtype for the row-parallel o_proj/down_proj reduction during the
    # decode and paged phases ("int8"/"fp8"); None keeps the implicit fp32
    # GSPMD all-reduce and the graphs bit-unchanged. Prefill always stays on
    # the fp32 collective — its reduction is amortized over the whole prompt.
    collective_dtype: Optional[str] = None
    collective_block: int = 32
    # low-rank (SVD-compressed) MLP (modules/low_rank.py, NeuronMLP
    # arxiv 2510.25977): rank of the {"lr_u","lr_v"} factor pairs the
    # gate/up/down projections are compressed to host-side; None = dense
    low_rank: Optional[low_rank_mod.LowRankSpec] = None
    # --- recurrent / hybrid state axis (reference: contrib/models/
    # Falcon-H1-0.5B-Instruct hybrid attention+mamba2 and contrib/models/
    # recurrentgemma-2b-it Griffin blocks — a SECOND cache pytree of
    # conv tails + recurrent states carried next to the KV cache) ---
    ssm: Optional[ssm_mod.SSMSpec] = None
    # per-layer flag: True = layer i carries the SSM block; None with ssm
    # set = every layer. With ssm_parallel the SSM runs NEXT TO attention
    # inside each flagged layer (falcon-h1 parallel hybrid); otherwise it
    # REPLACES attention there (recurrentgemma rec/rec/attn pattern).
    ssm_pattern: Optional[Tuple[bool, ...]] = None
    ssm_parallel: bool = False
    # family-specific static constants that conversion / layer hooks need
    # (falcon-h1 MuP multipliers) — a hashable (name, value) tuple so the
    # spec stays jit-static
    extras: Optional[Tuple[Tuple[str, Any], ...]] = None

    def extra(self, name: str, default=None):
        for k, v in (self.extras or ()):
            if k == name:
                return v
        return default

    @property
    def resolved_ssm_pattern(self) -> Optional[Tuple[bool, ...]]:
        if self.ssm is None:
            return None
        return (self.ssm_pattern if self.ssm_pattern is not None
                else (True,) * self.num_layers)

    @property
    def num_attn_layers(self) -> int:
        """Layers that read/write the KV cache (SSM-only layers don't)."""
        pat = self.resolved_ssm_pattern
        if pat is None or self.ssm_parallel:
            return self.num_layers
        return self.num_layers - sum(pat)

    @property
    def num_ssm_layers(self) -> int:
        pat = self.resolved_ssm_pattern
        return 0 if pat is None else sum(pat)

    @property
    def scale(self) -> float:
        return self.attn_scale if self.attn_scale is not None else self.head_dim ** -0.5

    @property
    def q_size(self) -> int:
        return self.gqa.num_q_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.gqa.num_kv_heads * self.head_dim

    @property
    def v_head_dim(self) -> int:
        return self.mla.v_head_dim if self.mla is not None else self.head_dim


def pad_vocab(vocab: int, tp: int, multiple: int = 128) -> int:
    m = max(tp, 1) * multiple
    return int(np.ceil(vocab / m) * m)


# ---------------------------------------------------------------------------
# Parameter specs (shapes + shardings) — reference analog: the parallel-layer
# module tree built in each model's init_model.
# ---------------------------------------------------------------------------

def _attn_param_specs(spec: DecoderSpec, L: int) -> Dict[str, ParamSpec]:
    H = spec.hidden_size
    dt = spec.dtype
    layers: Dict[str, ParamSpec] = {
        "input_norm": ParamSpec((L, H), P(), dt, "ones"),
        "post_norm": ParamSpec((L, H), P(), dt, "ones"),
    }
    if spec.norm_bias:
        layers["input_norm_b"] = ParamSpec((L, H), P(), dt, "zeros")
        layers["post_norm_b"] = ParamSpec((L, H), P(), dt, "zeros")
    if spec.mla is not None:
        m = spec.mla
        nh = spec.gqa.num_q_heads
        if m.q_lora_rank:
            layers["q_a_proj"] = ParamSpec((L, H, m.q_lora_rank), P(), dt)
            layers["q_a_norm"] = ParamSpec((L, m.q_lora_rank), P(), dt, "ones")
            layers["q_b_proj"] = column_parallel(
                m.q_lora_rank, nh * m.qk_head_dim, dt, True, L)
        else:
            layers["q_proj"] = column_parallel(H, nh * m.qk_head_dim, dt, True, L)
        layers["kv_a_proj"] = ParamSpec(
            (L, H, m.kv_lora_rank + m.qk_rope_head_dim), P(), dt)
        layers["kv_a_norm"] = ParamSpec((L, m.kv_lora_rank), P(), dt, "ones")
        layers["kv_b_proj"] = column_parallel(
            m.kv_lora_rank, nh * (m.qk_nope_head_dim + m.v_head_dim), dt, True, L)
        layers["o_proj"] = row_parallel(nh * m.v_head_dim, H, dt, True, L)
    else:
        # q/k/v fused into ONE stacked weight: a decode step is a GEMV per
        # weight — one (H, q+2kv) matmul streams the bytes at a higher
        # effective bandwidth than three separate ones (fewer fusion
        # boundaries; measured on v5e). The reference fuses the same way
        # (fused_qkv, modules/attention/gqa.py GroupQueryAttention_QKV).
        layers.update({
            "qkv_proj": column_parallel(H, spec.q_size + 2 * spec.kv_size,
                                        dt, True, L),
            "o_proj": row_parallel(spec.q_size, H, dt, True, L),
        })
        if spec.qkv_bias:
            layers["qkv_bias"] = ParamSpec(
                (L, spec.q_size + 2 * spec.kv_size), P(None, AXIS_MP), dt,
                "zeros")
        if spec.qk_norm:
            layers["q_norm"] = ParamSpec((L, spec.head_dim), P(), dt, "ones")
            layers["k_norm"] = ParamSpec((L, spec.head_dim), P(), dt, "ones")
        if spec.qk_norm_full:
            layers["q_norm"] = ParamSpec((L, spec.q_size), P(None, AXIS_MP),
                                         dt, "ones")
            layers["k_norm"] = ParamSpec((L, spec.kv_size), P(None, AXIS_MP),
                                         dt, "ones")
    if spec.qk_norm and spec.qk_norm_type == "layernorm":
        layers["q_norm_b"] = ParamSpec((L, spec.head_dim), P(), dt, "zeros")
        layers["k_norm_b"] = ParamSpec((L, spec.head_dim), P(), dt, "zeros")
    if spec.o_bias:
        # row-parallel bias: replicated, added after the psum'd projection
        layers["o_bias"] = ParamSpec((L, H), P(), dt, "zeros")
    if spec.sandwich_norm:
        layers["post_attn_norm"] = ParamSpec((L, H), P(), dt, "ones")
        layers["post_ff_norm"] = ParamSpec((L, H), P(), dt, "ones")
    if spec.attn_sink:
        layers["sink"] = ParamSpec((L, spec.gqa.num_q_heads),
                                   P(None, AXIS_MP), jnp.float32, "zeros")
    if spec.alibi:
        layers["alibi_slopes"] = ParamSpec((L, spec.gqa.num_q_heads),
                                           P(), jnp.float32, "zeros")
    if spec.lora is not None and spec.mla is None:
        _add_lora_specs(spec, layers, L, {
            "q_proj": (H, spec.q_size), "k_proj": (H, spec.kv_size),
            "v_proj": (H, spec.kv_size), "o_proj": (spec.q_size, H)})
    return layers


def _add_lora_specs(spec: DecoderSpec, layers: Dict[str, ParamSpec], L: int,
                    dims: Dict[str, Tuple[int, int]]) -> None:
    """Stacked adapter weights for each targeted module
    (reference: modules/lora_serving/lora_layer.py parallel LoRA linears).
    A (L, max_loras, in, r) replicated; B (L, max_loras, r, out) sharded
    like the base weight's out dim when it is model-parallel."""
    lo = spec.lora
    dt = spec.dtype
    col_sharded = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"}
    for mod, (d_in, d_out) in dims.items():
        if not lo.targets(mod):
            continue
        a_spec = P(None, None, AXIS_MP, None) if mod in ("o_proj", "down_proj") \
            else P()
        b_spec = P(None, None, None, AXIS_MP) if mod in col_sharded else P()
        layers[f"lora_A_{mod}"] = ParamSpec(
            (L, lo.max_loras, d_in, lo.rank), a_spec, dt, "zeros")
        layers[f"lora_B_{mod}"] = ParamSpec(
            (L, lo.max_loras, lo.rank, d_out), b_spec, dt, "zeros")


def _dense_mlp_param_specs(spec: DecoderSpec, L: int) -> Dict[str, ParamSpec]:
    H, I = spec.hidden_size, spec.intermediate_size
    dt = spec.dtype
    layers = {
        "gate_proj": column_parallel(H, I, dt, True, L),
        "down_proj": row_parallel(I, H, dt, True, L),
    }
    if spec.mlp_glu:
        layers["up_proj"] = column_parallel(H, I, dt, True, L)
    if spec.mlp_bias:
        layers["gate_bias"] = ParamSpec((L, I), P(None, AXIS_MP), dt, "zeros")
        layers["down_bias"] = ParamSpec((L, H), P(), dt, "zeros")
        if spec.mlp_glu:
            layers["up_bias"] = ParamSpec((L, I), P(None, AXIS_MP), dt,
                                          "zeros")
    if spec.act == "xielu":
        # [alpha_p_raw, alpha_n_raw, beta, eps] per layer (apertus)
        layers["xielu"] = ParamSpec((L, 4), P(), jnp.float32, "ones")
    if spec.lora is not None:
        dims = {"gate_proj": (H, I), "down_proj": (I, H)}
        if spec.mlp_glu:
            dims["up_proj"] = (H, I)
        _add_lora_specs(spec, layers, L, dims)
    return layers


def _moe_param_specs(spec: DecoderSpec, L: int) -> Dict[str, ParamSpec]:
    m = spec.moe
    H, dt = spec.hidden_size, spec.dtype
    E, Ie = m.num_experts, m.intermediate_size
    layers: Dict[str, ParamSpec] = {
        "router": ParamSpec((L, H, E), P(), jnp.float32),
        "expert_gate": expert_column_parallel(E, H, Ie, dt, True, L),
        "expert_up": expert_column_parallel(E, H, Ie, dt, True, L),
        "expert_down": expert_row_parallel(E, Ie, H, dt, True, L),
    }
    if m.has_router_bias:
        layers["router_bias"] = ParamSpec((L, E), P(), jnp.float32, "zeros")
    if m.expert_bias:
        layers["expert_gate_bias"] = ParamSpec(
            (L, E, Ie), P(None, AXIS_EP, AXIS_TP), dt, "zeros")
        layers["expert_up_bias"] = ParamSpec(
            (L, E, Ie), P(None, AXIS_EP, AXIS_TP), dt, "zeros")
        layers["expert_down_bias"] = ParamSpec(
            (L, E, H), P(None, AXIS_EP, None), dt, "zeros")
    if m.shared_intermediate > 0:
        Is = m.shared_intermediate
        layers.update({
            "shared_gate": column_parallel(H, Is, dt, True, L),
            "shared_up": column_parallel(H, Is, dt, True, L),
            "shared_down": row_parallel(Is, H, dt, True, L),
        })
    return layers


def decoder_param_specs(spec: DecoderSpec) -> Dict[str, Any]:
    """Shapes + shardings of the full param tree.

    Uniform models: one "layers" stack of num_layers. Mixed dense/MoE models
    (deepseek first_k_dense_replace): "layers" = the leading first_dense
    dense layers, "moe_layers" = the trailing MoE layers — two lax.scan
    stacks in run_layers."""
    L, H = spec.num_layers, spec.hidden_size
    dt = spec.dtype
    out: Dict[str, Any] = {
        "embed": (vocab_parallel_embedding(spec.padded_vocab, H, dt)
                  if spec.vocab_parallel
                  else ParamSpec((spec.padded_vocab, H), P(), dt)),
    }
    if not spec.skip_final_norm:
        out["final_norm"] = ParamSpec((H,), P(), dt, "ones")
        if spec.norm_bias:
            out["final_norm_b"] = ParamSpec((H,), P(), dt, "zeros")
    if spec.learned_pos:
        out["pos_embed"] = ParamSpec((spec.learned_pos, H), P(), dt)
    if spec.embed_norm:
        out["embed_norm"] = ParamSpec((H,), P(), dt, "ones")
        out["embed_norm_b"] = ParamSpec((H,), P(), dt, "zeros")
    if spec.moe is not None and spec.first_dense > 0:
        n_dense, n_moe = spec.first_dense, L - spec.first_dense
        dense = _attn_param_specs(spec, n_dense)
        dense.update(_dense_mlp_param_specs(spec, n_dense))
        moe = _attn_param_specs(spec, n_moe)
        moe.update(_moe_param_specs(spec, n_moe))
        out["layers"] = dense
        out["moe_layers"] = moe
    elif spec.moe is not None and spec.moe_pattern is not None:
        # interleaved dense/MoE (llama4): stacks hold each kind's layers in
        # order of appearance; run_layers walks the pattern
        n_moe = sum(spec.moe_pattern)
        n_dense = L - n_moe
        moe = _attn_param_specs(spec, n_moe)
        moe.update(_moe_param_specs(spec, n_moe))
        out["moe_layers"] = moe
        if n_dense:
            dense = _attn_param_specs(spec, n_dense)
            dense.update(_dense_mlp_param_specs(spec, n_dense))
            out["layers"] = dense
    elif spec.ssm is not None and not spec.ssm_parallel:
        # interleaved recurrent/attention stacks (recurrentgemma): "layers"
        # holds every layer's norms + MLP; attention weights stack over the
        # attention layers only ("attn_layers"), SSM weights over the
        # recurrent layers ("ssm_layers") — SSM-only layers carry no dead
        # attention params and no KV cache rows
        norm_keys = ("input_norm", "post_norm", "input_norm_b", "post_norm_b")
        full = _attn_param_specs(spec, L)
        shared = {k: v for k, v in full.items() if k in norm_keys}
        shared.update(_dense_mlp_param_specs(spec, L))
        out["layers"] = shared
        if spec.num_attn_layers:
            attn_full = _attn_param_specs(spec, spec.num_attn_layers)
            out["attn_layers"] = {k: v for k, v in attn_full.items()
                                  if k not in norm_keys}
        if spec.num_ssm_layers:
            out["ssm_layers"] = ssm_mod.ssm_param_specs(
                spec.ssm, H, spec.num_ssm_layers, dt)
    else:
        layers = _attn_param_specs(spec, L)
        layers.update(_dense_mlp_param_specs(spec, L) if spec.moe is None
                      else _moe_param_specs(spec, L))
        if spec.ssm is not None:
            # parallel hybrid (falcon-h1): every layer is uniform — the SSM
            # weights join the single "layers" stack
            layers.update(ssm_mod.ssm_param_specs(spec.ssm, H, L, dt))
        out["layers"] = layers
    if not spec.tie_word_embeddings:
        out["lm_head"] = ParamSpec((H, spec.padded_vocab), P(None, AXIS_MP), dt)
        if spec.lm_head_bias:
            out["lm_head_b"] = ParamSpec((spec.padded_vocab,),
                                         P(AXIS_MP), dt, "zeros")
    if spec.medusa_heads > 0:
        M = spec.medusa_heads
        out["medusa_blocks"] = ParamSpec((M, H, H), P(), dt)
        out["medusa_bias"] = ParamSpec((M, H), P(), dt, "zeros")
        out["medusa_lm"] = ParamSpec((M, H, spec.padded_vocab),
                                     P(None, None, AXIS_MP), dt)
    return out


def init_param_tree(specs: Dict[str, Any], key: jax.Array,
                    mesh: Optional[Mesh] = None) -> Dict[str, Any]:
    """Random-init a ParamSpec tree. Per-leaf keys are derived from the leaf
    PATH (fold_in of a stable hash), so adding optional params (lora, medusa)
    never reshuffles the other weights for a given seed."""
    import zlib
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, ParamSpec))
    leaves = []
    for path, ps in flat:
        pstr = "/".join(str(p) for p in path)
        k = jax.random.fold_in(key, zlib.crc32(pstr.encode()) & 0x7FFFFFFF)
        x = ps.initializer(k)
        if mesh is not None:
            x = jax.device_put(x, NamedSharding(mesh, ps.pspec))
        leaves.append(x)
    return jax.tree.unflatten(treedef, leaves)


def init_params(spec: DecoderSpec, key: jax.Array,
                mesh: Optional[Mesh] = None) -> Dict[str, Any]:
    """Random-init a sharded param tree (tiny-model tests / benchmarks with
    synthetic weights — reference: modules/checkpoint.py:202-287 random
    N-layer checkpoint creation)."""
    return init_param_tree(decoder_param_specs(spec), key, mesh)


def fuse_qkv_host(host: Dict[str, Any]) -> Dict[str, Any]:
    """Fuse per-projection q/k/v host weights (family converters emit them
    separately, matching the HF checkpoint) into the stacked ``qkv_proj`` /
    ``qkv_bias`` the layer graph consumes. Walks the decoder-layer subtrees
    only — cross-attention ("cross_layers") and vision params keep their own
    layouts. No-op when already fused (pre-fused quantized checkpoints)."""
    for key in ("layers", "moe_layers"):
        d = host.get(key)
        # MLA layers (deepseek) have a bare q_proj with separate compressed
        # kv projections — only fuse the standard q/k/v triple
        if (not isinstance(d, dict) or "q_proj" not in d
                or "k_proj" not in d or "v_proj" not in d):
            continue
        d["qkv_proj"] = np.concatenate(
            [np.asarray(d.pop("q_proj")), np.asarray(d.pop("k_proj")),
             np.asarray(d.pop("v_proj"))], axis=-1)
        if "q_bias" in d:
            d["qkv_bias"] = np.concatenate(
                [np.asarray(d.pop("q_bias")), np.asarray(d.pop("k_bias")),
                 np.asarray(d.pop("v_bias"))], axis=-1)
    return host


def stack_lora_host(spec: DecoderSpec, host: Dict[str, Any]) -> Dict[str, Any]:
    """Backfill the stacked ``lora_A_<mod>`` / ``lora_B_<mod>`` host
    leaves a checkpoint never carries: HF state dicts hold BASE weights
    only — adapters arrive at serving time, swapped into device slots by
    serving/lora_pool.py — so every load path stacks zeroed
    ``(L, max_loras, ...)`` factors here (slot 0 IS the pinned zero
    adapter). No-op without lora_config or when the leaves are already
    present (init_random_weights, quantized-state round-trips)."""
    if spec.lora is None:
        return host
    specs = decoder_param_specs(spec)
    for group, d in specs.items():
        if not isinstance(d, dict) or not isinstance(host.get(group), dict):
            continue
        for k, ps in d.items():
            if k.startswith("lora_") and k not in host[group]:
                host[group][k] = np.zeros(ps.shape, ps.dtype)
    return host


def param_shardings(spec: DecoderSpec, mesh: Mesh):
    specs = decoder_param_specs(spec)
    return jax.tree.map(lambda ps: NamedSharding(mesh, ps.pspec), specs,
                        is_leaf=lambda x: isinstance(x, ParamSpec))


# ---------------------------------------------------------------------------
# Layer stack
# ---------------------------------------------------------------------------



def _split_heads(x: jnp.ndarray, n_heads: int, head_dim: int) -> jnp.ndarray:
    b, t, _ = x.shape
    return x.reshape(b, t, n_heads, head_dim)


def _norm(spec: DecoderSpec, x, w, b=None):
    """Pre/post-block norm: RMSNorm (default, with optional gemma offset) or
    LayerNorm (dbrx bias-free; gpt2-family with bias)."""
    if spec.norm_type == "layernorm":
        return layer_norm(x, w, b, spec.rms_eps)
    return rms_norm(x, w, spec.rms_eps, spec.norm_offset)


def _mla_qkv(spec: DecoderSpec, h, layer_w, cos, sin):
    """Multi-head Latent Attention projections (reference: models/deepseek/
    modeling_deepseek.py MLA): Q through optional q-lora, KV through the
    compressed latent + shared rope head. Returns q/k (B,T,Hq,qk_head_dim),
    v (B,T,Hq,v_head_dim)."""
    m = spec.mla
    nh = spec.gqa.num_q_heads
    b, t, _ = h.shape
    if m.q_lora_rank:
        qa = rms_norm(qlinear(h, layer_w["q_a_proj"]), layer_w["q_a_norm"],
                      spec.rms_eps)
        q = qlinear(qa, layer_w["q_b_proj"])
    else:
        q = qlinear(h, layer_w["q_proj"])
    q = _shard(q.reshape(b, t, nh, m.qk_head_dim), AXIS_DP, None, AXIS_MP, None)
    q_nope, q_rot = q[..., :m.qk_nope_head_dim], q[..., m.qk_nope_head_dim:]

    ckv = qlinear(h, layer_w["kv_a_proj"])                  # (B,T,r+rope)
    k_pass, k_rot = ckv[..., :m.kv_lora_rank], ckv[..., m.kv_lora_rank:]
    kv = qlinear(rms_norm(k_pass, layer_w["kv_a_norm"], spec.rms_eps),
                 layer_w["kv_b_proj"])
    kv = _shard(kv.reshape(b, t, nh, m.qk_nope_head_dim + m.v_head_dim),
                AXIS_DP, None, AXIS_MP, None)
    k_nope, v = kv[..., :m.qk_nope_head_dim], kv[..., m.qk_nope_head_dim:]

    q_rot = apply_rope(q_rot, cos, sin, interleaved=spec.rope_interleaved)
    k_rot = apply_rope(k_rot[:, :, None, :], cos, sin,
                       interleaved=spec.rope_interleaved)   # (B,T,1,rope)
    k_rot = jnp.broadcast_to(k_rot, (b, t, nh, m.qk_rope_head_dim))
    q = jnp.concatenate([q_nope, q_rot], axis=-1)
    k = jnp.concatenate([k_nope, k_rot], axis=-1)
    return q, k, v


def attn_inputs(spec: DecoderSpec, position_ids, make_mask,
                rope_positions=None) -> Dict[str, Any]:
    """Bundle rope cos/sin + attention mask(s) for the layer stack.

    ``make_mask(window, chunk)`` builds the phase-appropriate mask. With a
    ``layer_pattern`` set (alternating local/global layers — reference:
    gemma3 / gpt_oss / llama4 families), both the local variant (sliding
    window or chunked attention + local_rope) and the global variant
    (optionally NoPE — identity rotation) are built once here; each scanned
    layer selects by its is_local flag — one compiled layer body, no
    per-layer branching (SURVEY §2.7)."""
    rp = rope_positions if rope_positions is not None else position_ids
    cos, sin = rope_cos_sin(rp, spec.rope)
    if spec.no_rope:
        cos, sin = jnp.ones_like(cos), jnp.zeros_like(sin)
    ai: Dict[str, Any] = {"cos": cos, "sin": sin}
    if spec.layer_pattern is None:
        ai["mask"] = make_mask(spec.sliding_window, spec.attn_chunk)
        return ai
    ai["mask"] = make_mask(0, 0)
    cos_l, sin_l = rope_cos_sin(rp, spec.local_rope or spec.rope)
    if spec.no_rope:
        # learned-position models with local/global patterns (gpt-neo):
        # neither variant rotates
        cos_l, sin_l = jnp.ones_like(cos_l), jnp.zeros_like(sin_l)
    if spec.nope_global:
        # llama4 NoPE global layers: identity rotation
        ai["cos"], ai["sin"] = jnp.ones_like(cos), jnp.zeros_like(sin)
    ai["cos_l"], ai["sin_l"] = cos_l, sin_l
    ai["mask_l"] = make_mask(spec.sliding_window, spec.attn_chunk)
    return ai


def _layer_body(spec: DecoderSpec, hidden, layer_w, k_full, v_full, li,
                ai, is_local, seq_ids, positions, phase: str,
                identity_seq_ids: bool = False,
                arange_positions: bool = False,
                slot_mapping=None, block_table=None,
                mlp_kind: Optional[str] = None,
                adapter_ids=None, replace=None, kv_view: int = None,
                deepstack=None, deepstack_mask=None, prefill_lens=None,
                side=None, mixed_local=None):
    """One transformer layer. hidden (B,T,H); k/v_full: the FULL stacked
    cache (L,B,S,Hkv,D) — or, in the paged layout, (L,N_blocks,Bs,Hkv,D)
    with ``slot_mapping``/``block_table`` set (phase "paged", reference:
    modules/kvcache/block_kv_cache_manager.py). ``li``: this layer's index
    into the cache (traced scalar). The cache flows through the layer scan
    as CARRY with in-place scatters — writes cost O(tokens), not O(cache)
    (the reference gets the same effect from buffer aliasing,
    model_wrapper.py:1578-1627).

    ai: attn_inputs() bundle; is_local: this layer's local/global flag
    (traced scalar from the scan xs).

    phase "prefill": attend within the window only (no prior cache read),
      then write the window into the cache (reference CTE path).
    phase "decode": write active tokens into cache, attend over full cache
      (reference TKG path; the reference's decomposed prior/active attention
      attention_base.py:1383-1461 is one fused softmax over the cache here —
      XLA fuses it, no manual decomposition needed).
    phase "paged": write at slot_mapping, gather via block_table, attend over
      the gathered view — covers paged prefill, prefix-cached continuation,
      chunked prefill and paged decode with one body.
    """
    if mlp_kind is None:
        mlp_kind = "dense" if spec.moe is None else "moe"
    caps: Dict[str, Any] = {}

    def _tap(name, val):
        """Tensor replacement (golden injection) then capture at one point
        (reference: utils/tensor_replacement/ + tensor capture
        model_base.py:1076-1149)."""
        if replace is not None and name in replace:
            val = jnp.where(replace[name + "_on"],
                            replace[name].astype(val.dtype), val)
        if spec.capture and name in spec.capture:
            caps[name] = val
        return val
    h = (_norm(spec, hidden, layer_w["input_norm"],
               layer_w.get("input_norm_b") if spec.norm_bias else None)
         if spec.norm_position == "pre" else hidden)
    attn_in = h        # parallel blocks feed the MLP from the same norm
    h, k_full, v_full, pending = _attn_block(
        spec, h, layer_w, k_full, v_full, li, ai, is_local, seq_ids,
        positions, phase, identity_seq_ids=identity_seq_ids,
        arange_positions=arange_positions, slot_mapping=slot_mapping,
        block_table=block_table, adapter_ids=adapter_ids, kv_view=kv_view,
        prefill_lens=prefill_lens, side=side, mixed_local=mixed_local)
    if spec.sandwich_norm:
        h = rms_norm(h, layer_w["post_attn_norm"], spec.rms_eps,
                     spec.norm_offset)
    h = _tap("attn_output", h)
    # SP: residual stream stays seq-sharded between blocks during prefill
    # (reference: sequence-parallel reduce-scatter, model_base.py:1482-1517)
    sp_axis = AXIS_CP if (spec.seq_parallel and phase == "prefill") else None

    def _mlp(x_in):
        return _mlp_block(spec, x_in, layer_w, mlp_kind, adapter_ids,
                          phase=phase)

    if spec.block_style != "sequential":
        # parallel residual: x + attn(norm(x)) + mlp(norm'(x)) (falcon
        # parallel_attn / phi share the attention norm; gpt-neox
        # use_parallel_residual has its own post norm over the INPUT)
        mlp_in = attn_in if spec.block_style == "parallel_shared" else \
            _norm(spec, hidden, layer_w["post_norm"],
                  layer_w.get("post_norm_b") if spec.norm_bias else None)
        m = _tap("mlp_output", _mlp(mlp_in))
        hidden = hidden + spec.residual_multiplier * _shard(
            h + m, AXIS_DP, sp_axis, None)
        hidden = _deepstack_add(hidden, deepstack, deepstack_mask)
        hidden = _tap("layer_output", hidden)
        if side is not None:
            return hidden, k_full, v_full, caps, pending
        return hidden, k_full, v_full, caps

    if spec.norm_position == "post_residual":
        # original-transformer post-LN (openai-gpt / GPT-1: x = ln(x + sub(x))
        # — reference: contrib/models/openai-gpt)
        hidden = _norm(spec, hidden + _shard(h, AXIS_DP, sp_axis, None),
                       layer_w["input_norm"],
                       layer_w.get("input_norm_b") if spec.norm_bias else None)
        h = _tap("mlp_output", _mlp(hidden))
        hidden = _norm(spec, hidden + _shard(h, AXIS_DP, sp_axis, None),
                       layer_w["post_norm"],
                       layer_w.get("post_norm_b") if spec.norm_bias else None)
        hidden = _tap("layer_output", hidden)
        if side is not None:
            return hidden, k_full, v_full, caps, pending
        return hidden, k_full, v_full, caps

    hidden = hidden + spec.residual_multiplier * _shard(h, AXIS_DP, sp_axis, None)

    h = (_norm(spec, hidden, layer_w["post_norm"],
               layer_w.get("post_norm_b") if spec.norm_bias else None)
         if spec.norm_position == "pre" else hidden)
    h = _mlp(h)
    if spec.sandwich_norm:
        h = rms_norm(h, layer_w["post_ff_norm"], spec.rms_eps,
                     spec.norm_offset)
    h = _tap("mlp_output", h)
    hidden = hidden + spec.residual_multiplier * _shard(h, AXIS_DP, sp_axis, None)
    hidden = _deepstack_add(hidden, deepstack, deepstack_mask)
    hidden = _tap("layer_output", hidden)
    if side is not None:
        return hidden, k_full, v_full, caps, pending
    return hidden, k_full, v_full, caps


def _row_parallel_out(spec: DecoderSpec, x, w, phase: str):
    """Row-parallel output reduction for o_proj / down_proj: the quantized
    ring exchange during decode/paged phases when the collective knob is on
    ("paged" covers the whole paged serving family including its context
    graphs — the unified ragged dispatch mixes both in one step), otherwise
    the plain (q)linear whose all-reduce GSPMD inserts."""
    if isinstance(w, dict) and "lr_u" in w:
        # low-rank (SVD) factors (modules/low_rank.py): the sharded
        # x @ U contraction's all-reduce lands on the rank-r
        # intermediate — already an ~out/r smaller wire than the dense
        # output — so the quantized ring is skipped; GSPMD reduces the
        # U half and the replicated V half needs no collective
        return qlinear(x, w)
    if spec.collective_dtype is not None and phase in ("decode", "paged"):
        return row_parallel_output(x, w,
                                   collective_dtype=spec.collective_dtype,
                                   collective_block=spec.collective_block)
    return qlinear(x, w)


def _mlp_block(spec: DecoderSpec, x_in, layer_w, mlp_kind, adapter_ids,
               phase: str = "prefill"):
    """The MLP / MoE half of a layer (GLU, plain 2-layer, or routed MoE)."""
    if mlp_kind == "moe":
        return moe_block(spec.moe, x_in, layer_w, phase=phase)
    if spec.act == "xielu":
        # Apertus xIELU with LEARNED per-layer alphas (reference:
        # contrib/models/Apertus-8B-Instruct-2509; HF XIELUActivation):
        # layer_w["xielu"] = [alpha_p_raw, alpha_n_raw, beta, eps]
        xp = layer_w["xielu"].astype(jnp.float32)
        alpha_p = jax.nn.softplus(xp[0])
        beta, eps = xp[2], xp[3]
        alpha_n = beta + jax.nn.softplus(xp[1])

        def act(x):
            xf = x.astype(jnp.float32)
            y = jnp.where(
                xf > 0,
                alpha_p * xf * xf + beta * xf,
                (jnp.expm1(jnp.minimum(xf, eps)) - xf) * alpha_n + beta * xf)
            return y.astype(x.dtype)
    else:
        act = ACT_FNS[spec.act]
    if not spec.mlp_glu:
        # plain 2-layer MLP (gpt2/falcon/starcoder2/phi/neox):
        # gate_proj/down_proj slots hold fc1/fc2
        inter = apply_lora(spec.lora, layer_w, "gate_proj", x_in,
                           qlinear(x_in, layer_w["gate_proj"]),
                           adapter_ids)
        if spec.mlp_bias:
            inter = inter + layer_w["gate_bias"]
        inter = _shard(act(inter), AXIS_DP, None, AXIS_MP)
        y = apply_lora(spec.lora, layer_w, "down_proj", inter,
                       _row_parallel_out(spec, inter, layer_w["down_proj"],
                                         phase), adapter_ids)
        if spec.mlp_bias:
            y = y + layer_w["down_bias"]
        return y
    gate = apply_lora(spec.lora, layer_w, "gate_proj", x_in,
                      qlinear(x_in, layer_w["gate_proj"]), adapter_ids)
    up = apply_lora(spec.lora, layer_w, "up_proj", x_in,
                    qlinear(x_in, layer_w["up_proj"]), adapter_ids)
    if spec.mlp_bias:
        gate = gate + layer_w["gate_bias"]
        up = up + layer_w["up_bias"]
    inter = _shard(act(gate) * up, AXIS_DP, None, AXIS_MP)
    y = apply_lora(spec.lora, layer_w, "down_proj", inter,
                   _row_parallel_out(spec, inter, layer_w["down_proj"],
                                     phase), adapter_ids)
    if spec.mlp_bias:
        y = y + layer_w["down_bias"]
    return y


def _attn_block(spec: DecoderSpec, h, layer_w, k_full, v_full, li, ai,
                is_local, seq_ids, positions, phase: str, *,
                identity_seq_ids=False, arange_positions=False,
                slot_mapping=None, block_table=None, adapter_ids=None,
                kv_view=None, prefill_lens=None, side=None,
                mixed_local=None):
    """The attention half of a layer: q/k/v projections, cache write, the
    phase-appropriate attention compute (Pallas kernel or XLA), and the
    output projection. ``h`` is the already-normed block input (B, T, H).
    Exposed (like ``run_layer_slice``) so families with non-standard block
    structures — the hybrid attention+SSM layers of Falcon-H1
    (reference: contrib/models/Falcon-H1-0.5B-Instruct/src/
    modeling_falcon_h1.py FalconH1DecoderLayer) — can stitch it next to
    their own temporal-mixing blocks.

    Returns (attn_h, k_full, v_full, pending): attn_h the post-o_proj
    hidden delta, pending the chunked-decode side-buffer pair (None unless
    ``side`` is set)."""
    g = spec.gqa
    dtype = h.dtype
    off = spec.norm_offset
    if mixed_local is not None:
        # mixed per-layer cache (gpt-oss): the local/global choice is
        # STATIC per unrolled layer — the local mask is rolling-shaped (W
        # slots) and cannot be where-selected against the global one
        if mixed_local:
            cos, sin, mask = ai["cos_l"], ai["sin_l"], ai["mask_l"]
        else:
            cos, sin, mask = ai["cos"], ai["sin"], ai["mask"]
    elif "cos_l" in ai:
        cos = jnp.where(is_local, ai["cos_l"], ai["cos"])
        sin = jnp.where(is_local, ai["sin_l"], ai["sin"])
        mask = jnp.where(is_local, ai["mask_l"], ai["mask"])
    else:
        cos, sin, mask = ai["cos"], ai["sin"], ai["mask"]
    sink = layer_w["sink"] if spec.attn_sink else None

    def _alibi_for(n_kv):
        # kv slot i holds absolute position i on every contiguous path
        if not spec.alibi:
            return None
        return (layer_w["alibi_slopes"],
                jnp.arange(n_kv, dtype=jnp.int32)[None, :])
    pending = None
    if spec.mla is not None:
        q, k, v = _mla_qkv(spec, h, layer_w, cos, sin)
    else:
        qkv = qlinear(h, layer_w["qkv_proj"])
        if spec.qkv_bias:
            qkv = qkv + layer_w["qkv_bias"]
        q, k, v = jnp.split(qkv, [spec.q_size, spec.q_size + spec.kv_size],
                            axis=-1)
        q = apply_lora(spec.lora, layer_w, "q_proj", h, q, adapter_ids)
        k = apply_lora(spec.lora, layer_w, "k_proj", h, k, adapter_ids)
        v = apply_lora(spec.lora, layer_w, "v_proj", h, v, adapter_ids)
        if spec.qk_norm_full:
            # olmo2: RMSNorm over the whole projection, pre head-split
            q = rms_norm(q, layer_w["q_norm"], spec.rms_eps, off)
            k = rms_norm(k, layer_w["k_norm"], spec.rms_eps, off)
        if spec.qkv_clip is not None:
            q = jnp.clip(q, -spec.qkv_clip, spec.qkv_clip)
            k = jnp.clip(k, -spec.qkv_clip, spec.qkv_clip)
            v = jnp.clip(v, -spec.qkv_clip, spec.qkv_clip)
        # CP prefill: Q seq-sharded over "cp", KV forced seq-replicated —
        # GSPMD then emits the all-gather-KV pattern of the reference
        # (attention_base.py:548-563)
        q_seq_axis = AXIS_CP if (spec.cp_prefill and phase == "prefill") else None
        q = _shard(_split_heads(q, g.num_q_heads, spec.head_dim),
                   AXIS_DP, q_seq_axis, AXIS_MP, None)
        k = _shard(_split_heads(k, g.num_kv_heads, spec.head_dim), AXIS_DP, None, AXIS_MP, None)
        v = _shard(_split_heads(v, g.num_kv_heads, spec.head_dim), AXIS_DP, None, AXIS_MP, None)
        if spec.qk_norm and not spec.qk_norm_after_rope:
            if spec.qk_norm_type == "layernorm":
                q = layer_norm(q, layer_w["q_norm"], layer_w["q_norm_b"],
                               spec.rms_eps)
                k = layer_norm(k, layer_w["k_norm"], layer_w["k_norm_b"],
                               spec.rms_eps)
            else:
                q = rms_norm(q, layer_w["q_norm"], spec.rms_eps, off)
                k = rms_norm(k, layer_w["k_norm"], spec.rms_eps, off)
        q = apply_rope(q, cos, sin, interleaved=spec.rope_interleaved)
        k = apply_rope(k, cos, sin, interleaved=spec.rope_interleaved)
        if spec.qk_norm and spec.qk_norm_after_rope:
            if spec.qk_norm_type == "layernorm":
                q = layer_norm(q, layer_w["q_norm"], layer_w["q_norm_b"],
                               spec.rms_eps)
                k = layer_norm(k, layer_w["k_norm"], layer_w["k_norm_b"],
                               spec.rms_eps)
            else:
                q = rms_norm(q, layer_w["q_norm"], spec.rms_eps, off)
                k = rms_norm(k, layer_w["k_norm"], spec.rms_eps, off)
        if spec.qk_l2_norm:
            # llama4: weightless L2 norm AFTER rope, rope (local) layers only
            def _l2(x):
                xf = x.astype(jnp.float32)
                n = xf * jax.lax.rsqrt(
                    jnp.mean(xf * xf, axis=-1, keepdims=True) + spec.rms_eps)
                return n.astype(x.dtype)
            if spec.layer_pattern is not None:
                q = jnp.where(is_local, _l2(q), q)
                k = jnp.where(is_local, _l2(k), k)
            else:
                q, k = _l2(q), _l2(k)
        if spec.attn_temp is not None:
            # llama4 NoPE temperature tuning (reference:
            # modeling_llama4_text.py attn_temperature_tuning; HF
            # attn_scales = log1p(floor((pos+1)/floor_scale))*scale + 1)
            floor_scale, a_scale = spec.attn_temp
            pos_f = positions.astype(jnp.float32)
            scales = (jnp.log1p(jnp.floor((pos_f + 1.0) / floor_scale))
                      * a_scale + 1.0)[:, :, None, None]
            q_t = (q.astype(jnp.float32) * scales).astype(q.dtype)
            q = jnp.where(is_local, q, q_t) \
                if spec.layer_pattern is not None else q_t

    if phase == "paged":
        from ..modules import block_kv_cache as bkv
        k_full = bkv.write_slots_at_layer(
            k_full, kv.quantize_kv(k, k_full.dtype, spec.kv_scale), li,
            slot_mapping)
        v_full = bkv.write_slots_at_layer(
            v_full, kv.quantize_kv(v, v_full.dtype, spec.kv_scale), li,
            slot_mapping)
        # ragged paged decode kernel (reference: DMA-skipping TKG attention
        # over the block layout, attention_base.py:1186-1382): reads only
        # each row's LIVE pages through the block table — the gather path
        # below materializes the whole table per layer per token. Default-on
        # for single-token paged decode (decode_kernel None/True).
        use_pkernel = (h.shape[1] == 1
                       and not spec.alibi
                       and spec.decode_kernel is not False
                       and decode_attention.supports(spec, 1)
                       and (k_full.dtype == dtype
                            or decode_attention.quantized_cache_ok(
                                k_full.dtype.name)))
        if use_pkernel:
            if spec.layer_pattern is not None:
                win = jnp.where(is_local, spec.sliding_window, 0)
            else:
                win = jnp.asarray(spec.sliding_window, jnp.int32)
            kernel_out = decode_attention.paged_dispatch(
                q[:, 0], k_full, v_full, k[:, 0], v[:, 0], li,
                positions[:, 0], block_table, scale=spec.scale, window=win,
                soft_cap=spec.attn_soft_cap, sink=sink,
                kv_scale=spec.kv_scale,
                interpret=jax.default_backend() != "tpu")
            if kernel_out is None:
                use_pkernel = False
            else:
                attn_out = kernel_out[:, None]
        if not use_pkernel:
            k_all = kv.dequantize_kv(
                bkv.gather_block_kv(bkv.read_layer(k_full, li), block_table),
                dtype, spec.kv_scale)
            v_all = kv.dequantize_kv(
                bkv.gather_block_kv(bkv.read_layer(v_full, li), block_table),
                dtype, spec.kv_scale)
            attn_out = attn_ops.mha(q, k_all, v_all, mask, spec.scale,
                                    logits_soft_cap=spec.attn_soft_cap,
                                    sink=sink,
                                    alibi=_alibi_for(k_all.shape[1]))
    elif phase == "prefill":
        # flash kernel requirements beyond supports(): per-row positions must
        # be arange (the kernel rebuilds causality from array indices — an
        # offset/chunked prefill must use the mask path), and the
        # window/sink must be uniform across layers (static kernel).
        # dispatch_prefill shard_maps over the model-parallel axes for tp>1.
        kernel_out = None
        if (spec.flash_prefill and arange_positions
                and spec.layer_pattern is None and not spec.attn_sink
                and not spec.alibi
                and not spec.bidir_image_attn
                and spec.mla is None and not spec.cp_prefill
                and not spec.seq_parallel
                and flash_attention.supports(
                    q.shape[1], spec.head_dim, has_sink=False, chunk=0)):
            kernel_out = flash_attention.dispatch_prefill(
                q, k, v, scale=spec.scale, causal=True,
                window=spec.sliding_window, soft_cap=spec.attn_soft_cap,
                interpret=jax.default_backend() != "tpu")
        if kernel_out is not None:
            attn_out = kernel_out
        else:
            # prefill kv positions = the window's own positions
            al = ((layer_w["alibi_slopes"], positions)
                  if spec.alibi else None)
            attn_out = attn_ops.mha(q, k, v, mask, spec.scale,
                                    logits_soft_cap=spec.attn_soft_cap,
                                    sink=sink, alibi=al)
        if spec.rolling_window and prefill_lens is not None:
            # rolling prefill write: only the LAST w positions of each row
            # land (earlier ones would alias the same slots and the scatter
            # order is undefined); padded positions past seq_len are dropped
            # so they cannot clobber live slots through the modulo
            w_c = k_full.shape[4]
            valid = ((positions >= prefill_lens[:, None] - w_c)
                     & (positions < prefill_lens[:, None]))
            eff = jnp.where(valid, positions % w_c, k_full.shape[4] + 1)
            k_full = kv.write_tokens_at_layer(
                k_full, kv.quantize_kv(k, k_full.dtype, spec.kv_scale),
                li, seq_ids, eff, k_transposed=True)
            v_full = kv.write_tokens_at_layer(
                v_full, kv.quantize_kv(v, v_full.dtype, spec.kv_scale),
                li, seq_ids, eff)
        else:
            k_full = kv.write_prefill_at_layer(
                k_full, kv.quantize_kv(k, k_full.dtype, spec.kv_scale),
                li, seq_ids,
                identity_seq_ids=identity_seq_ids and arange_positions,
                k_transposed=True)
            v_full = kv.write_prefill_at_layer(
                v_full, kv.quantize_kv(v, v_full.dtype, spec.kv_scale),
                li, seq_ids,
                identity_seq_ids=identity_seq_ids and arange_positions)
    else:
        pending = None
        if side is not None:
            # chunked decode (ops/attention.mha_decode_merged): the step's
            # K/V are handed back as PENDING — run_layer_slice batches all
            # layers' side-buffer writes into one update pair per step; the
            # BIG cache is read-only inside the decode scan and committed
            # once per chunk
            pending = (k, v)
        else:
            roll_w = (k_full.shape[4]
                      if (spec.rolling_window or mixed_local) else 0)
            k_full = kv.write_tokens_at_layer(
                k_full, kv.quantize_kv(k, k_full.dtype, spec.kv_scale),
                li, seq_ids, positions, window=roll_w, k_transposed=True)
            v_full = kv.write_tokens_at_layer(
                v_full, kv.quantize_kv(v, v_full.dtype, spec.kv_scale),
                li, seq_ids, positions, window=roll_w)
        use_kernel = (side is None
                      and not mixed_local
                      and not spec.alibi
                      and spec.decode_kernel is not False
                      and decode_attention.supports(spec, h.shape[1])
                      and not spec.rolling_window
                      and identity_seq_ids
                      and h.shape[0] == k_full.shape[1]
                      and (k_full.dtype == dtype
                           or decode_attention.quantized_cache_ok(
                               k_full.dtype.name))
                      and not spec.flash_decoding)
        if use_kernel and spec.decode_kernel is None:
            # auto admission (reference analog: flash-strategy heuristics,
            # attention_base.py:985-1034): the kernel wins where the XLA
            # path must stream cache slots the mask discards anyway —
            # sliding-window / alternating-local patterns and learned-sink
            # softmax (XLA's sink path pays a concat + second softmax).
            # Plain full attention with kv_view-bucketed reads measured
            # FASTER on the XLA path (v5e: 0.148 vs 0.231 ms/step at
            # S=1024 full-live), so auto keeps it off there.
            use_kernel = (spec.attn_sink or spec.sliding_window > 0
                          or spec.layer_pattern is not None)
        if use_kernel:
            # fused Pallas decode attention over the stacked cache: reads
            # only the live prefix of each row (DMA block elision) and folds
            # the active token in-registers — the cache row written above is
            # masked out (kpos < pos), so write order is irrelevant.
            # dispatch() shard_maps over the mesh's dp/mp axes for tp>1.
            if spec.layer_pattern is not None:
                win = jnp.where(is_local, spec.sliding_window, 0)
            else:
                win = jnp.asarray(spec.sliding_window, jnp.int32)
            kernel_out = decode_attention.dispatch(
                q[:, 0], k_full, v_full, k[:, 0], v[:, 0], li,
                positions[:, 0], scale=spec.scale, window=win,
                soft_cap=spec.attn_soft_cap, sink=sink,
                kv_scale=spec.kv_scale,
                interpret=jax.default_backend() != "tpu")
            if kernel_out is None:        # heads not shardable on this mesh
                use_kernel = False
            else:
                attn_out = kernel_out[:, None]
        if not use_kernel:
            # native-layout reads: K transposed (B, H, D, S), V (B, H, S,
            # D) — each attention einsum contracts its operand in place
            # (any shared layout costs a materialized relayout of the live
            # cache per layer per step)
            view = kv_view if (kv_view is not None
                               and kv_view < v_full.shape[3]) else None
            if isinstance(li, int) and view is not None:
                # decode unrolls layers with static indices: fold the layer
                # AND seq-bucket slice into ONE static slice so XLA stages
                # only the live prefix (two chained slices staged the full
                # row first — measured 2x the staging bytes)
                lb, hb, db = (k_full.shape[1], k_full.shape[2],
                              k_full.shape[3])
                k_layer = jax.lax.slice(
                    k_full, (li, 0, 0, 0, 0),
                    (li + 1, lb, hb, db, view))[0]       # (B, H, D, view)
                v_layer = jax.lax.slice(
                    v_full, (li, 0, 0, 0, 0),
                    (li + 1, lb, hb, view, v_full.shape[4]))[0]
            else:
                k_layer = kv.read_layer_hl(k_full, li)   # (B, H, D, S)
                v_layer = kv.read_layer_hl(v_full, li)   # (B, H, S, D)
                if view is not None:
                    # decode seq bucket: read only the live prefix (the mask
                    # is built against the same kv_view length)
                    k_layer = k_layer[:, :, :, :view]
                    v_layer = v_layer[:, :, :view]
            if identity_seq_ids and h.shape[0] == k_full.shape[1]:
                # static guarantee that seq_ids == arange (no continuous
                # batching): skip the row-gather copy of the whole cache
                k_all = kv.dequantize_kv(k_layer, dtype, spec.kv_scale)
                v_all = kv.dequantize_kv(v_layer, dtype, spec.kv_scale)
            else:
                k_all = kv.dequantize_kv(
                    kv.gather_cache_rows(k_layer, seq_ids), dtype,
                    spec.kv_scale)
                v_all = kv.dequantize_kv(
                    kv.gather_cache_rows(v_layer, seq_ids), dtype,
                    spec.kv_scale)
            if side is not None:
                # 'mask' here is the PRIOR mask (chunk slots excluded by the
                # chunk loop); earlier chunk tokens enter through the side
                # buffer with their own mask, the active token in-register
                mask_side = ai["mask_side"]
                attn_out = attn_ops.mha_decode_merged(
                    q, k_all, v_all, mask, side[0][li], side[1][li],
                    mask_side, k.astype(dtype), v.astype(dtype), spec.scale,
                    logits_soft_cap=spec.attn_soft_cap, sink=sink)
            else:
                attn_out = attn_ops.mha_hl(q, k_all, v_all, mask, spec.scale,
                                           logits_soft_cap=spec.attn_soft_cap,
                                           sink=sink,
                                           alibi=_alibi_for(
                                               v_all.shape[2]))

    attn_out = attn_out.reshape(h.shape[0], h.shape[1], -1)
    h = _row_parallel_out(spec, attn_out, layer_w["o_proj"], phase)
    if spec.mla is None:
        h = apply_lora(spec.lora, layer_w, "o_proj", attn_out, h, adapter_ids)
    if spec.o_bias:
        h = h + layer_w["o_bias"]
    return h, k_full, v_full, pending


def _deepstack_add(hidden, deepstack, deepstack_mask):
    """Add this layer's deepstack visual features at the image-token
    positions (reference: qwen3-vl deepstack, models/model_base.py:1374-1387;
    layers past the deepstack depth carry zeros)."""
    if deepstack is None or deepstack_mask is None:
        return hidden
    gi = jnp.clip(jnp.cumsum(deepstack_mask, axis=1) - 1, 0,
                  deepstack.shape[1] - 1)
    img = jnp.take_along_axis(deepstack.astype(hidden.dtype),
                              gi[..., None], axis=1)
    return hidden + jnp.where(deepstack_mask[..., None], img, 0)


def run_layers(spec: DecoderSpec, params, cache, hidden, ai,
               seq_ids, positions, phase: str,
               identity_seq_ids: bool = False,
               arange_positions: bool = False,
               slot_mapping=None, block_table=None,
               adapter_ids=None, replacements=None, kv_view: int = None,
               deepstack=None, deepstack_mask=None, prefill_lens=None,
               side=None, chunk_idx=None):
    """lax.scan over the stacked layer weights.

    Replaces the reference's per-layer Python loop
    (models/model_base.py:1216-1469 get_model_output).
    ai: attn_inputs() bundle; replacements: {point: (L,B,T,H),
    point+"_on": (L,)} golden-injection arrays.
    side: chunked-decode side buffers (see ``decode_loop``) — when set the
    big cache is read-only and the return gains a 4th element, the updated
    side pair.
    Returns (hidden, new_cache, captured[, side]) — captured = {} unless
    spec.capture names per-layer points (then each is stacked (L, ...)).
    """
    if spec.ssm is not None:
        if any(x is not None for x in (slot_mapping, block_table,
                                       replacements, deepstack, side)):
            raise NotImplementedError(
                "recurrent/hybrid stacks support the contiguous prefill + "
                "decode paths only (no paged layout, tensor replacement, "
                "deepstack, or chunked side-buffer decode)")
        return run_layers_ssm(
            spec, params, cache, hidden, ai, seq_ids, positions, phase,
            identity_seq_ids=identity_seq_ids, adapter_ids=adapter_ids,
            kv_view=kv_view, prefill_lens=prefill_lens)
    is_local = jnp.asarray(spec.layer_pattern if spec.layer_pattern is not None
                           else (False,) * spec.num_layers)
    rep = replacements or {}

    def sl(lo, hi):
        return jax.tree.map(lambda a: a[lo:hi], rep)

    kw = dict(seq_ids=seq_ids, positions=positions, phase=phase,
              identity_seq_ids=identity_seq_ids,
              arange_positions=arange_positions, slot_mapping=slot_mapping,
              block_table=block_table, adapter_ids=adapter_ids,
              replacements=replacements, kv_view=kv_view,
              deepstack_mask=deepstack_mask, prefill_lens=prefill_lens,
              chunk_idx=chunk_idx)

    def unpack(res, side_now):
        if side_now is not None:
            return res
        return res + (None,)

    if spec.moe is not None and spec.first_dense > 0:
        # mixed stacks (deepseek first_k_dense_replace): dense layers then
        # MoE layers, two scans carrying one contiguous cache
        nd = spec.first_dense
        L = spec.num_layers
        ds = deepstack
        hidden, kf, vf, c1, side = unpack(run_layer_slice(
            spec, params["layers"], cache["k"], cache["v"], hidden, ai,
            cache_offset=0, is_local=is_local[:nd], rep=sl(0, nd),
            mlp_kind="dense", deepstack=None if ds is None else ds[:nd],
            side=side, **kw), side)
        hidden, kf, vf, c2, side = unpack(run_layer_slice(
            spec, params["moe_layers"], kf, vf, hidden, ai,
            cache_offset=nd, is_local=is_local[nd:], rep=sl(nd, L),
            mlp_kind="moe", deepstack=None if ds is None else ds[nd:],
            side=side, **kw), side)
        caps = {k: jnp.concatenate([c1[k], c2[k]]) for k in c1}
        if side is not None:
            return hidden, {"k": kf, "v": vf}, caps, side
        return hidden, {"k": kf, "v": vf}, caps

    if spec.moe is not None and spec.moe_pattern is not None:
        # interleaved dense/MoE stacks (llama4 interleave_moe_layer_step):
        # walk contiguous runs of equal kind; cache layer index stays the
        # absolute layer position
        pat = spec.moe_pattern
        L = spec.num_layers
        runs = []
        s0 = 0
        for i in range(1, L + 1):
            if i == L or pat[i] != pat[s0]:
                runs.append((s0, i - s0, pat[s0]))
                s0 = i
        stack_pos = {"dense": 0, "moe": 0}
        kf, vf = cache["k"], cache["v"]
        caps_parts = []
        for start, count, is_moe in runs:
            kind = "moe" if is_moe else "dense"
            stack = params["moe_layers" if is_moe else "layers"]
            j0 = stack_pos[kind]
            stack_pos[kind] += count
            seg = jax.tree.map(lambda a: a[j0:j0 + count], stack)
            hidden, kf, vf, c, side = unpack(run_layer_slice(
                spec, seg, kf, vf, hidden, ai, cache_offset=start,
                is_local=is_local[start:start + count],
                rep=sl(start, start + count), mlp_kind=kind,
                deepstack=(None if deepstack is None
                           else deepstack[start:start + count]),
                side=side, **kw), side)
            caps_parts.append(c)
        caps = ({k: jnp.concatenate([c[k] for c in caps_parts])
                 for k in caps_parts[0]} if caps_parts and caps_parts[0]
                else {})
        if side is not None:
            return hidden, {"k": kf, "v": vf}, caps, side
        return hidden, {"k": kf, "v": vf}, caps

    L = spec.num_layers
    hidden, kf, vf, caps, side = unpack(run_layer_slice(
        spec, params["layers"], cache["k"], cache["v"], hidden, ai,
        cache_offset=0, is_local=is_local, rep=rep, mlp_kind=None,
        deepstack=deepstack, side=side, **kw), side)
    if side is not None:
        return hidden, {"k": kf, "v": vf}, caps, side
    return hidden, {"k": kf, "v": vf}, caps


def run_layer_slice(spec: DecoderSpec, layer_params, kf, vf, hidden, ai, *,
                    cache_offset: int, is_local, rep, mlp_kind,
                    seq_ids, positions, phase,
                    identity_seq_ids=False, arange_positions=False,
                    slot_mapping=None, block_table=None, adapter_ids=None,
                    replacements=None, kv_view=None, deepstack=None,
                    deepstack_mask=None, prefill_lens=None,
                    side=None, chunk_idx=None):
    """Run one contiguous run of stacked layers against the full cache
    (cache layer index = scan index + ``cache_offset``). Exposed so families
    with interleaved non-standard layers (mllama cross-attention decoder)
    can stitch standard segments around their own blocks.

    Decode (T = 1) UNROLLS the layer loop instead of scanning: with a
    static layer index, each layer's cache read is a lazily-fused static
    slice; under lax.scan the dynamic layer index forces XLA to
    MATERIALIZE every layer's cache slice (plus a relayout copy for the
    attention dot) every step — measured ~0.25 ms/step of pure copy
    traffic on v5e at B=2/S=1024/16 layers. Prefill keeps the scan (one
    compiled body, O(1) compile time in depth; the per-layer copies are
    amortized over the whole window there)."""
    n = jax.tree.leaves(layer_params)[0].shape[0]

    if phase == "decode" and jax.tree.leaves(hidden)[0].shape[1] == 1:
        caps_list = []
        pend = []
        for i in range(n):
            layer_w = jax.tree.map(lambda a: a[i], layer_params)
            res = _layer_body(
                spec, hidden, layer_w, kf, vf, i + cache_offset, ai,
                is_local[i], seq_ids, positions, phase, identity_seq_ids,
                arange_positions, slot_mapping, block_table, mlp_kind,
                adapter_ids,
                (jax.tree.map(lambda a: a[i], rep)
                 if replacements is not None else None),
                kv_view=kv_view, prefill_lens=prefill_lens,
                side=side)
            if side is not None:
                hidden, kf, vf, caps_i, pending = res
                pend.append(pending)
            else:
                hidden, kf, vf, caps_i = res
            caps_list.append(caps_i)
        caps = ({k: jnp.stack([c[k] for c in caps_list])
                 for k in caps_list[0]} if caps_list and caps_list[0] else {})
        if side is not None:
            # ONE side-buffer update pair per step for the whole layer run
            # (32 per-layer updates force a write-friendly layout onto the
            # scan-carried side buffers and relayout the reads)
            sk, sv = side
            k_stack = jnp.stack([p[0][:, 0] for p in pend])   # (n, B, H, D)
            v_stack = jnp.stack([p[1][:, 0] for p in pend])
            sk = jax.lax.dynamic_update_slice(
                sk, k_stack[..., None].astype(sk.dtype),
                (cache_offset, 0, 0, 0, chunk_idx))
            sv = jax.lax.dynamic_update_slice(
                sv, v_stack[:, :, :, None, :].astype(sv.dtype),
                (cache_offset, 0, 0, chunk_idx, 0))
            return hidden, kf, vf, caps, (sk, sv)
        return hidden, kf, vf, caps

    def body(carry, xs):
        h, k_, v_ = carry
        if deepstack is not None:
            layer_w, loc, rp, li, ds = xs
        else:
            layer_w, loc, rp, li = xs
            ds = None
        h, k_, v_, caps = _layer_body(
            spec, h, layer_w, k_, v_, li + cache_offset, ai, loc, seq_ids,
            positions, phase, identity_seq_ids, arange_positions,
            slot_mapping, block_table, mlp_kind, adapter_ids,
            rp if replacements is not None else None, kv_view=kv_view,
            deepstack=ds, deepstack_mask=deepstack_mask,
            prefill_lens=prefill_lens)
        return (h, k_, v_), caps

    xs = (layer_params, is_local, rep, jnp.arange(n, dtype=jnp.int32))
    if deepstack is not None:
        xs = xs + (deepstack,)
    (hidden, kf, vf), caps = jax.lax.scan(body, (hidden, kf, vf), xs)
    return hidden, kf, vf, caps


def run_layers_ssm(spec: DecoderSpec, params, cache, hidden, ai,
                   seq_ids, positions, phase: str, *,
                   identity_seq_ids=False, adapter_ids=None, kv_view=None,
                   prefill_lens=None):
    """Unrolled layer walk for recurrent/hybrid stacks (reference:
    contrib Falcon-H1 FalconH1DecoderLayer — parallel mamba+attention;
    contrib recurrentgemma RecurrentGemmaDecoderLayer — rec/rec/attn
    pattern). The KV cache covers only the attention-bearing layers; the
    recurrent state rides the same cache dict as stacked conv tails +
    SSM states, updated with static per-layer indices.

    Every layer shares the sequential residual shape: pre-norm temporal
    block(s) → residual add → pre-norm MLP → residual add; the temporal
    block is attention, the SSM, or (parallel hybrid) their sum.
    """
    s = spec.ssm
    pat = spec.resolved_ssm_pattern
    if phase not in ("prefill", "decode"):
        raise NotImplementedError(
            f"recurrent stacks do not support the {phase!r} phase")
    # the SSM residual walk below hard-codes the plain pre-norm shape; a
    # hybrid family that also sets these spec knobs would run silently wrong
    if spec.residual_multiplier != 1.0 or spec.sandwich_norm:
        raise NotImplementedError(
            "run_layers_ssm implements the plain pre-norm residual shape "
            f"only (got residual_multiplier={spec.residual_multiplier}, "
            f"sandwich_norm={spec.sandwich_norm}); teach the SSM layer walk "
            "these knobs before combining them with a recurrent stack")
    if phase == "decode" and hidden.shape[1] != 1:
        raise NotImplementedError(
            "recurrent stacks decode one token per step (no speculation "
            "windows / multi-token verify)")
    kf, vf = cache["k"], cache["v"]
    state_keys = [k for k in ("conv_x", "conv_bc", "ssm") if k in cache]
    new_state = {k: cache[k] for k in state_keys}
    not_local = jnp.asarray(False)
    attn_i = 0
    ssm_i = 0
    for i in range(spec.num_layers):
        has_ssm = bool(pat[i])
        has_attn = spec.ssm_parallel or not has_ssm
        lw = jax.tree.map(lambda a: a[i], params["layers"])
        if has_attn and "attn_layers" in params:
            ja = attn_i
            lw = {**lw, **jax.tree.map(lambda a: a[ja], params["attn_layers"])}
        if has_ssm and "ssm_layers" in params:
            js = ssm_i
            lw = {**lw, **jax.tree.map(lambda a: a[js], params["ssm_layers"])}
        h = _norm(spec, hidden, lw["input_norm"],
                  lw.get("input_norm_b") if spec.norm_bias else None)
        t_out = None
        if has_attn:
            a_out, kf, vf, _ = _attn_block(
                spec, h, lw, kf, vf, attn_i, ai, not_local, seq_ids,
                positions, phase, identity_seq_ids=identity_seq_ids,
                arange_positions=(phase == "prefill"),
                adapter_ids=adapter_ids, kv_view=kv_view,
                prefill_lens=prefill_lens)
            t_out = a_out
            attn_i += 1
        if has_ssm:
            st = {k: new_state[k][ssm_i] for k in state_keys}
            s_out, st_new = ssm_mod.ssm_block(
                s, lw, h, st, phase=phase, seq_lens=prefill_lens,
                positions=positions)
            for k2, v2 in st_new.items():
                new_state[k2] = new_state[k2].at[ssm_i].set(
                    v2.astype(new_state[k2].dtype))
            t_out = s_out if t_out is None else t_out + s_out
            ssm_i += 1
        hidden = hidden + _shard(t_out, AXIS_DP, None, None)
        h2 = _norm(spec, hidden, lw["post_norm"],
                   lw.get("post_norm_b") if spec.norm_bias else None)
        hidden = hidden + _shard(
            _mlp_block(spec, h2, lw, "dense", adapter_ids),
            AXIS_DP, None, None)
    return hidden, {"k": kf, "v": vf, **new_state}, {}


def run_layers_mixed_decode(spec: DecoderSpec, params, cache, hidden, ai,
                            seq_ids, positions, kv_view=None,
                            adapter_ids=None, identity_seq_ids=True):
    """Decode layer loop over the MIXED cache (reference: gpt-oss per-layer
    KV sizes, modules/kvcache/gpt_oss_kv_cache_manager.py): local layers
    read/write the rolling {"k_l","v_l"} stacks (W slots), global layers
    the full {"k","v"} stacks — selected statically per unrolled layer.
    identity_seq_ids=False (continuous-batching serving): reads gather and
    writes scatter through seq_ids on both stack kinds."""
    lmap = kv.mixed_layer_map(spec.layer_pattern)
    kf, vf = cache["k"], cache["v"]
    kl, vl = cache["k_l"], cache["v_l"]
    caps_list = []
    for i in range(spec.num_layers):
        layer_w = jax.tree.map(lambda a: a[i], params["layers"])
        loc = bool(spec.layer_pattern[i])
        if loc:
            hidden, kl, vl, caps_i = _layer_body(
                spec, hidden, layer_w, kl, vl, lmap[i], ai,
                jnp.asarray(True), seq_ids, positions, "decode",
                identity_seq_ids=identity_seq_ids, adapter_ids=adapter_ids,
                mixed_local=True)
        else:
            hidden, kf, vf, caps_i = _layer_body(
                spec, hidden, layer_w, kf, vf, lmap[i], ai,
                jnp.asarray(False), seq_ids, positions, "decode",
                identity_seq_ids=identity_seq_ids, adapter_ids=adapter_ids,
                kv_view=kv_view, mixed_local=False)
        caps_list.append(caps_i)
    caps = ({k2: jnp.stack([c[k2] for c in caps_list])
             for k2 in caps_list[0]} if caps_list and caps_list[0] else {})
    return hidden, {"k": kf, "v": vf, "k_l": kl, "v_l": vl}, caps


def fold_mixed_prefill(spec: DecoderSpec, scratch_cache, cache, seq_lens,
                       seq_ids=None):
    """Mixed-cache prefill epilogue: copy the scratch full-length rows of
    GLOBAL layers into the persistent full stacks and FOLD local layers'
    rows into the rolling stacks (reference: gpt-oss manager CTE path).
    seq_ids (b,) — continuous-batching target rows; None = rows [0, b)."""
    pat = spec.layer_pattern
    g_idx = [i for i, x in enumerate(pat) if not x]
    l_idx = [i for i, x in enumerate(pat) if x]
    gi = jnp.asarray(g_idx, jnp.int32)
    li = jnp.asarray(l_idx, jnp.int32)
    W = cache["k_l"].shape[4]
    kl_fold = kv.fold_rolling_prefill(
        scratch_cache["k"][li], seq_lens, W, k_transposed=True)
    vl_fold = kv.fold_rolling_prefill(scratch_cache["v"][li], seq_lens, W)
    new = dict(cache)
    if seq_ids is not None:
        # continuous batching: scatter the prefilled rows at their cache
        # slots; the scratch covers only the ctx-bucket slots [0, sb)
        # (reference: single-seq CTE update, kv_cache_manager.py:483)
        sb = scratch_cache["k"].shape[4]
        new["k"] = cache["k"].at[:, seq_ids, :, :, :sb].set(
            scratch_cache["k"][gi])
        new["v"] = cache["v"].at[:, seq_ids, :, :sb, :].set(
            scratch_cache["v"][gi])
        new["k_l"] = cache["k_l"].at[:, seq_ids].set(kl_fold)
        new["v_l"] = cache["v_l"].at[:, seq_ids].set(vl_fold)
        return new
    new["k"] = jax.lax.dynamic_update_slice(
        cache["k"], scratch_cache["k"][gi], (0, 0, 0, 0, 0))
    new["v"] = jax.lax.dynamic_update_slice(
        cache["v"], scratch_cache["v"][gi], (0, 0, 0, 0, 0))
    # partial-batch prefill (2-D batch buckets): update rows [0, b) in
    # place — replacing the stacks would change the cache pytree shape
    new["k_l"] = jax.lax.dynamic_update_slice(cache["k_l"], kl_fold,
                                              (0, 0, 0, 0, 0))
    new["v_l"] = jax.lax.dynamic_update_slice(cache["v_l"], vl_fold,
                                              (0, 0, 0, 0, 0))
    return new


# ---------------------------------------------------------------------------
# Step graphs
# ---------------------------------------------------------------------------

def _embed(spec: DecoderSpec, params, input_ids, position_ids=None):
    h = params["embed"][input_ids]        # sharded-vocab gather; XLA SPMD handles
    if spec.embed_scale is not None:
        h = (h.astype(jnp.float32) * spec.embed_scale).astype(h.dtype)
    if spec.embed_norm:
        # bloom word_embeddings_layernorm
        h = layer_norm(h, params["embed_norm"], params["embed_norm_b"],
                       spec.rms_eps)
    if spec.learned_pos and position_ids is not None:
        # gpt2 wpe: learned absolute position table added to token embeds
        h = h + params["pos_embed"][jnp.clip(position_ids, 0,
                                             spec.learned_pos - 1)]
    return _shard(h, AXIS_DP, None, None)


def _lm_head(spec: DecoderSpec, params, hidden):
    h = (hidden if spec.skip_final_norm else
         _norm(spec, hidden, params["final_norm"], params.get("final_norm_b")))
    w = params["embed"].T if spec.tie_word_embeddings else params["lm_head"]
    logits = (h @ w).astype(jnp.float32)
    if spec.lm_head_bias and "lm_head_b" in params:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    if spec.logits_divide:
        logits = logits / spec.logits_divide
    if spec.logits_soft_cap:
        logits = spec.logits_soft_cap * jnp.tanh(logits / spec.logits_soft_cap)
    logits = sampling_ops.mask_padded_logits(logits, spec.padded_vocab - spec.vocab_size)
    return _shard(logits, AXIS_DP, None, AXIS_MP)


def context_encoding_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                          input_ids, position_ids, seq_ids, seq_lens,
                          sampling_params, rng, adapter_ids=None,
                          replacements=None, image_embeds=None,
                          image_mask=None, rope_position_ids=None,
                          deepstack_embeds=None):
    """Prefill graph (reference submodel tag ``context_encoding_model``).

    input_ids (B, S_bucket) right-padded; seq_lens (B,) true lengths.
    image_embeds (B, N_img, H) + image_mask (B, S): multimodal prefill —
    projected vision features replace the embeddings at the image-token
    positions, in order (reference: image-to-text merge,
    models/image_to_text_model_base.py + deepstack embeds
    model_base.py:1374-1387).
    Returns dict(tokens (B,), last_logits (B, V) [optional], cache).
    """
    ai = attn_inputs(spec, position_ids, lambda w, c=0: attn_ops.prefill_causal_mask(
        input_ids.shape[1], position_ids, window=w, chunk=c),
        rope_positions=rope_position_ids)
    # padded positions: mask rows beyond seq_len attend only to themselves —
    # harmless, their outputs are discarded.
    hidden = _embed(spec, params, input_ids, position_ids)
    if image_embeds is not None:
        # scatter the i-th image feature into the i-th image-token slot
        gather_idx = jnp.clip(jnp.cumsum(image_mask, axis=1) - 1, 0,
                              image_embeds.shape[1] - 1)
        img = jnp.take_along_axis(image_embeds.astype(hidden.dtype),
                                  gather_idx[..., None], axis=1)
        hidden = jnp.where(image_mask[..., None], img, hidden)
    if spec.bidir_image_attn and image_mask is not None:
        # OR a bidirectional overlay over each contiguous image-token span
        # onto BOTH mask variants (it overrides the sliding window too —
        # reference: HF gemma3 token_type_ids_mask_function applied to the
        # full and sliding mask kwargs alike)
        is_img = image_mask.astype(bool)
        new_start = jnp.logical_and(
            is_img, ~jnp.pad(is_img, ((0, 0), (1, 0)))[:, :-1])
        gid = jnp.cumsum(new_start.astype(jnp.int32), axis=1) - 1
        gid = jnp.where(is_img, gid, -1)
        overlay = jnp.logical_and(gid[:, :, None] >= 0,
                                  gid[:, :, None] == gid[:, None, :])
        for mk in ("mask", "mask_l"):
            if mk in ai:
                ai[mk] = jnp.logical_or(ai[mk], overlay)
    if spec.seq_parallel:
        # SP: shard the embedded sequence (reference: reduce-scatter of
        # embeddings, model_base.py:1482-1517)
        hidden = _shard(hidden, AXIS_DP, AXIS_CP, None)
    # context_encoding_step always feeds arange positions per row (the host
    # shim builds them); chunked/offset prefill variants must pass False
    if deepstack_embeds is not None:
        # deepstack (qwen3-vl): per-layer visual features injected into the
        # first K layers' hidden states at the image-token positions
        # (reference: models/model_base.py:1374-1387 deepstack embeds)
        K = deepstack_embeds.shape[0]
        pad_l = spec.num_layers - K
        deepstack_embeds = jnp.pad(
            deepstack_embeds.astype(hidden.dtype),
            ((0, pad_l), (0, 0), (0, 0), (0, 0)))
    persistent = cache
    identity = not tpu_cfg.is_continuous_batching
    if spec.mixed_kv:
        # mixed per-layer cache: prefill runs on a full-length SCRATCH for
        # every layer (identity rows — the fold scatters to the real rows);
        # the epilogue folds local layers into the rolling stacks
        # (reference: gpt_oss_kv_cache_manager.py CTE path)
        b, sb = input_ids.shape
        g = spec.gqa
        kdt = cache["k"].dtype
        cache = {"k": jnp.zeros((spec.num_layers, b, g.num_kv_heads,
                                 spec.head_dim, sb), kdt),
                 "v": jnp.zeros((spec.num_layers, b, g.num_kv_heads, sb,
                                 spec.v_head_dim), kdt)}
        identity = True
    hidden, new_cache, caps = run_layers(
        spec, params, cache, hidden, ai, seq_ids, position_ids, "prefill",
        identity_seq_ids=identity,
        arange_positions=True, adapter_ids=adapter_ids,
        replacements=replacements, deepstack=deepstack_embeds,
        deepstack_mask=image_mask, prefill_lens=seq_lens)
    if spec.mixed_kv:
        new_cache = fold_mixed_prefill(
            spec, new_cache, persistent, seq_lens,
            seq_ids=None if not tpu_cfg.is_continuous_batching else seq_ids)
    # last-token gather (reference: lm-head index + logit padding mask :987-999)
    idx = jnp.maximum(seq_lens - 1, 0)
    last_h = jnp.take_along_axis(hidden, idx[:, None, None].astype(jnp.int32), axis=1)
    logits = _lm_head(spec, params, last_h)[:, 0, :]
    # last hidden state feeds EAGLE draft fusion / medusa heads
    # (reference: EAGLE draft hidden-state fusion, model_base.py:1526-1592)
    out = {"cache": new_cache, "last_hidden": last_h[:, 0, :]}
    if tpu_cfg.output_logits:
        full_logits = _lm_head(spec, params, hidden)
        out["logits"] = full_logits[..., :spec.vocab_size]
    if tpu_cfg.output_full_hidden:
        out["hidden_states"] = hidden
    if caps:
        out["captured"] = caps
    out["tokens"] = sampling_ops.sample_dp(
        logits, tpu_cfg.on_device_sampling_config, sampling_params, rng)
    return out


def token_generation_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                          input_ids, position_ids, seq_ids,
                          sampling_params, rng, adapter_ids=None,
                          replacements=None, rope_position_ids=None,
                          kv_view: int = None):
    """Decode graph (reference submodel tag ``token_generation_model``).

    input_ids (B, T) with T = 1 (or speculation window).
    rope_position_ids (B, T, 3): optional M-RoPE 3-axis positions
    (reference: qwen2_vl rotary_position_ids plumbing,
    models/model_base.py:566-578).
    kv_view: static decode seq bucket — the graph READS only cache slots
    [0, kv_view), so early decode streams a fraction of the allocated cache
    (reference: TKG seq buckets, autobucketing.py:226; decode is HBM-bound
    so this is a direct throughput win). Writes still address the full cache.
    """
    cache_len = kv_view or kv.cache_len_of(cache)
    if spec.rolling_window:
        # rolling cache: slot != position; the mask maps slots back to the
        # positions they hold
        ai = attn_inputs(
            spec, position_ids,
            lambda w, c=0: attn_ops.rolling_decode_mask(position_ids,
                                                        cache_len),
            rope_positions=rope_position_ids)
    else:
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.decode_mask(
                             position_ids, cache_len, window=w, chunk=c),
                         rope_positions=rope_position_ids)
    hidden = _embed(spec, params, input_ids, position_ids)
    if spec.mixed_kv:
        # local layers' rolling stacks: slot != position, rolling mask
        # (reference: gpt-oss per-layer KV decode)
        ai["mask_l"] = attn_ops.rolling_decode_mask(
            position_ids, cache["k_l"].shape[4])
        hidden, new_cache, caps = run_layers_mixed_decode(
            spec, params, cache, hidden, ai, seq_ids, position_ids,
            kv_view=kv_view, adapter_ids=adapter_ids,
            identity_seq_ids=not tpu_cfg.is_continuous_batching)
    else:
        hidden, new_cache, caps = run_layers(
            spec, params, cache, hidden, ai, seq_ids, position_ids,
            "decode", identity_seq_ids=not tpu_cfg.is_continuous_batching,
            adapter_ids=adapter_ids, replacements=replacements,
            kv_view=kv_view)
    logits = _lm_head(spec, params, hidden)
    out = {"cache": new_cache}
    if caps:
        out["captured"] = caps
    if tpu_cfg.output_logits:
        out["logits"] = logits[..., :spec.vocab_size]
    out["tokens"] = sampling_ops.sample_dp(
        logits[:, -1, :], tpu_cfg.on_device_sampling_config, sampling_params, rng)
    return out


def token_generation_multi(spec: DecoderSpec, tpu_cfg: TpuConfig, params,
                           cache, input_ids, position_ids, seq_ids):
    """Decode forward over T tokens returning logits at EVERY position —
    the target-verify graph of fused speculation (reference: target model
    scoring all candidate tokens, model_base.py:2617-2642). Within-step
    causality falls out of the cache-write-then-attend order plus the
    position mask."""
    if spec.mixed_kv:
        raise NotImplementedError(
            "multi-token decode over the mixed per-layer cache is not "
            "supported; disable speculation or set mixed_kv=False")
    if spec.ssm is not None:
        raise NotImplementedError(
            "multi-token decode (speculation verify / windowed CTE) is not "
            "supported on recurrent/hybrid stacks")
    cache_len = kv.cache_len_of(cache)
    ai = attn_inputs(spec, position_ids, lambda w, c=0: attn_ops.decode_mask(
        position_ids, cache_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids, position_ids)
    hidden, new_cache, _ = run_layers(
        spec, params, cache, hidden, ai, seq_ids, position_ids,
        "decode", identity_seq_ids=not tpu_cfg.is_continuous_batching)
    logits = _lm_head(spec, params, hidden)
    return {"logits_all": logits[..., :spec.vocab_size], "cache": new_cache,
            "hidden": hidden}


def _coupled_mode(tpu_cfg: TpuConfig, row_seeds) -> bool:
    """True when the positionally coupled sampling stream is active:
    the config opts in (``do_sample`` + ``stream_seed``) AND the caller
    threaded per-row seeds. ``row_seeds=None`` keeps the legacy graphs
    byte-identical (an absent optional arg is an empty pytree)."""
    sc = tpu_cfg.on_device_sampling_config
    return (row_seeds is not None and sc is not None and sc.do_sample
            and sc.stream_seed is not None)


def paged_forward_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                       input_ids, position_ids, slot_mapping, block_table,
                       last_idx, sampling_params, rng, row_seeds=None,
                       adapter_ids=None):
    """Unified paged-KV step graph (reference:
    modules/kvcache/block_kv_cache_manager.py + the prefix-caching prefill of
    attention_base.py:772-914). One graph covers:

      * paged prefill            (T = window, positions from 0)
      * prefix-cached prefill    (T = uncached suffix, positions offset)
      * chunked prefill          (T = chunk, positions at running offset)
      * paged decode             (T = 1)

    input_ids (B, T); position_ids (B, T) absolute positions;
    slot_mapping (B, T) flat cache slots (negative = drop);
    block_table (B, max_blocks); last_idx (B,) index into T of the token whose
    logits are sampled. Cache layout (L, N_blocks, Bs, Hkv, D).
    row_seeds (B,) optional per-request sampling seeds: when present and
    the config carries ``stream_seed``, sampling switches to the
    positionally coupled draw (``ops/sampling.coupled_sample``) keyed by
    the ABSOLUTE position of the sampled token — the invariant every
    sampled-speculation bit-identity guarantee rests on.
    adapter_ids (B,) optional per-row LoRA pool slots (serving/lora_pool):
    each row gathers its own (A, B) factors from the stacked adapter
    params inside the one dispatch; slot 0 is the pinned zero adapter, so
    base-model rows stay bit-identical. Absent (None) the traced graph is
    byte-identical to a LoRA-free build.
    """
    kv_len = block_table.shape[1] * cache["k"].shape[2]
    ai = attn_inputs(spec, position_ids, lambda w, c=0: attn_ops.decode_mask(
        position_ids, kv_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids, position_ids)
    hidden, new_cache, _ = run_layers(
        spec, params, cache, hidden, ai, None, position_ids,
        "paged", slot_mapping=slot_mapping, block_table=block_table,
        adapter_ids=adapter_ids)
    idx = last_idx[:, None, None].astype(jnp.int32)
    last_h = jnp.take_along_axis(hidden, idx, axis=1)
    logits = _lm_head(spec, params, last_h)[:, 0, :]
    out = {"cache": new_cache}
    if tpu_cfg.output_logits:
        out["logits"] = _lm_head(spec, params, hidden)[..., :spec.vocab_size]
    if _coupled_mode(tpu_cfg, row_seeds):
        # position of the sampled token = the last real input position
        pos_last = jnp.take_along_axis(
            position_ids, last_idx[:, None].astype(jnp.int32),
            axis=1)[:, 0]
        out["tokens"] = sampling_ops.coupled_sample(
            logits, tpu_cfg.on_device_sampling_config, sampling_params,
            row_seeds, pos_last)
    else:
        out["tokens"] = sampling_ops.sample_dp(
            logits, tpu_cfg.on_device_sampling_config, sampling_params, rng)
    return out


def decode_loop(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                first_tokens, position_ids, seq_ids, sampling_params, rng,
                num_steps: int, adapter_ids=None, rope_position_ids=None,
                kv_view: int = None):
    """Fused multi-token decode: ``lax.scan`` of ``num_steps`` decode steps in
    ONE device call. This is the TPU answer to the reference's async
    double-buffering (modules/async_execution.py) — instead of hiding the
    host-device round trip, we eliminate num_steps-1 of them.

    first_tokens (B,): the token to feed at the first step.
    position_ids (B,): position of first_tokens.
    Returns (tokens (B, num_steps), cache).
    """

    use_mrope = rope_position_ids is not None
    b = first_tokens.shape[0]

    # Chunked side-buffer decode (hot path): the big cache is READ-ONLY
    # inside the scan — the chunk's K/V accumulate in a small per-chunk side
    # buffer and land in the cache with ONE bulk write per chunk. Any write
    # into the scan-carried cache makes XLA pick a write-friendly layout for
    # the carry and relayout-copy the live cache for the attention reads
    # every step (~0.29 ms/step at B=2/S=1024/16L on v5e). Geometries the
    # Pallas decode kernel is admitted for (window/sink/local patterns,
    # models/model_base.py kernel admission) keep the per-step path.
    chunkable = (num_steps > 1
                 and not tpu_cfg.is_continuous_batching
                 and b == cache["k"].shape[1]
                 and not spec.rolling_window
                 and not spec.flash_decoding
                 and spec.ssm is None
                 and spec.decode_kernel is not True
                 and not spec.alibi
                 and not (spec.attn_sink or spec.sliding_window > 0
                          or spec.layer_pattern is not None
                          or spec.attn_chunk > 0))
    if rope_position_ids is None:
        rope_position_ids = jnp.zeros((b, 3), position_ids.dtype)
    rngs = jax.random.split(rng, num_steps)

    if chunkable:
        C = num_steps
        g = spec.gqa
        side_k0 = jnp.zeros((spec.num_layers, b, g.num_kv_heads,
                             spec.head_dim, C), spec.dtype)
        side_v0 = jnp.zeros((spec.num_layers, b, g.num_kv_heads, C,
                             spec.v_head_dim), spec.dtype)
        start_pos = position_ids                       # (B,)
        cache_len = kv_view or kv.cache_len_of(cache)
        slots = jnp.arange(cache_len)[None, None, :]
        side_positions = (start_pos[:, None]
                          + jnp.arange(C, dtype=position_ids.dtype)[None, :])

        def step(carry, xs):
            tok, pos, rpos, sk, sv = carry
            step_rng, idx = xs
            pos2 = pos[:, None]

            def prior_mask(w, c=0):
                m = attn_ops.decode_mask(pos2, cache_len, window=w, chunk=c)
                return jnp.logical_and(
                    m, slots < start_pos[:, None, None])

            ai = attn_inputs(
                spec, pos2, prior_mask,
                rope_positions=rpos[:, None, :] if use_mrope else None)
            # the active token (slot idx) is folded in-register, not read
            # from the side buffer — its side write lands at step end
            ai["mask_side"] = jnp.logical_and(
                attn_ops.causal_mask(pos2, side_positions, None,
                                     spec.sliding_window, spec.attn_chunk),
                jnp.arange(C, dtype=jnp.int32)[None, None, :] != idx)
            hidden = _embed(spec, params, tok[:, None], pos2)
            hidden, _, _, (sk, sv) = run_layers(
                spec, params, cache, hidden, ai, seq_ids, pos2, "decode",
                identity_seq_ids=True, adapter_ids=adapter_ids,
                kv_view=kv_view, side=(sk, sv), chunk_idx=idx)
            logits = _lm_head(spec, params, hidden)
            nxt = sampling_ops.sample_dp(
                logits[:, -1, :], tpu_cfg.on_device_sampling_config,
                sampling_params, step_rng)
            return (nxt, pos + 1, rpos + 1 if use_mrope else rpos,
                    sk, sv), nxt

        (_, _, _, sk, sv), toks = jax.lax.scan(
            step, (first_tokens, position_ids, rope_position_ids,
                   side_k0, side_v0),
            (rngs, jnp.arange(num_steps, dtype=jnp.int32)),
            unroll=int(os.environ.get("NXDI_TPU_DECODE_UNROLL", "2")))
        new_cache = {
            "k": kv.commit_chunk(
                cache["k"], kv.quantize_kv(sk, cache["k"].dtype,
                                           spec.kv_scale),
                seq_ids, start_pos, k_transposed=True),
            "v": kv.commit_chunk(
                cache["v"], kv.quantize_kv(sv, cache["v"].dtype,
                                           spec.kv_scale),
                seq_ids, start_pos),
        }
        return {"tokens": jnp.transpose(toks, (1, 0)), "cache": new_cache}

    def step(carry, step_rng):
        tok, pos, rpos, cch = carry
        out = token_generation_step(
            spec, replace_output_logits(tpu_cfg), params, cch,
            tok[:, None], pos[:, None], seq_ids, sampling_params, step_rng,
            adapter_ids,
            rope_position_ids=rpos[:, None, :] if use_mrope else None,
            kv_view=kv_view)
        nxt = out["tokens"]
        # text-token M-RoPE positions advance in lockstep on all 3 axes
        return (nxt, pos + 1, rpos + 1 if use_mrope else rpos,
                out["cache"]), nxt

    (_, _, _, new_cache), toks = jax.lax.scan(
        step, (first_tokens, position_ids, rope_position_ids, cache), rngs)
    return {"tokens": jnp.transpose(toks, (1, 0)), "cache": new_cache}


def paged_decode_loop(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                     first_tokens, position_ids, block_table,
                     sampling_params, rng, num_steps: int, row_seeds=None,
                     adapter_ids=None):
    """Fused multi-token PAGED decode: ``num_steps`` steps in one device
    call with ZERO per-token host work — slot mappings are computed
    IN-GRAPH from the (pre-extended) block tables, exactly the reference's
    in-graph tokengen slot-mapping generation
    (block_kv_cache_manager.py:376-430). The host must pre-allocate blocks
    covering positions [p, p+num_steps) before the call.

    first_tokens (B,); position_ids (B,); block_table (B, max_blocks).
    Returns tokens (B, num_steps) + cache."""
    bs = cache["k"].shape[2]                  # paged (L, N, Bs, H, D)
    b = first_tokens.shape[0]
    rows = jnp.arange(b)

    def step(carry, step_rng):
        tok, pos, cch = carry
        slot = (block_table[rows, pos // bs] * bs + pos % bs)
        out = paged_forward_step(
            spec, replace_output_logits(tpu_cfg), params, cch, tok[:, None],
            pos[:, None], slot[:, None], block_table,
            jnp.zeros((b,), jnp.int32), sampling_params, step_rng,
            row_seeds=row_seeds, adapter_ids=adapter_ids)
        return (out["tokens"], pos + 1, out["cache"]), out["tokens"]

    rngs = jax.random.split(rng, num_steps)
    (_, _, new_cache), toks = jax.lax.scan(
        step, (first_tokens, position_ids, cache), rngs)
    return {"tokens": jnp.transpose(toks, (1, 0)), "cache": new_cache}


def paged_spec_draft_loop(spec: DecoderSpec, tpu_cfg: TpuConfig, params,
                          cache, first_tokens, position_ids, block_table,
                          widths, sampling_params, rng, num_steps: int,
                          row_seeds=None, adapter_ids=None):
    """Masked greedy-k SELF-DRAFT loop over the paged cache — the
    always-available proposer of speculative serving (serving/speculation/):
    the target model drafts its own continuation through ``num_steps``
    fused T=1 paged steps, exactly :func:`paged_decode_loop` except each
    row stops drafting once it has contributed its per-row candidate
    width (``widths`` (B,) = drafts + 1; rows clamped by seq_len or a
    token budget draft fewer).

    A frozen row's step writes nothing (slot -1 → dropped) and keeps its
    token/position carry, so a ragged draft batch can never write KV past
    a short row's grown block table. Draft KV lands at positions
    [p, p+width-2]; the verify dispatch rewrites the same slots with the
    same values (same model, same inputs), so the double write is
    value-identical.

    first_tokens (B,); position_ids (B,); block_table (B, max_blocks);
    widths (B,) int32. Returns tokens (B, num_steps) + cache.
    """
    bs = cache["k"].shape[2]                  # paged (L, N, Bs, H, D)
    b = first_tokens.shape[0]
    rows = jnp.arange(b)

    def step(carry, xs):
        j, step_rng = xs
        tok, pos, cch = carry
        valid = j < widths - 1
        safe = jnp.where(valid, pos, 0)
        slot = jnp.where(valid,
                         block_table[rows, safe // bs] * bs + safe % bs,
                         -1)
        out = paged_forward_step(
            spec, replace_output_logits(tpu_cfg), params, cch, tok[:, None],
            pos[:, None], slot[:, None], block_table,
            jnp.zeros((b,), jnp.int32), sampling_params, step_rng,
            row_seeds=row_seeds, adapter_ids=adapter_ids)
        ntok = jnp.where(valid, out["tokens"], tok)
        npos = jnp.where(valid, pos + 1, pos)
        return (ntok, npos, out["cache"]), ntok

    rngs = jax.random.split(rng, num_steps)
    (_, _, new_cache), toks = jax.lax.scan(
        step, (first_tokens, position_ids, cache),
        (jnp.arange(num_steps), rngs))
    return {"tokens": jnp.transpose(toks, (1, 0)), "cache": new_cache}


def paged_spec_verify(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                      input_ids, position_ids, slot_mapping, block_table,
                      widths, sampling_params=None, row_seeds=None,
                      want_hidden: bool = False, adapter_ids=None):
    """Speculative VERIFY graph over the paged layout: score all candidate
    positions in ONE ragged multi-token dispatch and compute greedy
    acceptance in-graph (reference acceptance: the cumsum-of-mismatch
    trick, model_base.py:2726-2730; dispatch shape: the same ragged
    per-row-width paged rows as chunked prefill — "Ragged Paged
    Attention", arxiv 2604.15464).

    input_ids (B, W): column 0 is each row's last ACCEPTED token, columns
    1..W-1 its draft tokens (drafts may live on device — they never need
    a host round trip). position_ids (B, W) absolute; slot_mapping (B, W)
    with columns >= the row's width at -1 (dropped writes); widths (B,)
    per-row candidate counts in [1, W].

    Exact-match acceptance: draft j is accepted iff it equals the
    target's choice at the previous candidate position; one bonus token
    (the target's correction at the first mismatch) is always emitted,
    so ``num_emitted`` is in [1, width]. The emitted tokens ARE the
    target's choices at consecutive positions — identical to what eager
    decode would produce, whatever the draft quality.

    Under greedy the target choice is the argmax. Under the coupled
    sampled stream (``sampling_params``/``row_seeds`` threaded and the
    config carrying ``stream_seed``) it is the gumbel-coupled draw of
    ``ops/sampling.coupled_sample`` — the in-graph uniform (gumbel)
    variates are keyed by absolute position, so the ratio test of
    classic rejection sampling reduces to exact match under the shared
    noise: acceptance means the draft equals the token eager sampled
    decode would have drawn, and the bonus token is the coupled residual
    resample. Output distribution AND stream are preserved.

    Returns tokens (B, W) (emitted prefix, 0 past ``num_emitted``),
    num_emitted (B,), cache (+ hidden (B, W, H) when ``want_hidden`` —
    Medusa/EAGLE proposers feed on the verified features).
    """
    if spec.mixed_kv or spec.ssm is not None:
        raise NotImplementedError(
            "speculative verify over mixed per-layer / recurrent caches is "
            "not supported; disable speculation for this model")
    kv_len = block_table.shape[1] * cache["k"].shape[2]
    ai = attn_inputs(spec, position_ids, lambda w, c=0: attn_ops.decode_mask(
        position_ids, kv_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids, position_ids)
    hidden, new_cache, _ = run_layers(
        spec, params, cache, hidden, ai, None, position_ids,
        "paged", slot_mapping=slot_mapping, block_table=block_table,
        adapter_ids=adapter_ids)
    logits = _lm_head(spec, params, hidden)
    if _coupled_mode(tpu_cfg, row_seeds):
        # the same coupled draw the eager paged step applies at each
        # position — bit-identity depends on it
        target = sampling_ops.coupled_sample(
            logits, tpu_cfg.on_device_sampling_config, sampling_params,
            row_seeds, position_ids)                            # (B, W)
    else:
        # the same greedy the eager paged step applies
        # (sampling_ops.sample over the untruncated head output)
        target = sampling_ops.sample(logits, None, None, None)  # (B, W)
    b, w = input_ids.shape
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    if w > 1:
        # draft j (column j+1) must match the target choice at column j;
        # columns past the row's width are forced mismatches so a padded
        # row can never accept into its neighbour's padding
        mismatch = ((input_ids[:, 1:] != target[:, :-1])
                    | (idx[:, 1:] >= widths[:, None])).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)
    else:
        n_acc = jnp.zeros((b,), jnp.int32)
    # accepted drafts equal the target choices by construction, so the
    # emitted prefix is simply target[:, :n_acc+1] (bonus included)
    tokens = jnp.where(idx <= n_acc[:, None], target, 0)
    out = {"tokens": tokens, "num_emitted": n_acc + 1, "cache": new_cache}
    if want_hidden:
        out["hidden"] = hidden
    return out


def paged_ragged_step(spec: DecoderSpec, tpu_cfg: TpuConfig, params, cache,
                      input_ids, position_ids, slot_mapping, block_table,
                      widths, emit_modes, sampling_params, rng,
                      row_seeds=None, want_hidden: bool = False,
                      adapter_ids=None):
    """The RAGGED UNIFIED dispatch: ONE mixed paged forward whose rows mix
    decode steps (width 1), prefill chunks (width n, positions at the
    row's own suffix offset) and speculative verify windows (width k+1)
    over the existing slot-mapping/block-table graph — the vLLM-class
    shape of "Ragged Paged Attention" (arxiv 2604.15464), serving
    serving/ragged/'s ``RaggedBatchPlanner`` (README "Ragged dispatch").

    input_ids (B, W): per-kind row content — a decode row's last token in
    column 0, a prefill row's chunk tokens, a verify row's last accepted
    token + drafts (drafts may live on device — no host round trip).
    position_ids (B, W) absolute; slot_mapping (B, W) flat cache slots
    with columns >= the row's width at -1 (dropped writes); block_table
    (B, max_blocks); widths (B,) per-row real-token counts in [1, W].

    emit_modes (B,) selects each row's in-graph emission:

      * 0 — emit nothing (intermediate prefill chunk; frozen/pad row):
        ``num_emitted`` 0, KV writes still land per ``slot_mapping``.
      * 1 — emit the row's LAST real token's sample in column 0 (decode
        step; FINAL prefill chunk): the same ``sample_dp`` over the
        gathered last-position logits the eager paged step applies, so
        streams are bit-identical to :func:`paged_forward_step`.
      * 2 — exact-match acceptance over the candidate window
        (speculative verify): identical math to
        :func:`paged_spec_verify` — draft j accepted iff it equals the
        target's choice at the previous candidate position, columns past
        the row's width forced mismatches, one bonus token always
        emitted, so ``num_emitted`` is in [1, width] and the emitted
        tokens ARE the target's choices (greedy argmax, or the
        gumbel-coupled sampled draw when ``row_seeds`` is threaded and
        the config carries ``stream_seed`` — see
        :func:`paged_spec_verify` for why exact match IS rejection
        sampling under the shared positional noise).

    adapter_ids (B,) optional per-row LoRA pool slots: each row gathers
    its own stacked (A, B) factors in-graph (``modules/lora.lora_delta``),
    so ONE dispatch mixes rows from different adapters — slot 0 is the
    pinned zero adapter (base-model rows bit-identical), and leaving the
    argument absent keeps the graph byte-identical to a LoRA-free build.

    Returns tokens (B, W) (emitted prefix, 0 past ``num_emitted``),
    num_emitted (B,), cache (+ hidden (B, W, H) when ``want_hidden`` —
    Medusa/EAGLE proposers feed on the verified features).
    """
    if spec.mixed_kv or spec.ssm is not None:
        raise NotImplementedError(
            "the ragged unified dispatch over mixed per-layer / recurrent "
            "caches is not supported; disable ragged mode for this model")
    kv_len = block_table.shape[1] * cache["k"].shape[2]
    ai = attn_inputs(spec, position_ids, lambda w, c=0: attn_ops.decode_mask(
        position_ids, kv_len, window=w, chunk=c))
    hidden = _embed(spec, params, input_ids, position_ids)
    hidden, new_cache, _ = run_layers(
        spec, params, cache, hidden, ai, None, position_ids,
        "paged", slot_mapping=slot_mapping, block_table=block_table,
        adapter_ids=adapter_ids)
    logits = _lm_head(spec, params, hidden)
    coupled = _coupled_mode(tpu_cfg, row_seeds)
    if coupled:
        # verify-row acceptance AND emit-last sampling from the SAME
        # coupled draws the eager paged step applies at each position
        target = sampling_ops.coupled_sample(
            logits, tpu_cfg.on_device_sampling_config, sampling_params,
            row_seeds, position_ids)                            # (B, W)
    else:
        # verify-row acceptance: the same greedy the eager paged step
        # applies (sampling_ops.sample over the untruncated head output)
        target = sampling_ops.sample(logits, None, None, None)  # (B, W)
    b, w = input_ids.shape
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    if w > 1:
        mismatch = ((input_ids[:, 1:] != target[:, :-1])
                    | (idx[:, 1:] >= widths[:, None])).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumsum(mismatch, axis=1) == 0, axis=1)
    else:
        n_acc = jnp.zeros((b,), jnp.int32)
    # emit-last rows: per-row in-graph sampling at the row's last real
    # column — the identical sample_dp (or coupled) call of
    # paged_forward_step, over the last-position slice of the SAME
    # lm_head output
    last = jnp.maximum(widths - 1, 0).astype(jnp.int32)
    if coupled:
        sampled = jnp.take_along_axis(target, last[:, None],
                                      axis=1)[:, 0]
    else:
        last_logits = jnp.take_along_axis(logits, last[:, None, None],
                                          axis=1)[:, 0, :]
        sampled = sampling_ops.sample_dp(
            last_logits, tpu_cfg.on_device_sampling_config,
            sampling_params, rng).reshape(b)
    verify_toks = jnp.where(idx <= n_acc[:, None], target, 0)
    single_toks = jnp.where(idx == 0, sampled[:, None],
                            jnp.zeros((), target.dtype))
    tokens = jnp.where((emit_modes == 2)[:, None], verify_toks,
                       jnp.where((emit_modes == 1)[:, None], single_toks,
                                 jnp.zeros((), target.dtype)))
    n_emit = jnp.where(emit_modes == 2, n_acc + 1,
                       jnp.where(emit_modes == 1, 1, 0)).astype(jnp.int32)
    out = {"tokens": tokens, "num_emitted": n_emit, "cache": new_cache}
    if want_hidden:
        out["hidden"] = hidden
    return out


def replace_output_logits(cfg: TpuConfig) -> TpuConfig:
    """decode_loop never returns per-step logits. Called at trace time only,
    so a plain copy per call is fine."""
    if not cfg.output_logits:
        return cfg
    import copy
    c2 = copy.copy(cfg)
    c2.output_logits = False
    return c2


# ---------------------------------------------------------------------------
# Spec resolution from InferenceConfig
# ---------------------------------------------------------------------------

def spec_from_config(config: InferenceConfig, tp_degree: Optional[int] = None,
                     **overrides) -> DecoderSpec:
    """Build a DecoderSpec from HF-style attributes on an InferenceConfig
    (reference analog: each model's ``setup_attr_for_model`` + init_model)."""
    tcfg = config.tpu_config
    tp = tp_degree if tp_degree is not None else tcfg.tp_degree
    # core geometry: explicit overrides win (families whose HF configs use
    # non-standard attribute names — gpt2 n_embd/n_head — pass them in)
    n_q = overrides.pop("num_q_heads",
                        getattr(config, "num_attention_heads", None))
    n_kv = overrides.pop("num_kv_heads",
                         getattr(config, "num_key_value_heads", None)) or n_q
    hidden = overrides.pop("hidden_size",
                           getattr(config, "hidden_size", None))
    head_dim = (overrides.pop("head_dim", None)
                or getattr(config, "head_dim", None) or hidden // n_q)
    n_layers = overrides.pop("num_layers",
                             getattr(config, "num_hidden_layers", None))
    inter = overrides.pop("intermediate_size",
                          getattr(config, "intermediate_size", None))
    rotary_dim = overrides.pop("rotary_dim",
                               getattr(config, "rotary_dim", None))
    gqa = resolve_gqa_sharding(n_q, n_kv, tp)
    rope_scaling = getattr(config, "rope_scaling", None) or {}
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type"))
    # "default"/"mrope" are not frequency-scaling schemes: default = plain
    # rope; mrope = 3-axis multimodal sections (qwen2-VL)
    mrope_section = None
    mrope_interleaved = False
    if rope_type == "su":          # legacy phi-3 name for longrope
        rope_type = "longrope"
    if rope_type in ("default", "mrope"):
        if "mrope_section" in rope_scaling:
            mrope_section = tuple(int(x) for x in rope_scaling["mrope_section"])
            mrope_interleaved = bool(rope_scaling.get("mrope_interleaved",
                                                      False))
        rope_type = None
    attention_factor = rope_scaling.get("attention_factor")
    rope = RopeConfig(
        head_dim=head_dim,
        rope_theta=float(getattr(config, "rope_theta", 10000.0)),
        rotary_dim=rotary_dim,
        scaling_type=rope_type,
        scaling_factor=float(rope_scaling.get("factor", 1.0)),
        low_freq_factor=float(rope_scaling.get("low_freq_factor", 1.0)),
        high_freq_factor=float(rope_scaling.get("high_freq_factor", 4.0)),
        original_max_position=int(
            rope_scaling.get("original_max_position_embeddings")
            # phi-3 longrope keeps this at the config top level
            or getattr(config, "original_max_position_embeddings", None)
            or getattr(config, "max_position_embeddings", 8192)),
        beta_fast=float(rope_scaling.get("beta_fast") or 32.0),
        beta_slow=float(rope_scaling.get("beta_slow") or 1.0),
        mscale=float(rope_scaling.get("mscale") or 0.0),
        mscale_all_dim=float(rope_scaling.get("mscale_all_dim") or 0.0),
        attention_factor=(float(attention_factor)
                          if attention_factor is not None else None),
        truncate=bool(rope_scaling.get("truncate", True)),
        mrope_section=mrope_section,
        mrope_interleaved=mrope_interleaved,
        # longrope (phi-3 / minicpm4): per-slot rescale factor lists
        short_factor=(tuple(float(x) for x in rope_scaling["short_factor"])
                      if "short_factor" in rope_scaling else None),
        long_factor=(tuple(float(x) for x in rope_scaling["long_factor"])
                     if "long_factor" in rope_scaling else None),
        max_position=int(getattr(config, "max_position_embeddings", 0) or 0),
    )
    vocab = config.vocab_size
    kw = dict(
        num_layers=n_layers,
        hidden_size=hidden,
        num_q_heads=n_q,
        num_kv_heads=n_kv,
        head_dim=head_dim,
        intermediate_size=inter,
        vocab_size=vocab,
        padded_vocab=pad_vocab(vocab, tp),
        rms_eps=float(getattr(config, "rms_norm_eps", 1e-6)),
        rope=rope,
        act=getattr(config, "hidden_act", "silu"),
        gqa=gqa,
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", False)),
        sliding_window=0,
        dtype=tcfg.jax_dtype,
        kv_dtype=tcfg.jax_kv_dtype,
        # default: XLA path — measured faster than the v1 Pallas kernel on
        # v5e at every prefill length (XLA's fused attention avoids the
        # kernel's layout transposes); the kernel stays opt-in via
        # attn_kernel_enabled until it beats XLA (reference keeps the same
        # dual-path structure, attention_base.py:985-1034)
        flash_prefill=bool(tcfg.attn_kernel_enabled),
        # tri-state passthrough (None = auto cost-model admission)
        decode_kernel=tcfg.attn_block_tkg_kernel_enabled,
        vocab_parallel=tcfg.vocab_parallel,
        quant=quant_spec_from_config(tcfg),
        low_rank=low_rank_mod.low_rank_spec_from_config(tcfg),
        lora=lora_spec_from_config(tcfg),
        seq_parallel=bool(tcfg.sequence_parallel_enabled),
        cp_prefill=tcfg.cp_degree > 1,
        flash_decoding=bool(tcfg.flash_decoding_enabled),
        capture=(tuple(tcfg.tensor_capture_config.capture_targets)
                 if tcfg.tensor_capture_config else None),
        kv_scale=(tcfg.kv_cache_scale if tcfg.kv_cache_quant else None),
        collective_dtype=(tcfg.collective_config.dtype
                          if tcfg.collective_config else None),
        collective_block=(tcfg.collective_config.block
                          if tcfg.collective_config else 32),
    )
    kw.update(overrides)
    if kw.get("moe") is not None:
        mc = tcfg.moe_config
        tkg_ep = getattr(mc, "moe_tkg_ep_degree", None)
        for knob in ("moe_cte_tp_degree", "moe_cte_ep_degree",
                     "moe_tkg_tp_degree"):
            v = getattr(mc, knob, None)
            if v is not None:
                raise NotImplementedError(
                    f"{knob}={v}: under GSPMD the mesh fixes the CTE expert "
                    "layout and the TKG tp extent; only moe_tkg_ep_degree=1 "
                    "(all-experts-local decode) reshards per phase")
        if tkg_ep is not None:
            if tkg_ep != 1:
                raise NotImplementedError(
                    "hybrid MoE sharding supports moe_tkg_ep_degree=1 "
                    "(all-experts-local decode) only; the mesh fixes other "
                    "degree combinations")
            if kw.get("quant") is not None:
                # quantized expert weights keep the stored (prefill) layout
                # through decode — the per-phase reshard silently does not
                # happen (scale shapes vary per quant mode). Say so loudly
                # instead of letting the perf knob be a no-op.
                logger.warning(
                    "moe_tkg_ep_degree=1 (tkg_experts_local) has no effect "
                    "on quantized MoE expert weights: decode keeps the "
                    "prefill expert sharding (quantized leaves are not "
                    "re-constrained). Drop the knob or the quantization.")
                from ..telemetry import get_registry
                _reg = get_registry()
                if _reg.enabled:
                    from ..telemetry.metrics import moe_tkg_degraded_counter
                    moe_tkg_degraded_counter(_reg).inc()
            kw["moe"] = replace(kw["moe"], tkg_experts_local=True)
    if kw.get("ssm") is not None:
        sc = tcfg.speculation_config
        bad = []
        if tcfg.is_block_kv_layout:
            bad.append("paged KV layout")
        if tcfg.flash_decoding_enabled:
            bad.append("flash decoding")
        if tcfg.is_continuous_batching:
            bad.append("continuous batching")
        if tcfg.sequence_parallel_enabled:
            bad.append("sequence parallelism")
        if tcfg.windowed_context_encoding:
            bad.append("windowed context encoding")
        if sc and (sc.speculation_length or sc.medusa_speculation_length):
            bad.append("speculation")
        if tcfg.tensor_capture_config or tcfg.tensor_replacement_config:
            bad.append("tensor capture/replacement")
        if bad:
            raise NotImplementedError(
                "recurrent/hybrid (SSM) stacks do not yet support: "
                + ", ".join(bad))
        # the recurrent state replaces long-range KV; keep the attention
        # cache simple (full rows, no rolling/mixed layouts)
        kw.setdefault("rolling_window", False)
        kw.setdefault("mixed_kv", False)
    if "rolling_window" not in kw:
        roll = tcfg.rolling_kv_cache
        sc = tcfg.speculation_config
        has_spec = bool(sc and (sc.speculation_length
                                or sc.medusa_speculation_length))
        blockers = []
        if not (kw.get("sliding_window", 0) > 0
                and kw.get("layer_pattern") is None
                and kw.get("attn_chunk", 0) == 0):
            blockers.append("needs a uniform sliding_window model")
        if tcfg.is_block_kv_layout:
            blockers.append("incompatible with the paged KV layout")
        if tcfg.flash_decoding_enabled:
            blockers.append("incompatible with flash decoding")
        if has_spec:
            blockers.append("incompatible with speculation")
        # a window >= seq_len simply never rolls; allow but skip (the full
        # cache is already window-sized)
        worth_it = tcfg.seq_len > kw.get("sliding_window", 0)
        if roll is None:
            roll = not blockers and worth_it
        elif roll and blockers:
            raise ValueError("rolling_kv_cache: " + "; ".join(blockers))
        elif roll and not worth_it:
            roll = False
        kw["rolling_window"] = bool(roll)
    if "mixed_kv" not in kw:
        # per-layer cache sizes for alternating local/global stacks
        # (reference: gpt_oss_kv_cache_manager.py): local layers roll at W
        sc = tcfg.speculation_config
        kw["mixed_kv"] = bool(
            kw.get("layer_pattern") is not None
            and kw.get("sliding_window", 0) > 0
            and kw.get("attn_chunk", 0) == 0
            and tcfg.seq_len > kw["sliding_window"]
            and not tcfg.windowed_context_encoding
            and not tcfg.is_block_kv_layout
            and not tcfg.flash_decoding_enabled
            and not (sc and (sc.speculation_length
                             or sc.medusa_speculation_length))
            and not (tcfg.tensor_capture_config
                     or tcfg.tensor_replacement_config))
    if not kw.get("vocab_parallel", True) and tp > 1:
        # older saved configs carry vocab_parallel=false from when the knob
        # was inert; honoring it replicates the (V, H) table on every device
        logger.warning(
            "vocab_parallel=False with tp=%d: the embedding table will be "
            "REPLICATED on every device (%.0f MB each at bf16)", tp,
            kw["padded_vocab"] * kw["hidden_size"] * 2 / 1e6)
    if kw.get("learned_pos") and tcfg.seq_len > kw["learned_pos"]:
        # decoding past the learned position table would silently reuse the
        # last embedding (HF raises an index error) — fail loudly instead
        raise ValueError(
            f"seq_len {tcfg.seq_len} exceeds the learned position table "
            f"({kw['learned_pos']} positions)")
    return DecoderSpec(**kw)
