"""FLUX text-to-image pipeline (reference: models/diffusers/flux/
application.py:135 ``NeuronFluxApplication`` + pipeline.py — transformer,
CLIP, T5, VAE submodels orchestrated by a host loop).

Sampler: rectified-flow Euler over shifted sigmas (the flux time-shift
sigma' = shift*s / (1 + (shift-1)*s)); each denoise step is one jitted
transformer call; the scan-free host loop mirrors the reference pipeline."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import transformer as ftx
from . import vae as fvae
from .text_encoders import (ClipTextSpec, T5Spec, clip_text_forward,
                            t5_encoder_forward)


def shifted_sigmas(num_steps: int, shift: float = 3.0) -> np.ndarray:
    """Monotone 1 -> 0 sigma schedule with the flux time shift."""
    s = np.linspace(1.0, 0.0, num_steps + 1, dtype=np.float32)
    return (shift * s) / (1.0 + (shift - 1.0) * s)


def euler_step(x, v, sigma, sigma_next):
    """Rectified flow: dx/dt = v, x_{t+dt} = x + (sigma_next - sigma) * v."""
    return x + (sigma_next - sigma) * v


@dataclass
class FluxPipeline:
    spec: ftx.FluxSpec
    params: Any                       # flux transformer params
    clip_spec: ClipTextSpec
    clip_params: Any
    t5_spec: T5Spec
    t5_params: Any
    vae_spec: fvae.VaeSpec
    vae_params: Any

    def __post_init__(self):
        self._flux = jax.jit(partial(ftx.flux_forward, self.spec))
        self._clip = jax.jit(partial(clip_text_forward, self.clip_spec))
        self._t5 = jax.jit(partial(t5_encoder_forward, self.t5_spec))
        self._vae = jax.jit(partial(fvae.vae_decode, self.vae_spec))

    def encode_text(self, clip_ids: np.ndarray, t5_ids: np.ndarray):
        pooled = self._clip(self.clip_params, jnp.asarray(clip_ids))["pooled"]
        ctx = self._t5(self.t5_params, jnp.asarray(t5_ids))
        return ctx, pooled

    def _sample(self, clip_ids, t5_ids, x, lh, lw, sigmas, start,
                guidance, decode, known_packed=None, mask_packed=None,
                noise_packed=None, cond_packed=None) -> Dict[str, Any]:
        """Shared tail of every pipeline variant: text encode -> denoise
        from ``start`` -> unpack -> optional VAE decode."""
        b = clip_ids.shape[0]
        ctx, pooled = self.encode_text(clip_ids, t5_ids)
        img_ids = jnp.asarray(ftx.make_img_ids(b, lh, lw))
        txt_ids = jnp.zeros((b, t5_ids.shape[1], 3), jnp.int32)
        g = jnp.full((b,), guidance, jnp.float32)
        x = _denoise(self, x, ctx, pooled, img_ids, txt_ids, g, sigmas,
                     start, known_packed, mask_packed, noise_packed,
                     cond_packed)
        if mask_packed is not None:
            # final blend: known region restored exactly
            x = jnp.where(mask_packed, x, known_packed)
        lat = ftx.unpack_latents(x, lh, lw)
        out = {"latents": np.asarray(lat), "sigmas": sigmas}
        if decode:
            out["images"] = np.asarray(self._vae(self.vae_params, lat))
        return out

    def __call__(self, clip_ids: np.ndarray, t5_ids: np.ndarray,
                 height: int = 64, width: int = 64, num_steps: int = 4,
                 guidance: float = 3.5, shift: float = 3.0,
                 seed: int = 0, decode: bool = True) -> Dict[str, Any]:
        """height/width in pixels (multiples of 16); latents are
        (h/8, w/8) with 2x2 packing."""
        b = clip_ids.shape[0]
        lh, lw = height // 8, width // 8
        key = jax.random.PRNGKey(seed)
        lat = jax.random.normal(
            key, (b, self.vae_spec.latent_channels, lh, lw), jnp.float32)
        sigmas = shifted_sigmas(num_steps, shift)
        return self._sample(clip_ids, t5_ids, ftx.pack_latents(lat), lh, lw,
                            sigmas, 0, guidance, decode)


def _denoise(pipe: "FluxPipeline", x, ctx, pooled, img_ids, txt_ids, g,
             sigmas, start: int,
             known_packed=None, mask_packed=None, noise_packed=None,
             cond_packed=None):
    """Euler flow-matching loop from step ``start``; optional inpaint
    blending re-imposes the known region at each step's noise level;
    ``cond_packed`` (B, T, C_cond) is channel-concatenated onto the model
    input at EVERY step (Control / Fill conditioning — the transformer's
    in_channels covers base+cond, its output only the base; reference:
    diffusers/flux/pipeline.py text2img/control/fill/inpaint via
    NeuronFluxControlPipeline/NeuronFluxFillPipeline :393-429)."""
    b = x.shape[0]
    for i in range(start, len(sigmas) - 1):
        t = jnp.full((b,), sigmas[i], jnp.float32)
        xin = (x if cond_packed is None
               else jnp.concatenate([x, cond_packed], axis=-1))
        v = pipe._flux(pipe.params, xin, ctx, t, pooled, img_ids, txt_ids,
                       guidance=g)
        x = euler_step(x, v, float(sigmas[i]), float(sigmas[i + 1]))
        if mask_packed is not None:
            s_next = float(sigmas[i + 1])
            known_noised = (1.0 - s_next) * known_packed                 + s_next * noise_packed
            x = jnp.where(mask_packed, x, known_noised)
    return x


class FluxImg2ImgPipeline(FluxPipeline):
    """Image-conditioned variants (reference: diffusers/flux/pipeline.py —
    the control/img2img and inpaint pipelines named in BASELINE.json).
    Both consume init LATENTS (B, C, h/8, w/8); VAE encoding happens
    upstream."""

    def img2img(self, clip_ids: np.ndarray, t5_ids: np.ndarray,
                init_latents: np.ndarray, strength: float = 0.6,
                num_steps: int = 4, guidance: float = 3.5,
                shift: float = 3.0, seed: int = 0,
                decode: bool = True) -> Dict[str, Any]:
        lat0 = jnp.asarray(init_latents, jnp.float32)
        lh, lw = lat0.shape[2], lat0.shape[3]
        sigmas = shifted_sigmas(num_steps, shift)
        start = min(int(num_steps * (1.0 - strength)), num_steps - 1)
        noise = jax.random.normal(jax.random.PRNGKey(seed), lat0.shape,
                                  jnp.float32)
        # flow-matching interpolation to the start noise level
        s0 = float(sigmas[start])
        x = ftx.pack_latents((1.0 - s0) * lat0 + s0 * noise)
        out = self._sample(clip_ids, t5_ids, x, lh, lw, sigmas, start,
                           guidance, decode)
        out["start_step"] = start
        return out

    def inpaint(self, clip_ids: np.ndarray, t5_ids: np.ndarray,
                init_latents: np.ndarray, mask: np.ndarray,
                strength: float = 1.0, num_steps: int = 4,
                guidance: float = 3.5, shift: float = 3.0, seed: int = 0,
                decode: bool = True) -> Dict[str, Any]:
        """mask (B, 1, h/8, w/8): True/1 = region to REGENERATE; the known
        region is re-imposed at each step's noise level."""
        lat0 = jnp.asarray(init_latents, jnp.float32)
        lh, lw = lat0.shape[2], lat0.shape[3]
        sigmas = shifted_sigmas(num_steps, shift)
        start = min(int(num_steps * (1.0 - strength)), num_steps - 1)
        noise = jax.random.normal(jax.random.PRNGKey(seed), lat0.shape,
                                  jnp.float32)
        s0 = float(sigmas[start])
        x = ftx.pack_latents((1.0 - s0) * lat0 + s0 * noise)
        m = jnp.broadcast_to(jnp.asarray(mask, bool), lat0.shape)
        return self._sample(
            clip_ids, t5_ids, x, lh, lw, sigmas, start, guidance, decode,
            known_packed=ftx.pack_latents(lat0),
            mask_packed=ftx.pack_latents(m.astype(jnp.float32)) > 0.5,
            noise_packed=ftx.pack_latents(noise))


class FluxControlPipeline(FluxPipeline):
    """Control conditioning (reference: NeuronFluxControlPipeline,
    diffusers/flux/pipeline.py:420): the VAE-encoded control image's packed
    latents are channel-concatenated onto the transformer input at every
    denoise step — spec.in_channels must be 2x the packed latent width,
    spec.out_channels the base width."""

    def control(self, clip_ids: np.ndarray, t5_ids: np.ndarray,
                control_latents: np.ndarray, num_steps: int = 4,
                guidance: float = 3.5, shift: float = 3.0, seed: int = 0,
                decode: bool = True) -> Dict[str, Any]:
        """control_latents (B, C, h/8, w/8) — VAE-encoded control image."""
        cond_lat = jnp.asarray(control_latents, jnp.float32)
        lh, lw = cond_lat.shape[2], cond_lat.shape[3]
        cond = ftx.pack_latents(cond_lat)
        base_ch = cond.shape[-1]
        out_ch = self.spec.out_channels or self.spec.in_channels
        if self.spec.in_channels != 2 * base_ch or out_ch != base_ch:
            raise ValueError(
                f"control pipeline needs transformer in_channels "
                f"{2 * base_ch} (= 2x packed latents) and out_channels "
                f"{base_ch}, got in={self.spec.in_channels} out={out_ch}")
        x = ftx.pack_latents(jax.random.normal(
            jax.random.PRNGKey(seed), cond_lat.shape, jnp.float32))
        sigmas = shifted_sigmas(num_steps, shift)
        return self._sample(clip_ids, t5_ids, x, lh, lw, sigmas, 0,
                            guidance, decode, cond_packed=cond)


def fold_mask_8x8(mask: np.ndarray) -> np.ndarray:
    """Pixel-resolution inpaint mask (B, 1, 8*lh, 8*lw) -> 64-channel
    latent-resolution representation (B, 64, lh, lw): each latent pixel
    carries its 8x8 pixel-mask patch as channels (reference: diffusers
    FluxFillPipeline.prepare_mask_latents mask folding)."""
    m = np.asarray(mask, np.float32)
    b, one, hp, wp = m.shape
    lh, lw = hp // 8, wp // 8
    m = m.reshape(b, lh, 8, lw, 8)
    return np.ascontiguousarray(
        m.transpose(0, 2, 4, 1, 3).reshape(b, 64, lh, lw))


class FluxFillPipeline(FluxPipeline):
    """Fill / inpaint-conditioned transformer (reference:
    NeuronFluxFillPipeline, diffusers/flux/pipeline.py:393): conditioning =
    packed masked-image latents + the packed 64-channel folded pixel mask,
    channel-concatenated at every step. With a 16-ch VAE the transformer
    reads 64 (latents) + 64 (masked image) + 256 (mask) = 384 channels."""

    def fill(self, clip_ids: np.ndarray, t5_ids: np.ndarray,
             masked_latents: np.ndarray, mask_pixels: np.ndarray,
             num_steps: int = 4, guidance: float = 30.0, shift: float = 3.0,
             seed: int = 0, decode: bool = True) -> Dict[str, Any]:
        """masked_latents (B, C, lh, lw): VAE encoding of image*(1-mask);
        mask_pixels (B, 1, 8*lh, 8*lw): 1 = region to regenerate."""
        mlat = jnp.asarray(masked_latents, jnp.float32)
        lh, lw = mlat.shape[2], mlat.shape[3]
        cond_img = ftx.pack_latents(mlat)                    # (B, T, 64)
        mask64 = jnp.asarray(fold_mask_8x8(mask_pixels))
        cond_mask = ftx.pack_latents(mask64)                 # (B, T, 256)
        cond = jnp.concatenate([cond_img, cond_mask], axis=-1)
        base_ch = cond_img.shape[-1]
        want = base_ch + cond.shape[-1]
        out_ch = self.spec.out_channels or self.spec.in_channels
        if self.spec.in_channels != want or out_ch != base_ch:
            raise ValueError(
                f"fill pipeline needs transformer in_channels {want} and "
                f"out_channels {base_ch}, got in={self.spec.in_channels} "
                f"out={out_ch}")
        x = ftx.pack_latents(jax.random.normal(
            jax.random.PRNGKey(seed), mlat.shape, jnp.float32))
        sigmas = shifted_sigmas(num_steps, shift)
        return self._sample(clip_ids, t5_ids, x, lh, lw, sigmas, 0,
                            guidance, decode, cond_packed=cond)


def build_random_pipeline(seed: int = 0, tiny: bool = True) -> FluxPipeline:
    """Random-weight pipeline for tests/benches (reference analog: tiny
    random-weight integration configs, SURVEY §4)."""
    if tiny:
        spec = ftx.FluxSpec(hidden_size=64, num_heads=4, head_dim=16,
                            depth_double=2, depth_single=2, in_channels=64,
                            context_dim=32, pooled_dim=24,
                            axes_dim=(4, 6, 6))
        clip_spec = ClipTextSpec(hidden_size=24, num_layers=2, num_heads=2,
                                 intermediate_size=48, vocab_size=100,
                                 max_positions=32, eos_token_id=2)
        t5_spec = T5Spec(d_model=32, num_layers=2, num_heads=2, d_kv=8,
                         d_ff=64, vocab_size=100)
        vae_spec = fvae.VaeSpec(latent_channels=16, base_channels=32,
                                channel_mults=(1, 2), num_res_blocks=1)
    else:  # flux-dev geometry
        spec = ftx.FluxSpec()
        clip_spec = ClipTextSpec(hidden_size=768, num_layers=12, num_heads=12,
                                 intermediate_size=3072, vocab_size=49408,
                                 max_positions=77)
        t5_spec = T5Spec(d_model=4096, num_layers=24, num_heads=64, d_kv=64,
                         d_ff=10240, vocab_size=32128)
        vae_spec = fvae.VaeSpec()
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)
    from ...model_base import init_param_tree
    from .text_encoders import clip_text_forward  # noqa: F401

    def init_clip(key):
        H, L = clip_spec.hidden_size, clip_spec.num_layers
        I = clip_spec.intermediate_size
        r = lambda k, *s: jax.random.normal(k, s, jnp.float32) * 0.05
        ks = jax.random.split(key, 20)
        layers = {
            "ln1_w": jnp.ones((L, H)), "ln1_b": jnp.zeros((L, H)),
            "q_w": r(ks[0], L, H, H), "q_b": jnp.zeros((L, H)),
            "k_w": r(ks[1], L, H, H), "k_b": jnp.zeros((L, H)),
            "v_w": r(ks[2], L, H, H), "v_b": jnp.zeros((L, H)),
            "o_w": r(ks[3], L, H, H), "o_b": jnp.zeros((L, H)),
            "ln2_w": jnp.ones((L, H)), "ln2_b": jnp.zeros((L, H)),
            "fc1_w": r(ks[4], L, H, I), "fc1_b": jnp.zeros((L, I)),
            "fc2_w": r(ks[5], L, I, H), "fc2_b": jnp.zeros((L, H)),
        }
        return {"embed": r(ks[6], clip_spec.vocab_size, H),
                "pos": r(ks[7], clip_spec.max_positions, H),
                "layers": layers,
                "ln_f_w": jnp.ones((H,)), "ln_f_b": jnp.zeros((H,))}

    def init_t5(key):
        s = t5_spec
        r = lambda k, *sh: jax.random.normal(k, sh, jnp.float32) * 0.05
        ks = jax.random.split(key, 10)
        L = s.num_layers
        inner = s.num_heads * s.d_kv
        layers = {
            "ln1": jnp.ones((L, s.d_model)),
            "q": r(ks[0], L, s.d_model, inner),
            "k": r(ks[1], L, s.d_model, inner),
            "v": r(ks[2], L, s.d_model, inner),
            "o": r(ks[3], L, inner, s.d_model),
            "ln2": jnp.ones((L, s.d_model)),
            "wi0": r(ks[4], L, s.d_model, s.d_ff),
            "wi1": r(ks[5], L, s.d_model, s.d_ff),
            "wo": r(ks[6], L, s.d_ff, s.d_model),
        }
        return {"embed": r(ks[7], s.vocab_size, s.d_model),
                "rel_bias": r(ks[8], s.rel_buckets, s.num_heads),
                "layers": layers, "ln_f": jnp.ones((s.d_model,))}

    return FluxPipeline(
        spec=spec, params=ftx.init_flux_params(spec, keys[0]),
        clip_spec=clip_spec, clip_params=init_clip(keys[1]),
        t5_spec=t5_spec, t5_params=init_t5(keys[2]),
        vae_spec=vae_spec,
        vae_params=fvae.init_vae_params(vae_spec, keys[3]))
