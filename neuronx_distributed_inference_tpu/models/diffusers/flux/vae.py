"""VAE decoder (AutoencoderKL decoder path, FLUX 16-channel variant) —
reference: the VAE submodel of models/diffusers/flux/ (SURVEY §2.7).

Structure: conv_in -> mid(resnet, attn, resnet) -> up blocks (resnets +
nearest-2x upsample convs) -> groupnorm/silu/conv_out. GroupNorm(32),
silu activations. Latents are descaled with (z / scaling_factor +
shift_factor) before decoding (flux convention)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....parallel.layers import ParamSpec
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class VaeSpec:
    latent_channels: int = 16
    base_channels: int = 128
    channel_mults: Tuple[int, ...] = (1, 2, 4, 4)   # top-down (decoder reversed)
    num_res_blocks: int = 2
    out_channels: int = 3
    groups: int = 32
    scaling_factor: float = 0.3611
    shift_factor: float = 0.1159


def _conv(cin, cout, k):
    return {"w": ParamSpec((cout, cin, k, k), P()),
            "b": ParamSpec((cout,), P(), init="zeros")}


def _gn(c):
    return {"w": ParamSpec((c,), P(), init="ones"),
            "b": ParamSpec((c,), P(), init="zeros")}


def _resnet(cin, cout):
    s = {"gn1": _gn(cin), "conv1": _conv(cin, cout, 3),
         "gn2": _gn(cout), "conv2": _conv(cout, cout, 3)}
    if cin != cout:
        s["skip"] = _conv(cin, cout, 1)
    return s


def vae_decoder_param_specs(spec: VaeSpec) -> Dict[str, Any]:
    mults = list(spec.channel_mults)
    top = spec.base_channels * mults[-1]
    out: Dict[str, Any] = {
        "conv_in": _conv(spec.latent_channels, top, 3),
        "mid_res1": _resnet(top, top),
        "mid_attn": {"gn": _gn(top), "q": _conv(top, top, 1),
                     "k": _conv(top, top, 1), "v": _conv(top, top, 1),
                     "o": _conv(top, top, 1)},
        "mid_res2": _resnet(top, top),
        "gn_out": _gn(spec.base_channels * mults[0]),
        "conv_out": _conv(spec.base_channels * mults[0], spec.out_channels, 3),
    }
    cin = top
    for bi, m in enumerate(reversed(mults)):
        cout = spec.base_channels * m
        blk: Dict[str, Any] = {}
        for ri in range(spec.num_res_blocks + 1):
            blk[f"res{ri}"] = _resnet(cin if ri == 0 else cout, cout)
        if bi != len(mults) - 1:
            blk["upsample"] = _conv(cout, cout, 3)
        out[f"up{bi}"] = blk
        cin = cout
    return out


def init_vae_params(spec: VaeSpec, key, mesh=None):
    from ...model_base import init_param_tree
    return init_param_tree(vae_decoder_param_specs(spec), key, mesh)


def _conv2d(p, x, stride=1, pad=1):
    dn = ("NCHW", "OIHW", "NCHW")
    y = jax.lax.conv_general_dilated(
        x, p["w"], (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn)
    return y + p["b"][None, :, None, None]


def _group_norm(p, x, groups):
    b, c, h, w = x.shape
    xf = x.astype(jnp.float32).reshape(b, groups, c // groups, h, w)
    mu = jnp.mean(xf, axis=(2, 3, 4), keepdims=True)
    var = jnp.var(xf, axis=(2, 3, 4), keepdims=True)
    xf = ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).reshape(b, c, h, w)
    return (xf * p["w"][None, :, None, None]
            + p["b"][None, :, None, None]).astype(x.dtype)


def _res_block(p, x, groups):
    h = _conv2d(p["conv1"], jax.nn.silu(_group_norm(p["gn1"], x, groups)))
    h = _conv2d(p["conv2"], jax.nn.silu(_group_norm(p["gn2"], h, groups)))
    skip = _conv2d(p["skip"], x, pad=0) if "skip" in p else x
    return skip + h


def _attn_block(p, x, groups):
    b, c, hh, ww = x.shape
    n = _group_norm(p["gn"], x, groups)
    q = _conv2d(p["q"], n, pad=0).reshape(b, c, hh * ww)
    k = _conv2d(p["k"], n, pad=0).reshape(b, c, hh * ww)
    v = _conv2d(p["v"], n, pad=0).reshape(b, c, hh * ww)
    s = jnp.einsum("bct,bcs->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (c ** -0.5)
    a = jnp.einsum("bts,bcs->bct", jax.nn.softmax(s, -1),
                   v.astype(jnp.float32)).reshape(b, c, hh, ww)
    return x + _conv2d(p["o"], a.astype(x.dtype), pad=0)


def vae_decode(spec: VaeSpec, params, z: jnp.ndarray) -> jnp.ndarray:
    """latents (B, C_lat, h, w) -> images (B, 3, 8h, 8w) in [-1, 1]-ish."""
    g = spec.groups
    z = z / spec.scaling_factor + spec.shift_factor
    z = z.astype(params["conv_in"]["w"].dtype)
    x = _conv2d(params["conv_in"], z)
    x = _res_block(params["mid_res1"], x, g)
    x = _attn_block(params["mid_attn"], x, g)
    x = _res_block(params["mid_res2"], x, g)
    n_up = len(spec.channel_mults)
    for bi in range(n_up):
        blk = params[f"up{bi}"]
        for ri in range(spec.num_res_blocks + 1):
            x = _res_block(blk[f"res{ri}"], x, g)
        if bi != n_up - 1:
            b, c, hh, ww = x.shape
            x = jax.image.resize(x, (b, c, hh * 2, ww * 2), "nearest")
            x = _conv2d(blk["upsample"], x)
    x = jax.nn.silu(_group_norm(params["gn_out"], x, g))
    return _conv2d(params["conv_out"], x)
