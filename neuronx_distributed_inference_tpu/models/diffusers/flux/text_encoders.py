"""FLUX conditioning encoders in JAX: CLIP text (pooled vector) + T5 encoder
(sequence features) — reference: models/diffusers/flux/ compiles CLIP, T5 and
the VAE as separate Neuron submodels next to the transformer.

Both are HF-checkpoint compatible and golden-tested against transformers'
CPU implementations."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...vision import VIT_ACTS
from ....ops.normalization import layer_norm, rms_norm


# ---------------------------------------------------------------------------
# CLIP text encoder
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ClipTextSpec:
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    vocab_size: int
    max_positions: int
    eos_token_id: int = 2
    act: str = "quick_gelu"
    eps: float = 1e-5


def clip_text_spec_from_hf(cfg) -> ClipTextSpec:
    g = cfg.get if isinstance(cfg, dict) else lambda k, d=None: getattr(cfg, k, d)
    return ClipTextSpec(
        hidden_size=g("hidden_size"), num_layers=g("num_hidden_layers"),
        num_heads=g("num_attention_heads"),
        intermediate_size=g("intermediate_size"), vocab_size=g("vocab_size"),
        max_positions=g("max_position_embeddings"),
        eos_token_id=g("eos_token_id", 2), act=g("hidden_act", "quick_gelu"),
        eps=g("layer_norm_eps", 1e-5))


def clip_text_forward(spec: ClipTextSpec, params, input_ids
                      ) -> Dict[str, jnp.ndarray]:
    """Returns {'last_hidden_state', 'pooled'} — pooled = final-LN hidden at
    each row's eos position (CLIPTextModel pooler semantics)."""
    b, t = input_ids.shape
    x = params["embed"][input_ids] + params["pos"][:t]
    causal = jnp.tril(jnp.ones((t, t), bool))[None]
    act = VIT_ACTS[spec.act]
    nh = spec.num_heads
    scale = (spec.hidden_size // nh) ** -0.5

    def body(h, lw):
        r = layer_norm(h, lw["ln1_w"], lw["ln1_b"], spec.eps)
        q = (r @ lw["q_w"] + lw["q_b"]) * scale
        k = r @ lw["k_w"] + lw["k_b"]
        v = r @ lw["v_w"] + lw["v_b"]
        qf = q.reshape(b, t, nh, -1).astype(jnp.float32)
        kf = k.reshape(b, t, nh, -1).astype(jnp.float32)
        vf = v.reshape(b, t, nh, -1).astype(jnp.float32)
        s = jnp.einsum("bthd,bshd->bhts", qf, kf)
        s = jnp.where(causal[:, None], s, -30000.0)
        a = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), vf)
        h = h + (a.reshape(b, t, -1).astype(h.dtype) @ lw["o_w"] + lw["o_b"])
        r = layer_norm(h, lw["ln2_w"], lw["ln2_b"], spec.eps)
        h = h + (act(r @ lw["fc1_w"] + lw["fc1_b"]) @ lw["fc2_w"] + lw["fc2_b"])
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["ln_f_w"], params["ln_f_b"], spec.eps)
    if spec.eos_token_id == 2:
        # HF legacy pooling: position of the HIGHEST token id (for CLIP's
        # original vocab the eos id 49407 IS the max, so argmax finds it)
        eos_pos = jnp.argmax(input_ids, axis=1)
    else:
        eos_pos = jnp.argmax((input_ids == spec.eos_token_id).astype(jnp.int32),
                             axis=1)
    pooled = x[jnp.arange(b), eos_pos]
    return {"last_hidden_state": x, "pooled": pooled}


def convert_clip_text(sd: Dict[str, np.ndarray], spec: ClipTextSpec,
                      prefix: str = "text_model") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[n], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"{prefix}.encoder.layers.{i}"
        return {
            "ln1_w": get(f"{b}.layer_norm1.weight"),
            "ln1_b": get(f"{b}.layer_norm1.bias"),
            "q_w": t(get(f"{b}.self_attn.q_proj.weight")),
            "q_b": get(f"{b}.self_attn.q_proj.bias"),
            "k_w": t(get(f"{b}.self_attn.k_proj.weight")),
            "k_b": get(f"{b}.self_attn.k_proj.bias"),
            "v_w": t(get(f"{b}.self_attn.v_proj.weight")),
            "v_b": get(f"{b}.self_attn.v_proj.bias"),
            "o_w": t(get(f"{b}.self_attn.out_proj.weight")),
            "o_b": get(f"{b}.self_attn.out_proj.bias"),
            "ln2_w": get(f"{b}.layer_norm2.weight"),
            "ln2_b": get(f"{b}.layer_norm2.bias"),
            "fc1_w": t(get(f"{b}.mlp.fc1.weight")),
            "fc1_b": get(f"{b}.mlp.fc1.bias"),
            "fc2_w": t(get(f"{b}.mlp.fc2.weight")),
            "fc2_b": get(f"{b}.mlp.fc2.bias"),
        }

    layers = [lw(i) for i in range(spec.num_layers)]
    return {
        "embed": get(f"{prefix}.embeddings.token_embedding.weight"),
        "pos": get(f"{prefix}.embeddings.position_embedding.weight"),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
        "ln_f_w": get(f"{prefix}.final_layer_norm.weight"),
        "ln_f_b": get(f"{prefix}.final_layer_norm.bias"),
    }


# ---------------------------------------------------------------------------
# T5 encoder
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class T5Spec:
    d_model: int
    num_layers: int
    num_heads: int
    d_kv: int
    d_ff: int
    vocab_size: int
    rel_buckets: int = 32
    rel_max_distance: int = 128
    eps: float = 1e-6


def t5_spec_from_hf(cfg) -> T5Spec:
    g = cfg.get if isinstance(cfg, dict) else lambda k, d=None: getattr(cfg, k, d)
    return T5Spec(
        d_model=g("d_model"), num_layers=g("num_layers"),
        num_heads=g("num_heads"), d_kv=g("d_kv"), d_ff=g("d_ff"),
        vocab_size=g("vocab_size"),
        rel_buckets=g("relative_attention_num_buckets", 32),
        rel_max_distance=g("relative_attention_max_distance", 128),
        eps=g("layer_norm_epsilon", 1e-6))


def _t5_rel_bucket(rel_pos: jnp.ndarray, num_buckets: int,
                   max_distance: int) -> jnp.ndarray:
    """Bidirectional T5 relative position bucketing (HF semantics)."""
    nb = num_buckets // 2
    ret = jnp.where(rel_pos > 0, nb, 0)
    n = jnp.abs(rel_pos)
    max_exact = nb // 2
    is_small = n < max_exact
    log_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-9)
        / np.log(max_distance / max_exact) * (nb - max_exact)
    ).astype(jnp.int32)
    log_large = jnp.minimum(log_large, nb - 1)
    return ret + jnp.where(is_small, n, log_large)


def t5_encoder_forward(spec: T5Spec, params, input_ids) -> jnp.ndarray:
    """(B, T) -> (B, T, d_model). Relative-position bias from layer 0 is
    shared by every layer (T5 convention); attention is unscaled."""
    b, t = input_ids.shape
    x = params["embed"][input_ids]
    pos = jnp.arange(t)
    rel = pos[None, :] - pos[:, None]                  # mem - query
    bucket = _t5_rel_bucket(rel, spec.rel_buckets, spec.rel_max_distance)
    bias = params["rel_bias"][bucket]                  # (T, T, heads)
    bias = jnp.transpose(bias, (2, 0, 1))[None]        # (1, H, T, T)
    nh, dk = spec.num_heads, spec.d_kv

    def body(h, lw):
        r = rms_norm(h, lw["ln1"], spec.eps)
        q = (r @ lw["q"]).reshape(b, t, nh, dk).astype(jnp.float32)
        k = (r @ lw["k"]).reshape(b, t, nh, dk).astype(jnp.float32)
        v = (r @ lw["v"]).reshape(b, t, nh, dk).astype(jnp.float32)
        s = jnp.einsum("bthd,bshd->bhts", q, k) + bias
        a = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), v)
        h = h + (a.reshape(b, t, nh * dk).astype(h.dtype) @ lw["o"])
        r = rms_norm(h, lw["ln2"], spec.eps)
        gelu = jax.nn.gelu(r @ lw["wi0"], approximate=True)
        h = h + ((gelu * (r @ lw["wi1"])) @ lw["wo"])
        return h, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return rms_norm(x, params["ln_f"], spec.eps)


def convert_t5_encoder(sd: Dict[str, np.ndarray], spec: T5Spec,
                       prefix: str = "encoder") -> Dict[str, Any]:
    def get(n):
        return np.asarray(sd[n], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"{prefix}.block.{i}"
        return {
            "ln1": get(f"{b}.layer.0.layer_norm.weight"),
            "q": t(get(f"{b}.layer.0.SelfAttention.q.weight")),
            "k": t(get(f"{b}.layer.0.SelfAttention.k.weight")),
            "v": t(get(f"{b}.layer.0.SelfAttention.v.weight")),
            "o": t(get(f"{b}.layer.0.SelfAttention.o.weight")),
            "ln2": get(f"{b}.layer.1.layer_norm.weight"),
            "wi0": t(get(f"{b}.layer.1.DenseReluDense.wi_0.weight")),
            "wi1": t(get(f"{b}.layer.1.DenseReluDense.wi_1.weight")),
            "wo": t(get(f"{b}.layer.1.DenseReluDense.wo.weight")),
        }

    layers = [lw(i) for i in range(spec.num_layers)]
    embed_key = "shared.weight" if "shared.weight" in sd else \
        f"{prefix}.embed_tokens.weight"
    return {
        "embed": get(embed_key),
        "rel_bias": get(f"{prefix}.block.0.layer.0.SelfAttention"
                        ".relative_attention_bias.weight"),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
        "ln_f": get(f"{prefix}.final_layer_norm.weight"),
    }
