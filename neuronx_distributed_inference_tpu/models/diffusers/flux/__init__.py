from .pipeline import (FluxImg2ImgPipeline, FluxPipeline,
                       build_random_pipeline, shifted_sigmas)
from .transformer import (FluxSpec, flux_forward, init_flux_params,
                          make_img_ids, pack_latents, unpack_latents)
from .vae import VaeSpec, init_vae_params, vae_decode
