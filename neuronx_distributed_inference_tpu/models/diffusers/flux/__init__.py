from .pipeline import (FluxControlPipeline, FluxFillPipeline,
                       FluxImg2ImgPipeline, FluxPipeline,
                       build_random_pipeline, fold_mask_8x8,
                       shifted_sigmas)
from .transformer import (FluxSpec, flux_forward, init_flux_params,
                          make_img_ids, pack_latents, unpack_latents)
from .vae import VaeSpec, init_vae_params, vae_decode
