"""FLUX rectified-flow transformer (reference: models/diffusers/flux/ —
transformer + pipeline submodels, 4772 LoC total; SURVEY §2.7).

Architecture (MMDiT): double-stream blocks keep image and text tokens in
separate parameter streams but attend JOINTLY; single-stream blocks run the
concatenated sequence through a fused qkv+mlp linear. All blocks are
modulated (adaLN) by the conditioning vector built from the timestep,
guidance scale and CLIP pooled embedding; positions use 3-axis rope
(t, h, w) over the latent patch grid.

lax.scan over stacked block weights, same design as the decoder LM stack."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....parallel.layers import ParamSpec
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class FluxSpec:
    hidden_size: int = 3072          # num_heads * head_dim
    num_heads: int = 24
    head_dim: int = 128
    mlp_ratio: float = 4.0
    depth_double: int = 19
    depth_single: int = 38
    in_channels: int = 64            # packed 2x2 latent patches (16ch VAE)
    # velocity output channels; None = in_channels. Control/Fill variants
    # read concatenated conditioning channels but predict only the base
    # latents (reference: diffusers FluxControl/Fill transformer geometry)
    out_channels: Optional[int] = None
    context_dim: int = 4096          # T5 features
    pooled_dim: int = 768            # CLIP pooled
    axes_dim: Tuple[int, int, int] = (16, 56, 56)   # rope split per axis
    guidance_embed: bool = True
    theta: float = 10000.0

    @property
    def mlp_hidden(self) -> int:
        return int(self.hidden_size * self.mlp_ratio)


def _linear(h_in, h_out, bias=True, shard=None):
    """shard: None (replicated), "col" (out dim over the model-parallel
    axes — ColumnParallelLinear analog), "row" (in dim — RowParallel; the
    contraction psum is inserted by GSPMD)."""
    from ....parallel.mesh import AXIS_MP
    wspec = {None: P(), "col": P(None, AXIS_MP), "row": P(AXIS_MP, None)}[shard]
    s = {"w": ParamSpec((h_in, h_out), wspec)}
    if bias:
        s["b"] = ParamSpec((h_out,), P(AXIS_MP) if shard == "col" else P(),
                           init="zeros")
    return s


def flux_param_specs(spec: FluxSpec) -> Dict[str, Any]:
    H, Hm = spec.hidden_size, spec.mlp_hidden
    D = spec.head_dim

    def stacked(tree, n):
        def f(ps):
            return ParamSpec((n,) + ps.shape, P(None, *ps.pspec),
                             ps.dtype, ps.init)
        return jax.tree.map(f, tree, is_leaf=lambda x: isinstance(x, ParamSpec))

    # TP sharding (reference: the repo's whisper/FLUX were flagged
    # weights-replicated; here the heavy projections shard like
    # Column/RowParallelLinear — qkv/mlp-in column, proj/mlp-out row; the
    # tiny modulation/rmsnorm params stay replicated. GSPMD inserts the
    # row-side psums.)
    double = {
        "img_mod": _linear(H, 6 * H), "txt_mod": _linear(H, 6 * H),
        "img_qkv": _linear(H, 3 * H, shard="col"),
        "txt_qkv": _linear(H, 3 * H, shard="col"),
        "img_qnorm": {"w": ParamSpec((D,), P(), init="ones")},
        "img_knorm": {"w": ParamSpec((D,), P(), init="ones")},
        "txt_qnorm": {"w": ParamSpec((D,), P(), init="ones")},
        "txt_knorm": {"w": ParamSpec((D,), P(), init="ones")},
        "img_proj": _linear(H, H, shard="row"),
        "txt_proj": _linear(H, H, shard="row"),
        "img_mlp1": _linear(H, Hm, shard="col"),
        "img_mlp2": _linear(Hm, H, shard="row"),
        "txt_mlp1": _linear(H, Hm, shard="col"),
        "txt_mlp2": _linear(Hm, H, shard="row"),
    }
    single = {
        "mod": _linear(H, 3 * H),
        "linear1": _linear(H, 3 * H + Hm, shard="col"),  # qkv + mlp_in fused
        "qnorm": {"w": ParamSpec((D,), P(), init="ones")},
        "knorm": {"w": ParamSpec((D,), P(), init="ones")},
        "linear2": _linear(H + Hm, H, shard="row"),
    }
    specs: Dict[str, Any] = {
        "img_in": _linear(spec.in_channels, H),
        "txt_in": _linear(spec.context_dim, H),
        "time_in1": _linear(256, H), "time_in2": _linear(H, H),
        "vector_in1": _linear(spec.pooled_dim, H), "vector_in2": _linear(H, H),
        "double": stacked(double, spec.depth_double),
        "single": stacked(single, spec.depth_single),
        "final_mod": _linear(H, 2 * H),
        "final_proj": _linear(H, spec.out_channels or spec.in_channels),
    }
    if spec.guidance_embed:
        specs["guidance_in1"] = _linear(256, H)
        specs["guidance_in2"] = _linear(H, H)
    return specs


def init_flux_params(spec: FluxSpec, key, mesh=None):
    from ...model_base import init_param_tree
    return init_param_tree(flux_param_specs(spec), key, mesh)


def _lin(p, x):
    y = x @ p["w"]
    return y + p["b"] if "b" in p else y


def timestep_embedding(t: jnp.ndarray, dim: int = 256,
                       max_period: float = 10000.0) -> jnp.ndarray:
    """(B,) in [0,1] -> (B, dim) sinusoidal (flux scales t by 1000)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = (t.astype(jnp.float32) * 1000.0)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def rope_3d(ids: jnp.ndarray, axes_dim: Tuple[int, ...], theta: float
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ids (B, T, 3) -> cos/sin (B, T, head_dim/2): per-axis rotary bands
    concatenated (flux position encoding over (t, h, w))."""
    outs_c, outs_s = [], []
    for i, d in enumerate(axes_dim):
        freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        ang = ids[..., i].astype(jnp.float32)[..., None] * freqs
        outs_c.append(jnp.cos(ang))
        outs_s.append(jnp.sin(ang))
    return jnp.concatenate(outs_c, -1), jnp.concatenate(outs_s, -1)


def _apply_rope_interleaved(x, cos, sin):
    """x (B,T,H,D); cos/sin (B,T,D/2); flux rotates interleaved pairs."""
    b, t, h, d = x.shape
    xf = x.astype(jnp.float32).reshape(b, t, h, d // 2, 2)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    x1, x2 = xf[..., 0], xf[..., 1]
    out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.reshape(b, t, h, d).astype(x.dtype)


def _rms(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    return (xf * w).astype(x.dtype)


def _ln(x, eps=1e-6):
    """Affine-free LayerNorm (flux modulation supplies shift/scale)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


def _attention(q, k, v, cos, sin):
    """Joint attention: q/k/v (B,T,Hh,D); rope applied to q,k."""
    q = _apply_rope_interleaved(q, cos, sin)
    k = _apply_rope_interleaved(k, cos, sin)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    b, t, h, d = q.shape
    return o.reshape(b, t, h * d).astype(v.dtype)


def flux_forward(spec: FluxSpec, params, img, txt, timestep, pooled,
                 img_ids, txt_ids, guidance=None):
    """img (B, T_img, in_channels) packed latents; txt (B, T_txt, 4096);
    timestep (B,) in [0,1]; pooled (B, 768); ids (B, T, 3).
    Returns the predicted velocity (B, T_img, in_channels)."""
    nh, d = spec.num_heads, spec.head_dim
    vec = _lin(params["time_in2"], jax.nn.silu(
        _lin(params["time_in1"], timestep_embedding(timestep))))
    if spec.guidance_embed:
        g = guidance if guidance is not None else jnp.ones_like(timestep)
        vec = vec + _lin(params["guidance_in2"], jax.nn.silu(
            _lin(params["guidance_in1"], timestep_embedding(g))))
    vec = vec + _lin(params["vector_in2"], jax.nn.silu(
        _lin(params["vector_in1"], pooled)))
    vec = jax.nn.silu(vec)[:, None, :]                 # (B,1,H)

    img = _lin(params["img_in"], img)
    txt = _lin(params["txt_in"], txt)
    t_txt = txt.shape[1]
    ids = jnp.concatenate([txt_ids, img_ids], axis=1)
    cos, sin = rope_3d(ids, spec.axes_dim, spec.theta)

    def split_heads(x):
        b, t, _ = x.shape
        return x.reshape(b, t, nh, d)

    def double_body(carry, lw):
        im, tx = carry
        im_m = _lin(lw["img_mod"], vec)
        tx_m = _lin(lw["txt_mod"], vec)
        i_sh1, i_sc1, i_g1, i_sh2, i_sc2, i_g2 = jnp.split(im_m, 6, -1)
        t_sh1, t_sc1, t_g1, t_sh2, t_sc2, t_g2 = jnp.split(tx_m, 6, -1)

        imn = _ln(im) * (1 + i_sc1) + i_sh1
        txn = _ln(tx) * (1 + t_sc1) + t_sh1
        iq, ik, iv = jnp.split(_lin(lw["img_qkv"], imn), 3, -1)
        tq, tk, tv = jnp.split(_lin(lw["txt_qkv"], txn), 3, -1)
        iq, ik = (_rms(split_heads(iq), lw["img_qnorm"]["w"]),
                  _rms(split_heads(ik), lw["img_knorm"]["w"]))
        tq, tk = (_rms(split_heads(tq), lw["txt_qnorm"]["w"]),
                  _rms(split_heads(tk), lw["txt_knorm"]["w"]))
        q = jnp.concatenate([tq, iq], axis=1)
        k = jnp.concatenate([tk, ik], axis=1)
        v = jnp.concatenate([split_heads(tv), split_heads(iv)], axis=1)
        attn = _attention(q, k, v, cos, sin)
        t_attn, i_attn = attn[:, :t_txt], attn[:, t_txt:]

        im = im + i_g1 * _lin(lw["img_proj"], i_attn)
        tx = tx + t_g1 * _lin(lw["txt_proj"], t_attn)
        imn = _ln(im) * (1 + i_sc2) + i_sh2
        txn = _ln(tx) * (1 + t_sc2) + t_sh2
        im = im + i_g2 * _lin(lw["img_mlp2"], jax.nn.gelu(
            _lin(lw["img_mlp1"], imn), approximate=True))
        tx = tx + t_g2 * _lin(lw["txt_mlp2"], jax.nn.gelu(
            _lin(lw["txt_mlp1"], txn), approximate=True))
        return (im, tx), None

    (img, txt), _ = jax.lax.scan(double_body, (img, txt), params["double"])

    x = jnp.concatenate([txt, img], axis=1)

    def single_body(h, lw):
        sh, sc, g = jnp.split(_lin(lw["mod"], vec), 3, -1)
        hn = _ln(h) * (1 + sc) + sh
        fused = _lin(lw["linear1"], hn)
        qkv, mlp = (fused[..., :3 * spec.hidden_size],
                    fused[..., 3 * spec.hidden_size:])
        q, k, v = jnp.split(qkv, 3, -1)
        q = _rms(split_heads(q), lw["qnorm"]["w"])
        k = _rms(split_heads(k), lw["knorm"]["w"])
        attn = _attention(q, k, split_heads(v), cos, sin)
        out = _lin(lw["linear2"], jnp.concatenate(
            [attn, jax.nn.gelu(mlp, approximate=True)], axis=-1))
        return h + g * out, None

    x, _ = jax.lax.scan(single_body, x, params["single"])
    img = x[:, t_txt:]

    sh, sc = jnp.split(_lin(params["final_mod"], jax.nn.silu(vec)), 2, -1)
    img = _ln(img) * (1 + sc) + sh
    return _lin(params["final_proj"], img)


# ---------------------------------------------------------------------------
# latent packing + position ids (flux packs 2x2 latent patches)
# ---------------------------------------------------------------------------

def pack_latents(lat: jnp.ndarray) -> jnp.ndarray:
    """(B, C, H, W) -> (B, H/2*W/2, C*4)."""
    b, c, h, w = lat.shape
    x = lat.reshape(b, c, h // 2, 2, w // 2, 2)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5))
    return x.reshape(b, (h // 2) * (w // 2), c * 4)


def unpack_latents(x: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """(B, H/2*W/2, C*4) -> (B, C, H, W)."""
    b, _, cc = x.shape
    c = cc // 4
    x = x.reshape(b, h // 2, w // 2, c, 2, 2)
    x = jnp.transpose(x, (0, 3, 1, 4, 2, 5))
    return x.reshape(b, c, h, w)


def make_img_ids(batch: int, h: int, w: int) -> np.ndarray:
    """(B, H/2*W/2, 3) position ids over the packed patch grid."""
    hh, ww = h // 2, w // 2
    ids = np.zeros((hh, ww, 3), np.int32)
    ids[..., 1] = np.arange(hh)[:, None]
    ids[..., 2] = np.arange(ww)[None, :]
    return np.broadcast_to(ids.reshape(1, hh * ww, 3),
                           (batch, hh * ww, 3)).copy()
