from .modeling_deepseek import (DeepseekFamily, DeepseekInferenceConfig,
                                TpuDeepseekForCausalLM)
