"""DeepSeek-V2/V3 family (reference: models/deepseek/modeling_deepseek.py
``DeepseekV3*`` — SURVEY §2.7: MLA attention, custom rope_util, 493 LoC).

Covered deltas:
  * MLA (multi-head latent attention): q-lora + kv-lora compression with a
    shared rope head (model_base._mla_qkv); K dim = nope+rope, V dim =
    v_head_dim
  * yarn rope with mscale attention factor; softmax scale *= mscale(all_dim)^2
  * sigmoid router with e_score_correction_bias (selection only),
    group-limited greedy routing (n_group/topk_group), routed_scaling_factor
  * mixed stacks: first_k_dense_replace dense layers then MoE layers with
    shared experts
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, MLASpec, spec_from_config
from ...parallel.layers import ParamSpec


class DeepseekInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "vocab_size", "kv_lora_rank", "qk_nope_head_dim",
                "qk_rope_head_dim", "v_head_dim"]


def deepseek_style_moe_weights(get, prefix: str, i: int, spec,
                              transpose) -> Dict[str, Any]:
    """DeepSeek-V3-shaped MoE weights for layer ``i``: sigmoid/softmax
    router (+ optional e_score_correction_bias), per-expert gate/up/down,
    optional shared experts. Shared by every family with this checkpoint
    shape (deepseek v2/v3, glm4_moe)."""
    E = spec.moe.num_experts
    out: Dict[str, Any] = {
        "router": transpose(get(
            f"{prefix}.layers.{i}.mlp.gate.weight")).astype(np.float32),
    }
    if spec.moe.has_router_bias:
        out["router_bias"] = np.asarray(get(
            f"{prefix}.layers.{i}.mlp.gate.e_score_correction_bias")).astype(
            np.float32)
    for key, name in (("expert_gate", "gate_proj"),
                      ("expert_up", "up_proj"),
                      ("expert_down", "down_proj")):
        out[key] = np.stack([
            transpose(get(f"{prefix}.layers.{i}.mlp.experts.{e}.{name}.weight"))
            for e in range(E)])
    if spec.moe.shared_intermediate:
        for key, name in (("shared_gate", "gate_proj"),
                          ("shared_up", "up_proj"),
                          ("shared_down", "down_proj")):
            out[key] = transpose(get(
                f"{prefix}.layers.{i}.mlp.shared_experts.{name}.weight"))
    return out


@register_family("deepseek_v3", "deepseek_v2")
class DeepseekFamily(DecoderFamily):
    config_cls = DeepseekInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        mla = MLASpec(
            kv_lora_rank=config.kv_lora_rank,
            qk_nope_head_dim=config.qk_nope_head_dim,
            qk_rope_head_dim=config.qk_rope_head_dim,
            v_head_dim=config.v_head_dim,
            q_lora_rank=getattr(config, "q_lora_rank", None),
        )
        scale = mla.qk_head_dim ** -0.5
        rope_scaling = getattr(config, "rope_scaling", None) or {}
        mscale_all_dim = rope_scaling.get("mscale_all_dim", 0) or 0
        if mscale_all_dim:
            f = float(rope_scaling["factor"])
            m = (1.0 if f <= 1 else
                 0.1 * mscale_all_dim * math.log(f) + 1.0)
            scale = scale * m * m
        moe = None
        first_dense = 0
        if getattr(config, "n_routed_experts", None):
            moe = MoESpec(
                num_experts=config.n_routed_experts,
                top_k=config.num_experts_per_tok,
                intermediate_size=config.moe_intermediate_size,
                normalize_topk=bool(getattr(config, "norm_topk_prob", True)),
                routed_scaling=float(getattr(config, "routed_scaling_factor",
                                             1.0)),
                router_act="sigmoid",
                has_router_bias=True,          # e_score_correction_bias
                router_bias_mode="select",
                shared_intermediate=(config.moe_intermediate_size
                                     * getattr(config, "n_shared_experts", 0)),
                n_group=int(getattr(config, "n_group", 1) or 1),
                topk_group=int(getattr(config, "topk_group", 1) or 1),
            )
            first_dense = int(getattr(config, "first_k_dense_replace", 0))
        spec = spec_from_config(
            config, tp_degree,
            mla=mla,
            moe=moe,
            first_dense=first_dense,
            head_dim=mla.qk_head_dim,
            attn_scale=scale,
            rope_interleaved=bool(getattr(config, "rope_interleave", True)),
        )
        # rope operates on the dedicated rope head only
        import dataclasses
        return dataclasses.replace(
            spec, rope=dataclasses.replace(spec.rope,
                                           head_dim=mla.qk_rope_head_dim))

    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray], spec: DecoderSpec
                              ) -> Dict[str, Any]:
        p = cls.hf_prefix
        L = spec.num_layers
        nd = spec.first_dense if spec.moe is not None else L

        def get(name):
            if name in sd:
                return np.asarray(sd[name])
            raise KeyError(f"missing checkpoint tensor {name}")

        def t(w):
            return np.ascontiguousarray(np.asarray(w).T)

        def ident(w):
            return np.asarray(w)

        def attn_layer(i: int) -> Dict[str, np.ndarray]:
            base = f"{p}.layers.{i}.self_attn"
            out = {
                "input_norm": ident(get(f"{p}.layers.{i}.input_layernorm.weight")),
                "post_norm": ident(get(
                    f"{p}.layers.{i}.post_attention_layernorm.weight")),
                "kv_a_proj": t(get(f"{base}.kv_a_proj_with_mqa.weight")),
                "kv_a_norm": ident(get(f"{base}.kv_a_layernorm.weight")),
                "kv_b_proj": t(get(f"{base}.kv_b_proj.weight")),
                "o_proj": t(get(f"{base}.o_proj.weight")),
            }
            if spec.mla.q_lora_rank:
                out["q_a_proj"] = t(get(f"{base}.q_a_proj.weight"))
                out["q_a_norm"] = ident(get(f"{base}.q_a_layernorm.weight"))
                out["q_b_proj"] = t(get(f"{base}.q_b_proj.weight"))
            else:
                out["q_proj"] = t(get(f"{base}.q_proj.weight"))
            return out

        def dense_layer(i: int) -> Dict[str, np.ndarray]:
            out = attn_layer(i)
            for k, n in (("gate_proj", "gate_proj"), ("up_proj", "up_proj"),
                         ("down_proj", "down_proj")):
                out[k] = t(get(f"{p}.layers.{i}.mlp.{n}.weight"))
            return out

        def moe_layer(i: int) -> Dict[str, np.ndarray]:
            out = attn_layer(i)
            out.update(deepseek_style_moe_weights(get, p, i, spec, t))
            return out

        def stack(dicts: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
            return {k: np.stack([d[k] for d in dicts]) for k in dicts[0]}

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0])] +
                           [(0, 0)] * (w.ndim - 1))
            return w

        out: Dict[str, Any] = {
            "embed": vpad(get(p + ".embed_tokens.weight")),
            "final_norm": ident(get(p + ".norm.weight")),
        }
        if spec.moe is not None and spec.first_dense > 0:
            out["layers"] = stack([dense_layer(i) for i in range(nd)])
            out["moe_layers"] = stack([moe_layer(i) for i in range(nd, L)])
        elif spec.moe is not None:
            out["layers"] = stack([moe_layer(i) for i in range(L)])
        else:
            out["layers"] = stack([dense_layer(i) for i in range(L)])
        if not spec.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(vpad(get("lm_head.weight")).T)
        return out


def TpuDeepseekForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, DeepseekFamily)
