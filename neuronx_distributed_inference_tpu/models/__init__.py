"""Model hub (reference: models/ — SURVEY §2.7)."""

from . import family  # noqa: F401
from .llama import modeling_llama  # noqa: F401
from .dbrx import modeling_dbrx  # noqa: F401
from .deepseek import modeling_deepseek  # noqa: F401
from .gemma2 import modeling_gemma2  # noqa: F401
from .gemma3 import modeling_gemma3  # noqa: F401
from .granite import modeling_granite  # noqa: F401
from .olmo2 import modeling_olmo2  # noqa: F401
from .phi3 import modeling_phi3  # noqa: F401
from .gpt_oss import modeling_gpt_oss  # noqa: F401
from .mistral import modeling_mistral  # noqa: F401
from .mixtral import modeling_mixtral  # noqa: F401
from .qwen2 import modeling_qwen2  # noqa: F401
from .qwen3 import modeling_qwen3  # noqa: F401
from .qwen3_moe import modeling_qwen3_moe  # noqa: F401
