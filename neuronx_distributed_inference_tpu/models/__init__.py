"""Model hub (reference: models/ — SURVEY §2.7)."""

from . import family  # noqa: F401
from .llama import modeling_llama  # noqa: F401  (registers "llama")
