"""mistral family."""
from .modeling_mistral import *  # noqa: F401,F403
