"""Mistral family (reference: models/mistral/modeling_mistral.py
``NeuronMistralForCausalLM``). Llama-shaped with optional sliding-window
attention (Mistral-7B-v0.1 window=4096)."""

from __future__ import annotations

from typing import List, Optional

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class MistralInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]


@register_family("mistral", "ministral")
class MistralFamily(DecoderFamily):
    """mistral + ministral (reference: contrib/models/
    Ministral-4b-instruct — mistral-shaped, uniformly sliding layers)."""
    config_cls = MistralInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        window = getattr(config, "sliding_window", None) or 0
        lt = list(getattr(config, "layer_types", []) or [])
        pattern = (tuple(t == "sliding_attention" for t in lt)
                   if lt and not all(t == lt[0] for t in lt) else None)
        if lt and all(t == "full_attention" for t in lt):
            window = 0
        return spec_from_config(config, tp_degree, sliding_window=int(window),
                                layer_pattern=pattern)
