"""Wav2Vec2 audio frame classifier (reference: contrib/models/
LaughterSegmentation — a Wav2Vec2-based per-frame laughter classifier).

Covers both HF variants: feat_extract_norm "group" (base: GroupNorm on the
first conv layer only) / "layer" (large: LayerNorm after every conv), and
do_stable_layer_norm False (post-LN encoder) / True (pre-LN). The
positional convolution's weight-norm parametrization is reconstructed at
conversion (w = g * v / ||v|| per kernel slot)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..ops.normalization import layer_norm
from ..utils import checkpoint as ckpt


@dataclass(frozen=True)
class Wav2Vec2Spec:
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    conv_dim: Tuple[int, ...]
    conv_kernel: Tuple[int, ...]
    conv_stride: Tuple[int, ...]
    pos_kernel: int
    pos_groups: int
    feat_norm: str = "group"         # "group" | "layer"
    stable_ln: bool = False          # pre-LN encoder (wav2vec2-large)
    num_labels: int = 2
    eps: float = 1e-5


def spec_from_hf(cfg) -> Wav2Vec2Spec:
    g = lambda k, d=None: getattr(cfg, k, d) if not isinstance(cfg, dict) \
        else cfg.get(k, d)
    return Wav2Vec2Spec(
        hidden_size=int(g("hidden_size")),
        num_layers=int(g("num_hidden_layers")),
        num_heads=int(g("num_attention_heads")),
        intermediate_size=int(g("intermediate_size")),
        conv_dim=tuple(int(x) for x in g("conv_dim")),
        conv_kernel=tuple(int(x) for x in g("conv_kernel")),
        conv_stride=tuple(int(x) for x in g("conv_stride")),
        pos_kernel=int(g("num_conv_pos_embeddings", 128)),
        pos_groups=int(g("num_conv_pos_embedding_groups", 16)),
        feat_norm=str(g("feat_extract_norm", "group")),
        stable_ln=bool(g("do_stable_layer_norm", False)),
        num_labels=int(g("num_labels", 2)),
        eps=float(g("layer_norm_eps", 1e-5)),
    )


def _conv1d(x, w, b=None, stride=1, pad=0, groups=1):
    """x (B, C, T), w (O, I/g, K)."""
    y = jax.lax.conv_general_dilated(
        x, w, (stride,), [(pad, pad)], feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"))
    if b is not None:
        y = y + b[:, None]
    return y


def wav2vec2_forward(spec: Wav2Vec2Spec, params, waveform: jnp.ndarray
                     ) -> jnp.ndarray:
    """waveform (B, T_samples) -> frame logits (B, T_frames, num_labels)."""
    x = waveform[:, None, :]                       # (B, 1, T)
    for i, (k, s) in enumerate(zip(spec.conv_kernel, spec.conv_stride)):
        lw = params["conv_layers"][i]
        x = _conv1d(x, lw["w"], lw.get("b"), stride=s)
        if spec.feat_norm == "group" and i == 0:
            # GroupNorm(groups == channels): per-channel instance norm;
            # torch hardcodes eps=1e-5 here regardless of layer_norm_eps
            mu = x.mean(axis=2, keepdims=True)
            var = x.var(axis=2, keepdims=True)
            x = (x - mu) * jax.lax.rsqrt(var + 1e-5)
            x = x * lw["ln_w"][:, None] + lw["ln_b"][:, None]
        elif spec.feat_norm == "layer":
            x = layer_norm(x.transpose(0, 2, 1), lw["ln_w"], lw["ln_b"],
                           1e-5).transpose(0, 2, 1)
        x = jax.nn.gelu(x, approximate=False)
    x = x.transpose(0, 2, 1)                       # (B, T, C_last)

    x = layer_norm(x, params["proj_ln_w"], params["proj_ln_b"], spec.eps)
    x = x @ params["proj_w"] + params["proj_b"]

    # positional conv (weight-norm reconstructed at load); HF trims the
    # last output when the kernel is even
    pos = _conv1d(x.transpose(0, 2, 1), params["pos_w"], params["pos_b"],
                  pad=spec.pos_kernel // 2, groups=spec.pos_groups)
    if spec.pos_kernel % 2 == 0:
        pos = pos[:, :, :-1]
    x = x + jax.nn.gelu(pos, approximate=False).transpose(0, 2, 1)
    if not spec.stable_ln:
        x = layer_norm(x, params["enc_ln_w"], params["enc_ln_b"], spec.eps)

    nh = spec.num_heads
    hd = spec.hidden_size // nh
    b, t, d = x.shape
    for lw in params["layers"]:
        r = (layer_norm(x, lw["ln1_w"], lw["ln1_b"], spec.eps)
             if spec.stable_ln else x)
        q = (r @ lw["q_w"] + lw["q_b"]).reshape(b, t, nh, hd)
        k = (r @ lw["k_w"] + lw["k_b"]).reshape(b, t, nh, hd)
        v = (r @ lw["v_w"] + lw["v_b"]).reshape(b, t, nh, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        x = x + (a.reshape(b, t, d).astype(x.dtype) @ lw["o_w"] + lw["o_b"])
        if not spec.stable_ln:
            x = layer_norm(x, lw["ln1_w"], lw["ln1_b"], spec.eps)
        r = (layer_norm(x, lw["ln2_w"], lw["ln2_b"], spec.eps)
             if spec.stable_ln else x)
        m = jax.nn.gelu(r @ lw["fc1_w"] + lw["fc1_b"], approximate=False)
        x = x + m @ lw["fc2_w"] + lw["fc2_b"]
        if not spec.stable_ln:
            x = layer_norm(x, lw["ln2_w"], lw["ln2_b"], spec.eps)
    if spec.stable_ln:
        x = layer_norm(x, params["enc_ln_w"], params["enc_ln_b"], spec.eps)
    return x @ params["cls_w"] + params["cls_b"]


def convert_wav2vec2(sd, spec: Wav2Vec2Spec, prefix="wav2vec2"):
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    conv_layers = []
    for i in range(len(spec.conv_kernel)):
        lw = {"w": get(f"feature_extractor.conv_layers.{i}.conv.weight")}
        bias_key = f"{prefix}.feature_extractor.conv_layers.{i}.conv.bias"
        if bias_key in sd:          # conv_bias=True (wav2vec2-large)
            lw["b"] = np.asarray(sd[bias_key], np.float32)
        if (spec.feat_norm == "layer"
                or (spec.feat_norm == "group" and i == 0)):
            lw["ln_w"] = get(f"feature_extractor.conv_layers.{i}"
                             ".layer_norm.weight")
            lw["ln_b"] = get(f"feature_extractor.conv_layers.{i}"
                             ".layer_norm.bias")
        conv_layers.append(lw)

    # weight-norm: w[o, i, k] = g[0, 0, k] * v[o, i, k] / ||v[:, :, k]||
    base = "encoder.pos_conv_embed.conv"
    if f"{prefix}.{base}.parametrizations.weight.original0" in sd:
        g_key, v_key = (f"{base}.parametrizations.weight.original0",
                        f"{base}.parametrizations.weight.original1")
    else:                       # older checkpoints: weight_g / weight_v
        g_key, v_key = f"{base}.weight_g", f"{base}.weight_v"
    g0, v = get(g_key), get(v_key)
    norm = np.sqrt((v ** 2).sum(axis=(0, 1), keepdims=True))
    pos_w = v * (g0 / np.maximum(norm, 1e-12))

    def enc_layer(i):
        p = f"encoder.layers.{i}"
        return {
            "q_w": t(get(f"{p}.attention.q_proj.weight")),
            "q_b": get(f"{p}.attention.q_proj.bias"),
            "k_w": t(get(f"{p}.attention.k_proj.weight")),
            "k_b": get(f"{p}.attention.k_proj.bias"),
            "v_w": t(get(f"{p}.attention.v_proj.weight")),
            "v_b": get(f"{p}.attention.v_proj.bias"),
            "o_w": t(get(f"{p}.attention.out_proj.weight")),
            "o_b": get(f"{p}.attention.out_proj.bias"),
            "ln1_w": get(f"{p}.layer_norm.weight"),
            "ln1_b": get(f"{p}.layer_norm.bias"),
            "fc1_w": t(get(f"{p}.feed_forward.intermediate_dense.weight")),
            "fc1_b": get(f"{p}.feed_forward.intermediate_dense.bias"),
            "fc2_w": t(get(f"{p}.feed_forward.output_dense.weight")),
            "fc2_b": get(f"{p}.feed_forward.output_dense.bias"),
            "ln2_w": get(f"{p}.final_layer_norm.weight"),
            "ln2_b": get(f"{p}.final_layer_norm.bias"),
        }

    return {
        "conv_layers": conv_layers,
        "proj_ln_w": get("feature_projection.layer_norm.weight"),
        "proj_ln_b": get("feature_projection.layer_norm.bias"),
        "proj_w": t(get("feature_projection.projection.weight")),
        "proj_b": get("feature_projection.projection.bias"),
        "pos_w": pos_w, "pos_b": get(f"{base}.bias"),
        "enc_ln_w": get("encoder.layer_norm.weight"),
        "enc_ln_b": get("encoder.layer_norm.bias"),
        "layers": [enc_layer(i) for i in range(spec.num_layers)],
        "cls_w": t(np.asarray(sd["classifier.weight"], np.float32)),
        "cls_b": np.asarray(sd["classifier.bias"], np.float32),
    }


class Wav2Vec2FrameClassifierConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_hidden_layers", "num_attention_heads",
                "intermediate_size", "conv_dim", "conv_kernel",
                "conv_stride"]

    def get_text_config(self):
        return self


class Wav2Vec2FrameClassifierApplication:
    """Per-frame audio classifier (LaughterSegmentation-style serving)."""

    def __init__(self, model_path: Optional[str],
                 config: Wav2Vec2FrameClassifierConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        if getattr(config, "use_weighted_layer_sum", False):
            raise NotImplementedError(
                "use_weighted_layer_sum checkpoints (SUPERB convention) "
                "classify from a learned sum over ALL layer outputs — not "
                "implemented; only last-hidden-state heads are supported")
        self.spec = spec_from_hf(config)
        self.params = None
        # OPT-IN sample-length buckets bound the compile count for
        # variable-length serving. Default 1 = exact (no padding): the
        # feature extractor's time-axis GroupNorm folds padding into every
        # frame's statistics, so padded inference matches HF's
        # padded-batch semantics, not the unpadded single-audio result —
        # callers choose the trade-off explicitly.
        self.sample_bucket = int(getattr(config, "sample_bucket", 1))
        self._fwd = jax.jit(partial(wav2vec2_forward, self.spec))

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        self.params = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            convert_wav2vec2(sd, self.spec))
        return self

    def _frames_for(self, n_samples: int) -> int:
        t = n_samples
        for k, s in zip(self.spec.conv_kernel, self.spec.conv_stride):
            t = (t - k) // s + 1
        return t

    def predict(self, waveform: np.ndarray) -> np.ndarray:
        """(B, T_samples) float waveform -> (B, T_frames, num_labels).

        With ``sample_bucket`` > 1 (serving), waveforms are right-padded
        to a bucket multiple so new lengths reuse compiled graphs; frames
        are trimmed to the TRUE length's count, and the numerics match HF
        on the PADDED batch (the time-axis GroupNorm sees the padding) —
        exact-match single-audio inference keeps the default bucket 1."""
        wav = np.asarray(waveform, np.float32)
        t = wav.shape[1]
        pad = (-t) % self.sample_bucket
        if pad:
            wav = np.pad(wav, ((0, 0), (0, pad)))
        out = np.asarray(self._fwd(self.params, jnp.asarray(wav)))
        return out[:, : self._frames_for(t)]
