from .modeling_phi3 import (Phi3Family, Phi3InferenceConfig,
                            TpuPhi3ForCausalLM)
