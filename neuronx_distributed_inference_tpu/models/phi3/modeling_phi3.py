"""Phi-3 family (reference analog: contrib phi models — SURVEY §2.7).
Llama-shaped with FUSED projections: qkv_proj (q|k|v halves) and
gate_up_proj (gate|up halves, chunked not interleaved); no biases."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec
from ...parallel.layers import place_q_weight, replicate_kv_weight


class Phi3InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]


@register_family("phi3", "phi4")
class Phi3Family(DecoderFamily):
    config_cls = Phi3InferenceConfig

    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray], spec: DecoderSpec
                              ) -> Dict[str, np.ndarray]:
        """Split the fused projections into the standard layout, then let the
        base converter do the rest."""
        g = spec.gqa
        D = spec.head_dim
        nq, nkv = g.orig_q_heads * D, g.orig_kv_heads * D
        I = spec.intermediate_size
        split = dict(sd)
        for k in list(sd):
            if k.endswith("self_attn.qkv_proj.weight"):
                w = np.asarray(sd[k])
                base = k[: -len("qkv_proj.weight")]
                split[base + "q_proj.weight"] = w[:nq]
                split[base + "k_proj.weight"] = w[nq:nq + nkv]
                split[base + "v_proj.weight"] = w[nq + nkv:nq + 2 * nkv]
            elif k.endswith("mlp.gate_up_proj.weight"):
                w = np.asarray(sd[k])
                base = k[: -len("gate_up_proj.weight")]
                split[base + "gate_proj.weight"] = w[:I]
                split[base + "up_proj.weight"] = w[I:]
        return super().convert_hf_state_dict(split, spec)


def TpuPhi3ForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, Phi3Family)
