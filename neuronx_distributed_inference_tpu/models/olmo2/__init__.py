from .modeling_olmo2 import (Olmo2Family, Olmo2InferenceConfig,
                            TpuOlmo2ForCausalLM)
