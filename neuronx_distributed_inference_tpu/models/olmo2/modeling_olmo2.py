"""OLMo2 family (reference analog: contrib olmo models — SURVEY §2.7).
POST-norm architecture: no pre-norms; RMSNorm applied to the attention and
MLP OUTPUTS before the residual add; full-width q/k RMSNorm pre head-split.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config
from ...parallel.layers import place_q_weight, replicate_kv_weight


class Olmo2InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]


@register_family("olmo2")
class Olmo2Family(DecoderFamily):
    config_cls = Olmo2InferenceConfig
    # the spec's pre-MLP "post_norm" slot is unused in post-norm mode; feed it
    # the post_attention weights so the base converter finds a real tensor
    post_norm_src = "post_attention_layernorm"

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        return spec_from_config(
            config, tp_degree,
            norm_position="post",
            sandwich_norm=True,       # provides post_attn/post_ff norm slots
            qk_norm_full=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray], spec: DecoderSpec
                              ) -> Dict[str, np.ndarray]:
        # olmo2 has no input_layernorm; the (unused) pre-norm slots load ones
        aug = dict(sd)
        H = spec.hidden_size
        for i in range(spec.num_layers):
            aug[f"model.layers.{i}.input_layernorm.weight"] = np.ones(
                (H,), np.float32)
        return super().convert_hf_state_dict(aug, spec)

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec: DecoderSpec
                                    ) -> Dict[str, np.ndarray]:
        g = spec.gqa
        D = spec.head_dim
        p = cls.hf_prefix

        def ident(w):
            return np.asarray(w)

        def q_n(w):   # full-width norm weight follows the padded q layout
            return place_q_weight(np.asarray(w), g, D)

        def kv_n(w):
            return replicate_kv_weight(np.asarray(w), g, D)

        return {
            "post_attn_norm": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.weight", ident),
            "post_ff_norm": layer_stack(
                p + ".layers.{i}.post_feedforward_layernorm.weight", ident),
            "q_norm": layer_stack(p + ".layers.{i}.self_attn.q_norm.weight",
                                  q_n),
            "k_norm": layer_stack(p + ".layers.{i}.self_attn.k_norm.weight",
                                  kv_n),
        }


def TpuOlmo2ForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, Olmo2Family)
