"""Granite family (reference analog: contrib granite models — SURVEY §2.7).
Llama-shaped with the IBM multiplier set: embedding_multiplier on the
embeddings, attention_multiplier as the softmax scale, residual_multiplier
on every block output, logits_scaling dividing the lm-head logits."""

from __future__ import annotations

from typing import List, Optional

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class GraniteInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]


@register_family("granite")
class GraniteFamily(DecoderFamily):
    config_cls = GraniteInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        return spec_from_config(
            config, tp_degree,
            embed_scale=float(getattr(config, "embedding_multiplier", 1.0)),
            attn_scale=float(getattr(config, "attention_multiplier",
                                     None) or 0) or None,
            residual_multiplier=float(getattr(config, "residual_multiplier",
                                              1.0)),
            logits_divide=float(getattr(config, "logits_scaling", 0) or 0)
            or None,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )


def TpuGraniteForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, GraniteFamily)
