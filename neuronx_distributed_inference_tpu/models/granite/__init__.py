from .modeling_granite import (GraniteFamily, GraniteInferenceConfig,
                            TpuGraniteForCausalLM)
