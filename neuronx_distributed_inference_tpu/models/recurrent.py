"""Recurrent / hybrid model families on the SSM state axis:

  * Falcon-H1 — parallel hybrid: every layer runs a Mamba-2 mixer NEXT TO
    standard attention, plus MuP multipliers throughout (reference:
    contrib/models/Falcon-H1-0.5B-Instruct/src/modeling_falcon_h1.py).
    All MuP multipliers are folded into the WEIGHTS at conversion time
    (they are all linear pre/post scalings), so the traced graph carries
    zero extra multiplies; the tied embedding/lm-head pair is untied at
    conversion because the two carry different multipliers.
  * RecurrentGemma (Griffin) — interleaved rec/rec/attn pattern of RG-LRU
    recurrent blocks and sliding-window MQA attention (reference:
    contrib/models/recurrentgemma-2b-it/src/modeling_recurrent_gemma.py).
"""

from __future__ import annotations

import math
from typing import List

import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..modules.ssm import SSMSpec
from ..parallel.layers import place_q_weight, replicate_kv_weight
from .family import DecoderFamily, register_family
from .model_base import spec_from_config


def _t(w):
    return np.ascontiguousarray(np.asarray(w).T)


class FalconH1InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "mamba_d_ssm", "mamba_n_heads", "mamba_d_state"]

    def get_text_config(self):
        return self


@register_family("falcon_h1")
class FalconH1Family(DecoderFamily):
    """Falcon-H1 hybrid attention+mamba2
    (reference: contrib/models/Falcon-H1-0.5B-Instruct/src/)."""

    config_cls = FalconH1InferenceConfig
    post_norm_src = "pre_ff_layernorm"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        d_ssm = getattr(config, "mamba_d_ssm", None) \
            or getattr(config, "mamba_expand", 2) * H
        extras = (
            ("embedding_multiplier",
             float(getattr(config, "embedding_multiplier", 1.0))),
            ("lm_head_multiplier",
             float(getattr(config, "lm_head_multiplier", 1.0))),
            ("key_multiplier", float(getattr(config, "key_multiplier", 1.0))),
            ("attention_in_multiplier",
             float(getattr(config, "attention_in_multiplier", 1.0))),
            ("attention_out_multiplier",
             float(getattr(config, "attention_out_multiplier", 1.0))),
            ("mlp_multipliers",
             tuple(float(x) for x in
                   getattr(config, "mlp_multipliers", (1.0, 1.0)))),
            ("ssm_multipliers",
             tuple(float(x) for x in
                   getattr(config, "ssm_multipliers", (1.0,) * 5))),
            ("ssm_in_multiplier",
             float(getattr(config, "ssm_in_multiplier", 1.0))),
            ("ssm_out_multiplier",
             float(getattr(config, "ssm_out_multiplier", 1.0))),
        )
        return spec_from_config(
            config, tp_degree,
            ssm=SSMSpec(
                kind="mamba2",
                d_inner=int(d_ssm),
                num_heads=int(config.mamba_n_heads),
                head_dim=int(getattr(config, "mamba_d_head",
                                     d_ssm // config.mamba_n_heads)),
                d_state=int(config.mamba_d_state),
                n_groups=int(getattr(config, "mamba_n_groups", 1)),
                d_conv=int(getattr(config, "mamba_d_conv", 4)),
                chunk_size=int(getattr(config, "mamba_chunk_size", 128)),
                conv_bias=bool(getattr(config, "mamba_conv_bias", True)),
                gated_norm=bool(getattr(config, "mamba_rms_norm", False)),
                norm_before_gate=bool(
                    getattr(config, "mamba_norm_before_gate", True)),
                norm_eps=float(getattr(config, "rms_norm_eps", 1e-5)),
            ),
            ssm_parallel=True,
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=bool(getattr(config, "attention_bias", False)),
            # embedding and lm-head carry DIFFERENT MuP multipliers — the
            # pair is untied at conversion even when the checkpoint ties it
            tie_word_embeddings=False,
            extras=extras,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        """Fold every MuP multiplier into the weights, rename the falcon-h1
        module names onto the base-converter layout, then let the base
        handle attention/MLP/norms; the mamba weights land via
        ``convert_extra_layer_weights``."""
        sd = dict(sd)
        aim = spec.extra("attention_in_multiplier", 1.0)
        km = spec.extra("key_multiplier", 1.0)
        aom = spec.extra("attention_out_multiplier", 1.0)
        mm = spec.extra("mlp_multipliers", (1.0, 1.0))
        em = spec.extra("embedding_multiplier", 1.0)
        lm = spec.extra("lm_head_multiplier", 1.0)

        def scale(key, m):
            if key in sd and m != 1.0:
                sd[key] = np.asarray(sd[key]) * np.asarray(sd[key]).dtype.type(m)

        embed_raw = np.asarray(sd["model.embed_tokens.weight"])
        if "lm_head.weight" not in sd:          # tied checkpoint: untie
            sd["lm_head.weight"] = embed_raw.copy()
        scale("lm_head.weight", lm)
        scale("model.embed_tokens.weight", em)
        for i in range(spec.num_layers):
            p = f"model.layers.{i}."
            scale(p + "self_attn.q_proj.weight", aim)
            scale(p + "self_attn.k_proj.weight", aim * km)
            scale(p + "self_attn.v_proj.weight", aim)
            scale(p + "self_attn.o_proj.weight", aom)
            for src, dst, m in (("gate_proj", "gate_proj", mm[0]),
                                ("up_proj", "up_proj", 1.0),
                                ("down_proj", "down_proj", mm[1])):
                k = p + f"feed_forward.{src}.weight"
                if k in sd:
                    scale(k, m)
                    sd[p + f"mlp.{dst}.weight"] = sd.pop(k)
        if "model.final_layernorm.weight" in sd:
            sd["model.norm.weight"] = sd.pop("model.final_layernorm.weight")
        return super().convert_hf_state_dict(sd, spec)

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        s = spec.ssm
        d = s.d_inner
        gn = s.n_groups * s.d_state
        nh = s.num_heads
        sim = spec.extra("ssm_in_multiplier", 1.0)
        m0, m1, m2, m3, m4 = spec.extra("ssm_multipliers", (1.0,) * 5)
        p = "model.layers.{i}.mamba."

        def in_part(lo, hi, mult):
            # in_proj rows [gate d | x d | B gn | C gn | dt nh] with the
            # section's mup multiplier and ssm_in_multiplier folded in
            def tr(w):
                w = np.asarray(w)[lo:hi].T
                return np.ascontiguousarray(w * w.dtype.type(sim * mult))
            return tr

        def conv_part(lo, hi):
            return lambda w: np.ascontiguousarray(np.asarray(w)[lo:hi, 0, :])

        def conv_bias_part(lo, hi):
            return lambda b: np.ascontiguousarray(np.asarray(b)[lo:hi])

        def f32(w):
            return np.asarray(w).astype(np.float32)

        def out_t(w):
            w = _t(w)
            som = spec.extra("ssm_out_multiplier", 1.0)
            return np.ascontiguousarray(w * w.dtype.type(som))

        out = {
            "ssm_in_gate": layer_stack(p + "in_proj.weight", in_part(0, d, m0)),
            "ssm_in_x": layer_stack(p + "in_proj.weight",
                                    in_part(d, 2 * d, m1)),
            "ssm_in_bc": np.concatenate([
                layer_stack(p + "in_proj.weight",
                            in_part(2 * d, 2 * d + gn, m2)),
                layer_stack(p + "in_proj.weight",
                            in_part(2 * d + gn, 2 * d + 2 * gn, m3)),
            ], axis=-1),
            "ssm_in_dt": layer_stack(p + "in_proj.weight",
                                     in_part(2 * d + 2 * gn,
                                             2 * d + 2 * gn + nh, m4)),
            "ssm_conv_x": layer_stack(p + "conv1d.weight", conv_part(0, d)),
            "ssm_conv_bc": layer_stack(p + "conv1d.weight",
                                       conv_part(d, d + 2 * gn)),
            "ssm_dt_bias": layer_stack(p + "dt_bias", f32),
            "ssm_A_log": layer_stack(p + "A_log", f32),
            "ssm_D": layer_stack(p + "D", f32),
            "ssm_out": layer_stack(p + "out_proj.weight", out_t),
        }
        if s.conv_bias:
            out["ssm_conv_x_b"] = layer_stack(p + "conv1d.bias",
                                              conv_bias_part(0, d))
            out["ssm_conv_bc_b"] = layer_stack(p + "conv1d.bias",
                                               conv_bias_part(d, d + 2 * gn))
        if s.gated_norm:
            out["ssm_norm"] = layer_stack(p + "norm.weight",
                                          lambda w: np.asarray(w))
        return out

    @classmethod
    def load_hf_model(cls, model_path: str):
        import transformers
        return transformers.FalconH1ForCausalLM.from_pretrained(model_path)


class RecurrentGemmaInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "vocab_size", "lru_width", "block_types"]

    def get_text_config(self):
        return self


@register_family("recurrent_gemma")
class RecurrentGemmaFamily(DecoderFamily):
    """RecurrentGemma / Griffin: rec/rec/attn interleave of RG-LRU blocks
    and sliding-window MQA (reference: contrib/models/recurrentgemma-2b-it/
    src/modeling_recurrent_gemma.py)."""

    config_cls = RecurrentGemmaInferenceConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = getattr(config, "head_dim", None) or H // nh
        W = int(getattr(config, "lru_width", None) or H)
        bt = list(getattr(config, "block_types",
                          ("recurrent", "recurrent", "attention")))
        pattern = tuple((bt * config.num_hidden_layers)[
            :config.num_hidden_layers])
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            ssm=SSMSpec(
                kind="rglru",
                d_inner=W,
                num_heads=nh,
                head_dim=W // nh,
                d_conv=int(getattr(config, "conv1d_width", 4)),
            ),
            ssm_pattern=tuple(x == "recurrent" for x in pattern),
            ssm_parallel=False,
            sliding_window=int(getattr(config, "attention_window_size",
                                       2048)),
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=True,                      # rgemma o_proj always has bias
            rotary_dim=int(hd * float(getattr(config,
                                              "partial_rotary_factor", 0.5))),
            act=getattr(config, "hidden_activation", "gelu_pytorch_tanh"),
            # HF halves the config intermediate for the actual MLP width
            intermediate_size=config.intermediate_size // 2,
            mlp_bias=True,
            # HF rounds the sqrt(H) embedding normalizer through bfloat16
            embed_scale=float(jnp.bfloat16(math.sqrt(H))),
            norm_offset=1.0,                  # gemma (1+w) RMSNorm
            logits_soft_cap=float(getattr(config, "logits_soft_cap", 30.0)),
            rms_eps=float(getattr(config, "rms_norm_eps", 1e-6)),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        """Interleaved layout: "layers" = every layer's norms + MLP;
        "attn_layers"/"ssm_layers" = the temporal blocks, stacked in order
        of appearance (reference weight names:
        modeling_recurrent_gemma.py RecurrentGemmaDecoderLayer)."""
        g = spec.gqa
        D = spec.head_dim
        pat = spec.resolved_ssm_pattern

        def get(n):
            return np.asarray(sd[n])

        def stack(idx, fmt, tr):
            return np.stack([tr(get(fmt.format(i=i))) for i in idx])

        all_i = list(range(spec.num_layers))
        attn_i = [i for i in all_i if not pat[i]]
        ssm_i = [i for i in all_i if pat[i]]
        p = "model.layers.{i}."
        tb = p + "temporal_block."

        layers = {
            "input_norm": stack(all_i, p + "temporal_pre_norm.weight",
                                np.asarray),
            "post_norm": stack(all_i, p + "channel_pre_norm.weight",
                               np.asarray),
        }
        for w in ("gate", "up", "down"):
            layers[w + "_proj"] = stack(
                all_i, p + f"mlp_block.{w}_proj.weight", _t)
            layers[w + "_bias"] = stack(
                all_i, p + f"mlp_block.{w}_proj.bias", np.asarray)

        def q_t(w):
            return place_q_weight(_t(w), g, D, axis=-1)

        def kv_t(w):
            return replicate_kv_weight(_t(w), g, D, axis=-1)

        attn_layers = {} if not attn_i else {
            "qkv_proj": np.concatenate([
                stack(attn_i, tb + "q_proj.weight", q_t),
                stack(attn_i, tb + "k_proj.weight", kv_t),
                stack(attn_i, tb + "v_proj.weight", kv_t)], axis=-1),
            "o_proj": stack(attn_i, tb + "o_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(attn_i, tb + "o_proj.bias", np.asarray),
        }

        def f32(w):
            return np.asarray(w).astype(np.float32)

        ssm_layers = {} if not ssm_i else {
            "rg_y": stack(ssm_i, tb + "linear_y.weight", _t),
            "rg_y_b": stack(ssm_i, tb + "linear_y.bias", np.asarray),
            "rg_x": stack(ssm_i, tb + "linear_x.weight", _t),
            "rg_x_b": stack(ssm_i, tb + "linear_x.bias", np.asarray),
            "rg_out": stack(ssm_i, tb + "linear_out.weight", _t),
            "rg_out_b": stack(ssm_i, tb + "linear_out.bias", np.asarray),
            "rg_conv": stack(ssm_i, tb + "conv_1d.weight",
                             lambda w: np.asarray(w)[:, 0, :]),
            "rg_conv_b": stack(ssm_i, tb + "conv_1d.bias", np.asarray),
            "rg_param": stack(ssm_i, tb + "rg_lru.recurrent_param", f32),
            "rg_igate_w": stack(ssm_i, tb + "rg_lru.input_gate_weight",
                                np.asarray),
            "rg_igate_b": stack(ssm_i, tb + "rg_lru.input_gate_bias",
                                np.asarray),
            "rg_rgate_w": stack(ssm_i, tb + "rg_lru.recurrent_gate_weight",
                                np.asarray),
            "rg_rgate_b": stack(ssm_i, tb + "rg_lru.recurrent_gate_bias",
                                np.asarray),
        }

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0]), (0, 0)])
            return w

        out = {
            "embed": vpad(get("model.embed_tokens.weight")),
            "layers": layers,
            "final_norm": get("model.final_norm.weight"),
        }
        if attn_layers:
            out["attn_layers"] = attn_layers
        if ssm_layers:
            out["ssm_layers"] = ssm_layers
        if not spec.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(
                vpad(get("lm_head.weight")).T)
        return out

    @classmethod
    def load_hf_model(cls, model_path: str):
        import transformers
        return transformers.RecurrentGemmaForCausalLM.from_pretrained(
            model_path)
