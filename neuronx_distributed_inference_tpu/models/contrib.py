"""Contrib model hub — the breadth wave of community decoder families
(reference: contrib/models/, 64 community models each with src + tests —
SURVEY §2.7). Every family here is a thin DecoderSpec mapping + checkpoint
conversion over the shared layer machinery (model_base.py), mirroring how
the reference's contrib models subclass its L5 bases.

Families: gpt2, gpt_neox (pythia), falcon, starcoder2, phi (phi-1/2),
gemma (v1), olmo (v1), glm4, stablelm, cohere (command-r)."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ..config import InferenceConfig
from .family import DecoderFamily, register_family
from .model_base import DecoderSpec, pad_vocab, spec_from_config
from ..parallel.layers import place_q_weight, replicate_kv_weight


class _SimpleConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["vocab_size"]

    def get_text_config(self):
        return self


def _t(w):
    return np.ascontiguousarray(np.asarray(w).T)


def _ident(w):
    return np.asarray(w)


def _split_interleaved_qkv(get, key_fmt, num_layers, nh, g, D,
                           with_bias=True):
    """Split a per-head-interleaved fused QKV stack — weights (nh, 3, D, H)
    per layer (gpt_neox / bloom / persimmon layout) — into placed q/k/v
    weight (and bias) stacks."""
    qs, ks, vs, qb, kb, vb = [], [], [], [], [], []
    for i in range(num_layers):
        w = np.asarray(get(key_fmt.format(i=i) + ".weight"))
        w = w.reshape(nh, 3, D, -1)
        qs.append(place_q_weight(_t(w[:, 0].reshape(nh * D, -1)), g, D,
                                 axis=-1))
        ks.append(replicate_kv_weight(_t(w[:, 1].reshape(nh * D, -1)), g, D,
                                      axis=-1))
        vs.append(replicate_kv_weight(_t(w[:, 2].reshape(nh * D, -1)), g, D,
                                      axis=-1))
        if with_bias:
            b = np.asarray(get(key_fmt.format(i=i) + ".bias")).reshape(
                nh, 3, D)
            qb.append(place_q_weight(b[:, 0].reshape(-1), g, D))
            kb.append(replicate_kv_weight(b[:, 1].reshape(-1), g, D))
            vb.append(replicate_kv_weight(b[:, 2].reshape(-1), g, D))
    out = {"qkv_proj": np.concatenate(
        [np.stack(qs), np.stack(ks), np.stack(vs)], axis=-1)}
    if with_bias:
        out["qkv_bias"] = np.concatenate(
            [np.stack(qb), np.stack(kb), np.stack(vb)], axis=-1)
    return out


# ---------------------------------------------------------------------------
# GPT-2 (reference: contrib/models/gpt2)
# ---------------------------------------------------------------------------

@register_family("gpt2")
class GPT2Family(DecoderFamily):
    """Learned positions, fused Conv1D c_attn, plain gelu MLP, LN+bias."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.n_embd
        nh = config.n_head
        inner = getattr(config, "n_inner", None) or 4 * H
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layer,
            hidden_size=H,
            num_q_heads=nh,
            num_kv_heads=nh,
            head_dim=H // nh,
            intermediate_size=inner,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act={"gelu_new": "gelu_new", "gelu": "gelu",
                 "gelu_pytorch_tanh": "gelu_pytorch_tanh"}.get(
                getattr(config, "activation_function", "gelu_new"),
                "gelu_new"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            no_rope=True,
            learned_pos=int(getattr(config, "n_positions", 1024)),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        H = spec.hidden_size
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        def split_cattn(w):   # Conv1D weight (H, 3H) already (in, out)
            return np.asarray(w)[:, :H], np.asarray(w)[:, H:2 * H], \
                np.asarray(w)[:, 2 * H:]

        qs, ks, vs, qb, kb, vb = [], [], [], [], [], []
        for i in range(spec.num_layers):
            wq, wk, wv = split_cattn(get(f"{p}.h.{i}.attn.c_attn.weight"))
            bq, bk, bv = np.split(get(f"{p}.h.{i}.attn.c_attn.bias"), 3)
            qs.append(place_q_weight(wq, g, D, axis=-1))
            ks.append(replicate_kv_weight(wk, g, D, axis=-1))
            vs.append(replicate_kv_weight(wv, g, D, axis=-1))
            qb.append(place_q_weight(bq, g, D))
            kb.append(replicate_kv_weight(bk, g, D))
            vb.append(replicate_kv_weight(bv, g, D))
        layers = {
            "input_norm": stack(p + ".h.{i}.ln_1.weight", _ident),
            "input_norm_b": stack(p + ".h.{i}.ln_1.bias", _ident),
            "post_norm": stack(p + ".h.{i}.ln_2.weight", _ident),
            "post_norm_b": stack(p + ".h.{i}.ln_2.bias", _ident),
            "q_proj": np.stack(qs), "k_proj": np.stack(ks),
            "v_proj": np.stack(vs),
            "q_bias": np.stack(qb), "k_bias": np.stack(kb),
            "v_bias": np.stack(vb),
            # c_proj is Conv1D: already (in, out); pad the q-sized input axis
            "o_proj": stack(p + ".h.{i}.attn.c_proj.weight",
                            lambda w: place_q_weight(np.asarray(w), g, D,
                                                     axis=0)),
            "o_bias": stack(p + ".h.{i}.attn.c_proj.bias", _ident),
            "gate_proj": stack(p + ".h.{i}.mlp.c_fc.weight", _ident),
            "gate_bias": stack(p + ".h.{i}.mlp.c_fc.bias", _ident),
            "down_proj": stack(p + ".h.{i}.mlp.c_proj.weight", _ident),
            "down_bias": stack(p + ".h.{i}.mlp.c_proj.bias", _ident),
        }
        # fuse q/k/v (+biases) like the shared path
        layers["qkv_proj"] = np.concatenate(
            [layers.pop("q_proj"), layers.pop("k_proj"),
             layers.pop("v_proj")], axis=-1)
        layers["qkv_bias"] = np.concatenate(
            [layers.pop("q_bias"), layers.pop("k_bias"),
             layers.pop("v_bias")], axis=-1)

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0]), (0, 0)])
            return w

        return {
            "embed": vpad(get(p + ".wte.weight")),
            "pos_embed": get(p + ".wpe.weight"),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
        }


# ---------------------------------------------------------------------------
# GPT-NeoX / Pythia (reference: contrib/models gpt_neox-style families)
# ---------------------------------------------------------------------------

@register_family("gpt_neox")
class GPTNeoXFamily(DecoderFamily):
    """Per-head-interleaved fused QKV, partial rotary, parallel-dual
    residual, plain gelu MLP, LN+bias."""
    config_cls = _SimpleConfig
    hf_prefix = "gpt_neox"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = H // nh
        return spec_from_config(
            config, tp_degree,
            num_kv_heads=nh,
            head_dim=hd,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            act=getattr(config, "hidden_act", "gelu"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            rotary_dim=int(hd * getattr(config, "rotary_pct", 0.25)),
            block_style=("parallel_dual"
                         if getattr(config, "use_parallel_residual", True)
                         else "sequential"),
            tie_word_embeddings=False,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        nh = spec.num_q_heads
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        fused = _split_interleaved_qkv(
            get, p + ".layers.{i}.attention.query_key_value",
            spec.num_layers, nh, g, D)
        layers = {
            "input_norm": stack(p + ".layers.{i}.input_layernorm.weight", _ident),
            "input_norm_b": stack(p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm": stack(p + ".layers.{i}.post_attention_layernorm.weight", _ident),
            "post_norm_b": stack(p + ".layers.{i}.post_attention_layernorm.bias", _ident),
            **fused,
            "o_proj": stack(p + ".layers.{i}.attention.dense.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "o_bias": stack(p + ".layers.{i}.attention.dense.bias", _ident),
            "gate_proj": stack(p + ".layers.{i}.mlp.dense_h_to_4h.weight", _t),
            "gate_bias": stack(p + ".layers.{i}.mlp.dense_h_to_4h.bias", _ident),
            "down_proj": stack(p + ".layers.{i}.mlp.dense_4h_to_h.weight", _t),
            "down_bias": stack(p + ".layers.{i}.mlp.dense_4h_to_h.bias", _ident),
        }

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0]), (0, 0)])
            return w

        return {
            "embed": vpad(get(p + ".embed_in.weight")),
            "layers": layers,
            "final_norm": get(p + ".final_layer_norm.weight"),
            "final_norm_b": get(p + ".final_layer_norm.bias"),
            "lm_head": _t(vpad(get("embed_out.weight"))),
        }


# ---------------------------------------------------------------------------
# Falcon (reference: contrib/models/falcon)
# ---------------------------------------------------------------------------

@register_family("falcon")
class FalconFamily(DecoderFamily):
    """Fused grouped QKV, parallel-shared residual (falcon-7B style),
    plain gelu MLP, LN+bias."""
    config_cls = _SimpleConfig
    hf_prefix = "transformer"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        new_arch = bool(getattr(config, "new_decoder_architecture", False))
        n_kv = (config.num_kv_heads if new_arch
                else (1 if getattr(config, "multi_query", True) else nh))
        parallel = bool(getattr(config, "parallel_attn", True))
        # old arch (falcon-7b): ONE shared norm feeds attn and MLP;
        # new arch (falcon-40b/180b): separate ln_attn / ln_mlp, both over
        # the block input -> parallel_dual
        if new_arch:
            style = "parallel_dual"
        elif parallel:
            style = "parallel_shared"
        else:
            style = "sequential"
        return spec_from_config(
            config, tp_degree,
            num_kv_heads=n_kv,
            head_dim=H // nh,
            intermediate_size=getattr(config, "ffn_hidden_size", None)
            or 4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act="gelu",
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=bool(getattr(config, "bias", False)),
            qkv_bias=bool(getattr(config, "bias", False)),
            o_bias=bool(getattr(config, "bias", False)),
            block_style=style,
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        nh = spec.num_q_heads
        nkv = spec.num_kv_heads
        p = cls.hf_prefix

        def get(n):
            return np.asarray(sd[n])

        def stack(fmt, tr):
            return np.stack([tr(get(fmt.format(i=i)))
                             for i in range(spec.num_layers)])

        gsize = nh // nkv
        qs, ks, vs = [], [], []
        for i in range(spec.num_layers):
            w = get(f"{p}.h.{i}.self_attention.query_key_value.weight")
            # falcon fused layout: (nkv, g+2, hd, H) — q heads of each kv
            # group, then that group's k and v
            w = w.reshape(nkv, gsize + 2, D, -1)
            q = w[:, :gsize].reshape(nkv * gsize * D, -1)
            k = w[:, gsize].reshape(nkv * D, -1)
            v = w[:, gsize + 1].reshape(nkv * D, -1)
            qs.append(place_q_weight(_t(q), g, D, axis=-1))
            ks.append(replicate_kv_weight(_t(k), g, D, axis=-1))
            vs.append(replicate_kv_weight(_t(v), g, D, axis=-1))
        new_arch = any(".ln_attn." in k for k in sd)
        ln = "ln_attn" if new_arch else "input_layernorm"
        if new_arch:
            # falcon-40b style: separate MLP norm over the block input
            post_norm = stack(p + ".h.{i}.ln_mlp.weight", _ident)
            post_norm_b = stack(p + ".h.{i}.ln_mlp.bias", _ident)
        else:
            # parallel_shared never reads post_norm; keep identity
            post_norm = np.ones((spec.num_layers, spec.hidden_size),
                                np.float32)
            post_norm_b = np.zeros((spec.num_layers, spec.hidden_size),
                                   np.float32)
        layers = {
            "input_norm": stack(p + ".h.{i}." + ln + ".weight", _ident),
            "input_norm_b": stack(p + ".h.{i}." + ln + ".bias", _ident),
            "post_norm": post_norm,
            "post_norm_b": post_norm_b,
            "qkv_proj": np.concatenate(
                [np.stack(qs), np.stack(ks), np.stack(vs)], axis=-1),
            "o_proj": stack(p + ".h.{i}.self_attention.dense.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "gate_proj": stack(p + ".h.{i}.mlp.dense_h_to_4h.weight", _t),
            "down_proj": stack(p + ".h.{i}.mlp.dense_4h_to_h.weight", _t),
        }
        if spec.qkv_bias:
            qbs, kbs, vbs = [], [], []
            for i in range(spec.num_layers):
                b = get(f"{p}.h.{i}.self_attention.query_key_value.bias")
                b = b.reshape(nkv, gsize + 2, D)
                qbs.append(place_q_weight(
                    b[:, :gsize].reshape(-1), g, D))
                kbs.append(replicate_kv_weight(b[:, gsize].reshape(-1), g, D))
                vbs.append(replicate_kv_weight(
                    b[:, gsize + 1].reshape(-1), g, D))
            layers["qkv_bias"] = np.concatenate(
                [np.stack(qbs), np.stack(kbs), np.stack(vbs)], axis=-1)
        if spec.o_bias:
            layers["o_bias"] = stack(
                p + ".h.{i}.self_attention.dense.bias", _ident)
        if spec.mlp_bias:
            layers["gate_bias"] = stack(
                p + ".h.{i}.mlp.dense_h_to_4h.bias", _ident)
            layers["down_bias"] = stack(
                p + ".h.{i}.mlp.dense_4h_to_h.bias", _ident)

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0]), (0, 0)])
            return w

        return {
            "embed": vpad(get(p + ".word_embeddings.weight")),
            "layers": layers,
            "final_norm": get(p + ".ln_f.weight"),
            "final_norm_b": get(p + ".ln_f.bias"),
        }


# ---------------------------------------------------------------------------
# StarCoder2 (reference: contrib/models/starcoder2)
# ---------------------------------------------------------------------------

@register_family("starcoder2")
class Starcoder2Family(DecoderFamily):
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        bias = bool(getattr(config, "use_bias", True))
        return spec_from_config(
            config, tp_degree,
            rms_eps=float(getattr(config, "norm_epsilon", 1e-5)),
            act=getattr(config, "hidden_act", "gelu_pytorch_tanh"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=bias,
            qkv_bias=bias, o_bias=bias,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        out = {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.c_fc.weight", _t),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.c_proj.weight", _t),
        }
        if spec.mlp_bias:
            out["gate_bias"] = layer_stack(p + ".layers.{i}.mlp.c_fc.bias",
                                           _ident)
            out["down_bias"] = layer_stack(p + ".layers.{i}.mlp.c_proj.bias",
                                           _ident)
        return out

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "input_norm_b": layer_stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm_b": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.bias", _ident),
        }

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        out = super().convert_hf_state_dict(sd, spec)
        out["final_norm_b"] = np.asarray(sd["model.norm.bias"])
        return out


# ---------------------------------------------------------------------------
# Phi (phi-1 / phi-2) (reference: contrib/models/phi)
# ---------------------------------------------------------------------------

@register_family("phi")
class PhiFamily(DecoderFamily):
    """Parallel-shared residual, partial rotary, plain gelu MLP, LN+bias,
    biased lm_head."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = H // nh
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            act=getattr(config, "hidden_act", "gelu_new"),
            norm_type="layernorm", norm_bias=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True, lm_head_bias=True,
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.5)),
            block_style="parallel_shared",
            tie_word_embeddings=False,
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.fc1.weight", _t),
            "gate_bias": layer_stack(p + ".layers.{i}.mlp.fc1.bias", _ident),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.fc2.weight", _t),
            "down_bias": layer_stack(p + ".layers.{i}.mlp.fc2.bias", _ident),
        }

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        L, H = spec.num_layers, spec.hidden_size
        return {
            "input_norm_b": layer_stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            # parallel_shared: post_norm unused
            "post_norm": np.ones((L, H), np.float32),
            "post_norm_b": np.zeros((L, H), np.float32),
        }

    # phi has no post_attention_layernorm; base conversion must not fetch it
    post_norm_src = "input_layernorm"
    attn_o_src = "self_attn.dense"

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        sd = dict(sd)
        # phi names its final norm "final_layernorm"
        sd.setdefault("model.norm.weight",
                      np.asarray(sd["model.final_layernorm.weight"]))
        out = super().convert_hf_state_dict(sd, spec)
        out["final_norm"] = np.asarray(sd["model.final_layernorm.weight"])
        out["final_norm_b"] = np.asarray(sd["model.final_layernorm.bias"])
        out["lm_head_b"] = _vpad1(np.asarray(sd["lm_head.bias"]),
                                  spec.padded_vocab)
        return out


# ---------------------------------------------------------------------------
# Gemma v1 (reference: contrib/models/gemma)
# ---------------------------------------------------------------------------

@register_family("gemma")
class GemmaFamily(DecoderFamily):
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            head_dim=config.head_dim,
            norm_offset=1.0,
            embed_scale=math.sqrt(config.hidden_size),
            act=getattr(config, "hidden_activation", None)
            or "gelu_pytorch_tanh",
            tie_word_embeddings=True,
        )


# ---------------------------------------------------------------------------
# OLMo v1 (reference: contrib/models/olmo)
# ---------------------------------------------------------------------------

@register_family("olmo")
class OlmoFamily(DecoderFamily):
    """Non-parametric LayerNorm (no weight/bias in the checkpoint)."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            norm_type="layernorm",
            rms_eps=1e-5,
            qkv_clip=getattr(config, "clip_qkv", None),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        # synthesize unit norm weights: OLMo's LayerNorm has no params
        L, H = spec.num_layers, spec.hidden_size
        ones = np.ones((H,), np.float32)
        sd = dict(sd)
        for i in range(L):
            sd.setdefault(f"model.layers.{i}.input_layernorm.weight", ones)
            sd.setdefault(f"model.layers.{i}.post_attention_layernorm.weight",
                          ones)
        sd.setdefault("model.norm.weight", ones)
        return super().convert_hf_state_dict(sd, spec)


# ---------------------------------------------------------------------------
# GLM-4 (reference: contrib/models/glm)
# ---------------------------------------------------------------------------

@register_family("glm4")
class Glm4Family(DecoderFamily):
    """Fused gate_up MLP, sandwich norms, partial interleaved rotary."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = getattr(config, "head_dim", None) or H // nh
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            qkv_bias=bool(getattr(config, "attention_bias", True)),
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.5)),
            rope_interleaved=True,
            sandwich_norm=True,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             False)),
        )

    post_norm_src = "post_attention_layernorm"

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        I = spec.intermediate_size

        def gate(w):
            return _t(np.asarray(w)[:I])

        def up(w):
            return _t(np.asarray(w)[I:])

        return {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.gate_up_proj.weight",
                                     gate),
            "up_proj": layer_stack(p + ".layers.{i}.mlp.gate_up_proj.weight",
                                   up),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.down_proj.weight",
                                     _t),
        }

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "post_attn_norm": layer_stack(
                p + ".layers.{i}.post_self_attn_layernorm.weight", _ident),
            "post_ff_norm": layer_stack(
                p + ".layers.{i}.post_mlp_layernorm.weight", _ident),
        }


# ---------------------------------------------------------------------------
# StableLM (reference: contrib/models/stablelm)
# ---------------------------------------------------------------------------

@register_family("stablelm")
class StableLmFamily(DecoderFamily):
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        nh = config.num_attention_heads
        hd = H // nh
        return spec_from_config(
            config, tp_degree,
            head_dim=hd,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            norm_type="layernorm", norm_bias=True,
            qkv_bias=bool(getattr(config, "use_qkv_bias", False)),
            rotary_dim=int(hd * getattr(config, "partial_rotary_factor",
                                        0.25)),
            tie_word_embeddings=False,
        )

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "input_norm_b": layer_stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm_b": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.bias", _ident),
        }

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        out = super().convert_hf_state_dict(sd, spec)
        out["final_norm"] = np.asarray(sd["model.norm.weight"])
        out["final_norm_b"] = np.asarray(sd["model.norm.bias"])
        return out


# ---------------------------------------------------------------------------
# Cohere / Command-R (reference: contrib/models/cohere)
# ---------------------------------------------------------------------------

@register_family("cohere")
class CohereFamily(DecoderFamily):
    """Parallel-shared residual, bias-free LayerNorm, logit scaling,
    tied embeddings."""
    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        scale = float(getattr(config, "logit_scale", 1.0))
        return spec_from_config(
            config, tp_degree,
            rms_eps=float(getattr(config, "layer_norm_eps", 1e-5)),
            norm_type="layernorm",
            block_style="parallel_shared",
            logits_divide=1.0 / scale if scale else None,
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        L, H = spec.num_layers, spec.hidden_size
        return {"post_norm": np.ones((L, H), np.float32)}

    post_norm_src = "input_layernorm"   # parallel_shared: post_norm unused


def _vpad(w: np.ndarray, padded: int) -> np.ndarray:
    if w.shape[0] < padded:
        w = np.pad(w, [(0, padded - w.shape[0])] + [(0, 0)] * (w.ndim - 1))
    return w


def _vpad1(b: np.ndarray, padded: int) -> np.ndarray:
    if b.shape[0] < padded:
        b = np.pad(b, (0, padded - b.shape[0]))
    return b
