"""Contrib hub wave 3 (reference: contrib/models/ — SURVEY §2.7):
openai-gpt (post-LN GPT-1), LFM2 (hybrid short-conv + attention),
VaultGemma, Apertus (xIELU), Phi-3.5-MoE (sparsemixer routing)."""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..config import InferenceConfig
from ..modules.moe import MoESpec
from ..modules.ssm import SSMSpec
from ..parallel.layers import place_q_weight, replicate_kv_weight
from .contrib import GPT2Family, _SimpleConfig, _ident, _t
from .family import DecoderFamily, register_family
from .model_base import spec_from_config
from .contrib import StableLmFamily
from .olmo2.modeling_olmo2 import Olmo2Family
from ..ops.rope import RopeConfig


@register_family("openai-gpt")
class OpenAIGPTFamily(GPT2Family):
    """GPT-1 (reference: contrib/models/openai-gpt): gpt2-shaped fused
    Conv1D attention + learned positions, but POST-layernorm blocks
    (x = ln(x + sublayer(x))) and no final norm."""

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.n_embd
        nh = config.n_head
        return spec_from_config(
            config, tp_degree,
            num_layers=config.n_layer,
            hidden_size=H,
            num_q_heads=nh, num_kv_heads=nh, head_dim=H // nh,
            intermediate_size=getattr(config, "n_inner", None) or 4 * H,
            rms_eps=float(getattr(config, "layer_norm_epsilon", 1e-5)),
            act={"gelu": "gelu_new", "gelu_new": "gelu_new",
                 "relu": "relu", "silu": "silu"}.get(
                getattr(config, "afn", "gelu"), "gelu_new"),
            norm_type="layernorm", norm_bias=True,
            norm_position="post_residual", skip_final_norm=True,
            mlp_glu=False, mlp_bias=True,
            qkv_bias=True, o_bias=True,
            no_rope=True,
            learned_pos=int(getattr(config, "n_positions", 512)),
            tie_word_embeddings=True,
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        sd = dict(sd)
        p = cls.hf_prefix
        # tokens_embed/positions_embed -> the gpt2 wte/wpe names the base
        # converter consumes; no ln_f exists (skip_final_norm)
        sd[p + ".wte.weight"] = sd.pop(p + ".tokens_embed.weight")
        sd[p + ".wpe.weight"] = sd.pop(p + ".positions_embed.weight")
        H = spec.hidden_size
        sd[p + ".ln_f.weight"] = np.ones((H,), np.float32)
        sd[p + ".ln_f.bias"] = np.zeros((H,), np.float32)
        out = super().convert_hf_state_dict(sd, spec)
        out.pop("final_norm", None)
        out.pop("final_norm_b", None)
        return out


class Lfm2InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "vocab_size", "layer_types", "conv_L_cache"]

    def get_text_config(self):
        return self


@register_family("lfm2")
class Lfm2Family(DecoderFamily):
    """Liquid LFM2 (reference: contrib/models/lfm2-2.6b): interleaved
    gated-short-conv and attention layers on the recurrent state axis,
    per-head q/k RMSNorm applied BEFORE rope, w1/w3/w2 GLU MLP."""

    config_cls = Lfm2InferenceConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        inter = config.intermediate_size
        if getattr(config, "block_auto_adjust_ff_dim", False):
            inter = int(2 * inter / 3)
            mult = getattr(config, "block_ffn_dim_multiplier", None)
            if mult is not None:
                inter = int(mult * inter)
            mo = int(getattr(config, "block_multiple_of", 256))
            inter = mo * ((inter + mo - 1) // mo)
        lt = list(config.layer_types)
        return spec_from_config(
            config, tp_degree,
            intermediate_size=inter,
            rms_eps=float(getattr(config, "norm_eps", 1e-5)),
            qk_norm=True,
            ssm=SSMSpec(kind="shortconv", d_inner=H, num_heads=1,
                        head_dim=H,
                        d_conv=int(config.conv_L_cache),
                        conv_bias=bool(getattr(config, "conv_bias", False))),
            ssm_pattern=tuple(t == "conv" for t in lt),
            ssm_parallel=False,
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        g, D = spec.gqa, spec.head_dim
        H = spec.hidden_size
        pat = spec.resolved_ssm_pattern

        def get(n):
            return np.asarray(sd[n])

        def stack(idx, fmt, tr):
            return np.stack([tr(get(fmt.format(i=i))) for i in idx])

        all_i = list(range(spec.num_layers))
        attn_i = [i for i in all_i if not pat[i]]
        conv_i = [i for i in all_i if pat[i]]
        p = "model.layers.{i}."

        layers = {
            "input_norm": stack(all_i, p + "operator_norm.weight", _ident),
            "post_norm": stack(all_i, p + "ffn_norm.weight", _ident),
            "gate_proj": stack(all_i, p + "feed_forward.w1.weight", _t),
            "up_proj": stack(all_i, p + "feed_forward.w3.weight", _t),
            "down_proj": stack(all_i, p + "feed_forward.w2.weight", _t),
        }
        attn_layers = {} if not attn_i else {
            "qkv_proj": np.concatenate([
                stack(attn_i, p + "self_attn.q_proj.weight",
                      lambda w: place_q_weight(_t(w), g, D, axis=-1)),
                stack(attn_i, p + "self_attn.k_proj.weight",
                      lambda w: replicate_kv_weight(_t(w), g, D, axis=-1)),
                stack(attn_i, p + "self_attn.v_proj.weight",
                      lambda w: replicate_kv_weight(_t(w), g, D, axis=-1)),
            ], axis=-1),
            "o_proj": stack(attn_i, p + "self_attn.out_proj.weight",
                            lambda w: place_q_weight(_t(w), g, D, axis=0)),
            "q_norm": stack(attn_i, p + "self_attn.q_layernorm.weight",
                            _ident),
            "k_norm": stack(attn_i, p + "self_attn.k_layernorm.weight",
                            _ident),
        }
        ssm_layers = {} if not conv_i else {
            # in_proj rows [B | C | x] (HF BCx chunk order)
            "sc_in_b": stack(conv_i, p + "conv.in_proj.weight",
                             lambda w: _t(np.asarray(w)[:H])),
            "sc_in_c": stack(conv_i, p + "conv.in_proj.weight",
                             lambda w: _t(np.asarray(w)[H:2 * H])),
            "sc_in_x": stack(conv_i, p + "conv.in_proj.weight",
                             lambda w: _t(np.asarray(w)[2 * H:])),
            "sc_conv": stack(conv_i, p + "conv.conv.weight",
                             lambda w: np.asarray(w)[:, 0, :]),
            "sc_out": stack(conv_i, p + "conv.out_proj.weight", _t),
        }
        if spec.ssm.conv_bias and conv_i:
            ssm_layers["sc_conv_b"] = stack(
                conv_i, p + "conv.conv.bias", _ident)
            ssm_layers["sc_out_b"] = stack(
                conv_i, p + "conv.out_proj.bias", _ident)
            for part, key in (("b", "sc_in_b_b"), ("c", "sc_in_c_b"),
                              ("x", "sc_in_x_b")):
                lo = {"b": 0, "c": H, "x": 2 * H}[part]
                ssm_layers[key] = stack(
                    conv_i, p + "conv.in_proj.bias",
                    lambda bvec, lo=lo: np.asarray(bvec)[lo:lo + H])

        def vpad(w):
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0]), (0, 0)])
            return w

        out = {
            "embed": vpad(get("model.embed_tokens.weight")),
            "layers": layers,
            "final_norm": get("model.embedding_norm.weight"),
        }
        if attn_layers:
            out["attn_layers"] = attn_layers
        if ssm_layers:
            out["ssm_layers"] = ssm_layers
        if not spec.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(
                vpad(get("lm_head.weight")).T)
        return out

    @classmethod
    def load_hf_model(cls, model_path: str):
        import transformers
        return transformers.Lfm2ForCausalLM.from_pretrained(model_path)


@register_family("vaultgemma")
class VaultGemmaFamily(DecoderFamily):
    """VaultGemma (reference: contrib/models/vaultgemma-1b): gemma2-style
    soft caps + alternating sliding/full layers, but only two pre-norms
    per layer (no sandwich norms)."""

    config_cls = _SimpleConfig
    post_norm_src = "pre_feedforward_layernorm"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        H = config.hidden_size
        lt = list(getattr(config, "layer_types", []) or [])
        pattern = (tuple(t == "sliding_attention" for t in lt)
                   if lt and not all(t == lt[0] for t in lt) else None)
        window = int(getattr(config, "sliding_window", 0) or 0)
        qpre = getattr(config, "query_pre_attn_scalar", None)
        return spec_from_config(
            config, tp_degree,
            act=getattr(config, "hidden_activation", "gelu_pytorch_tanh"),
            embed_scale=math.sqrt(H),
            norm_offset=1.0,
            attn_scale=(float(qpre) ** -0.5 if qpre else None),
            attn_soft_cap=getattr(config, "attn_logit_softcapping", None),
            logits_soft_cap=getattr(config, "final_logit_softcapping", None),
            sliding_window=window,
            layer_pattern=pattern,
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=bool(getattr(config, "attention_bias", False)),
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )


class ApertusInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size"]

    def get_text_config(self):
        return self


@register_family("apertus")
class ApertusFamily(DecoderFamily):
    """Swiss AI Apertus (reference: contrib/models/Apertus-8B-Instruct-2509):
    llama attention + per-head q/k RMSNorm before rope + plain up/down MLP
    with the learned-alpha xIELU activation."""

    config_cls = ApertusInferenceConfig
    input_norm_src = "attention_layernorm"
    post_norm_src = "feedforward_layernorm"

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            qk_norm=True,
            mlp_glu=False,
            act="xielu",
            qkv_bias=bool(getattr(config, "attention_bias", False)),
            o_bias=bool(getattr(config, "attention_bias", False)),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            # plain-MLP slots: gate_proj/down_proj hold fc1/fc2
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.up_proj.weight",
                                     _t),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.down_proj.weight",
                                     _t),
        }

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = "model.layers.{i}.mlp.act_fn."

        def scalar(name):
            def tr(i):
                return np.float32(np.asarray(get(p.format(i=i) + name))
                                  .reshape(-1)[0])
            return tr

        xi = np.stack([
            np.array([scalar("alpha_p")(i), scalar("alpha_n")(i),
                      scalar("beta")(i), scalar("eps")(i)], np.float32)
            for i in range(spec.num_layers)])
        return {"xielu": xi}


@register_family("phimoe")
class PhimoeFamily(DecoderFamily):
    """Phi-3.5-MoE (reference: contrib/models/Phi-3.5-MoE-instruct):
    mixtral-shaped 16-expert top-2 MoE with the sparsemixer inference
    routing, LayerNorm (with bias) norms, and an optional lm-head bias."""

    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        moe = MoESpec(
            num_experts=config.num_local_experts,
            top_k=config.num_experts_per_tok,
            intermediate_size=config.intermediate_size,
            normalize_topk=False,
            router_act="sparsemixer",
            sparsemixer_eps=float(getattr(config, "router_jitter_noise",
                                          0.01)),
            act=getattr(config, "hidden_act", "silu"),
        )
        bias = bool(getattr(config, "attention_bias", False))
        window = getattr(config, "sliding_window", None) or 0
        return spec_from_config(
            config, tp_degree, moe=moe,
            norm_type="layernorm", norm_bias=True,
            qkv_bias=bias, o_bias=bias,
            lm_head_bias=bool(getattr(config, "lm_head_bias", False)),
            sliding_window=int(window),
        )

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return cls.convert_moe_weights(
            get, spec,
            router_name=p + ".layers.{i}.block_sparse_moe.gate.weight",
            expert_fmt=(p + ".layers.{i}.block_sparse_moe.experts.{e}."
                        "{name}.weight"),
            gate="w1", up="w3", down="w2")

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec):
        p = cls.hf_prefix
        return {
            "input_norm_b": layer_stack(
                p + ".layers.{i}.input_layernorm.bias", _ident),
            "post_norm_b": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.bias", _ident),
        }

    @classmethod
    def convert_hf_state_dict(cls, sd, spec):
        out = super().convert_hf_state_dict(sd, spec)
        out["final_norm_b"] = np.asarray(sd["model.norm.bias"])
        if spec.lm_head_bias and "lm_head.bias" in sd:
            b = np.asarray(sd["lm_head.bias"])
            if b.shape[0] < spec.padded_vocab:
                b = np.pad(b, (0, spec.padded_vocab - b.shape[0]))
            out["lm_head_b"] = b
        return out

    @classmethod
    def load_hf_model(cls, model_path: str):
        from transformers.models.phimoe import PhimoeForCausalLM
        return PhimoeForCausalLM.from_pretrained(model_path)


@register_family("minicpm", "minicpm4")
class MiniCPMFamily(DecoderFamily):
    """MiniCPM / MiniCPM4 (reference: contrib/models/MiniCPM4-8B/src/
    modeling_minicpm.py): llama shape with MuP-style scalings — embeddings
    x scale_emb, every sublayer residual x scale_depth/sqrt(L), lm-head
    input / (hidden/dim_model_base) — and longrope scaling for the 4-series.
    The scalings map 1:1 onto existing spec knobs (embed_scale,
    residual_multiplier, logits_divide)."""

    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        L = config.num_hidden_layers
        H = config.hidden_size
        dmb = float(getattr(config, "dim_model_base", H) or H)
        return spec_from_config(
            config, tp_degree,
            embed_scale=float(getattr(config, "scale_emb", 1.0)),
            residual_multiplier=float(
                getattr(config, "scale_depth", 1.0)) / math.sqrt(L),
            logits_divide=H / dmb,
        )


@register_family("orion")
class OrionFamily(StableLmFamily):
    """Orion-14B (reference: contrib/models/orion-14b-chat/src/
    modeling_orion.py): llama shape with biased LayerNorm everywhere —
    structurally stablelm at full rotary without qkv biases, so the
    LayerNorm-bias conversion is inherited."""

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            rms_eps=float(getattr(config, "rms_norm_eps", 1e-5)),
            norm_type="layernorm", norm_bias=True,
        )


@register_family("internlm3")
class InternLM3Family(DecoderFamily):
    """InternLM3 (reference: contrib/models/internlm3-8b-instruct/src/
    modeling_internlm3.py): llama shape with independent qkv_bias /
    (o+mlp) bias knobs."""

    config_cls = _SimpleConfig

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        return spec_from_config(
            config, tp_degree,
            qkv_bias=bool(getattr(config, "qkv_bias", False)),
            o_bias=bool(getattr(config, "bias", False)),
            mlp_bias=bool(getattr(config, "bias", False)),
        )


@register_family("olmo3")
class Olmo3Family(Olmo2Family):
    """OLMo-3 (reference: contrib/models/OLMo-3-7B-Think/src/
    modeling_olmo3.py): olmo2's post-norm blocks + full-width q/k RMSNorm,
    plus an alternating sliding/full layer pattern."""

    @classmethod
    def build_spec(cls, config, tp_degree=None):
        lt = list(getattr(config, "layer_types", []) or [])
        pattern = (tuple(t == "sliding_attention" for t in lt)
                   if lt and not all(t == lt[0] for t in lt) else None)
        all_sliding = bool(lt) and all(t == "sliding_attention" for t in lt)
        window = int(getattr(config, "sliding_window", 0) or 0)
        # HF olmo3 rotates sliding layers with PLAIN rope regardless of the
        # config's rope_scaling (two rotary embeddings, rope_type="default"
        # for sliding_attention)
        local = None
        if pattern is not None and getattr(config, "rope_scaling", None):
            H = config.hidden_size
            hd = (getattr(config, "head_dim", None)
                  or H // config.num_attention_heads)
            local = RopeConfig(head_dim=hd, rope_theta=float(
                getattr(config, "rope_theta", 500000.0)))
        return spec_from_config(
            config, tp_degree,
            norm_position="post",
            sandwich_norm=True,
            qk_norm_full=True,
            sliding_window=window if (pattern is not None
                                      or all_sliding) else 0,
            layer_pattern=pattern,
            local_rope=local,
        )
