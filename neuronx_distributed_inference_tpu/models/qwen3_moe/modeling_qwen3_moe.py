"""Qwen3-MoE family (reference: models/qwen3_moe/modeling_qwen3_moe.py
``NeuronQwen3MoeForCausalLM`` — MoE + EP flagship of the reference hub).

Qwen3 attention (per-head q/k RMSNorm, decoupled head_dim) + Mixtral-style
routing (softmax, top-k, optional renormalization via ``norm_topk_prob``).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class Qwen3MoeInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "head_dim",
                "num_experts", "num_experts_per_tok", "moe_intermediate_size"]


@register_family("qwen3_moe")
class Qwen3MoeFamily(DecoderFamily):
    config_cls = Qwen3MoeInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig,
                   tp_degree: Optional[int] = None) -> DecoderSpec:
        if getattr(config, "mlp_only_layers", None):
            raise NotImplementedError(
                "qwen3_moe mlp_only_layers (mixed dense/MoE stacks) not "
                "supported yet")
        if getattr(config, "decoder_sparse_step", 1) != 1:
            raise NotImplementedError("decoder_sparse_step != 1 not supported")
        moe = MoESpec(
            num_experts=config.num_experts,
            top_k=config.num_experts_per_tok,
            intermediate_size=config.moe_intermediate_size,
            normalize_topk=bool(getattr(config, "norm_topk_prob", True)),
            act=getattr(config, "hidden_act", "silu"),
        )
        return spec_from_config(config, tp_degree, moe=moe, qk_norm=True,
                                intermediate_size=config.moe_intermediate_size)

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec: DecoderSpec
                            ) -> Dict[str, np.ndarray]:
        """HF names: mlp.gate.weight (E,H) router;
        mlp.experts.{e}.gate_proj/up_proj/down_proj."""
        p = cls.hf_prefix
        return cls.convert_moe_weights(
            get, spec,
            router_name=p + ".layers.{i}.mlp.gate.weight",
            expert_fmt=p + ".layers.{i}.mlp.experts.{e}.{name}.weight",
            gate="gate_proj", up="up_proj", down="down_proj")


def TpuQwen3MoeForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, Qwen3MoeFamily)
