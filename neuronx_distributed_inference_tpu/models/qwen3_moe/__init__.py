from .modeling_qwen3_moe import (Qwen3MoeFamily, Qwen3MoeInferenceConfig,
                                 TpuQwen3MoeForCausalLM)

__all__ = ["Qwen3MoeFamily", "Qwen3MoeInferenceConfig",
           "TpuQwen3MoeForCausalLM"]
