"""Generic ViT vision encoder (reference: the vision towers of
models/mllama/, models/llama4/, models/pixtral/, models/qwen2_vl/ and the
encoder side of models/image_to_text_model_base.py — SURVEY §2.7).

CLIP-style: patch conv + optional CLS token + learned positions + pre-LN
transformer stack. ``feature_layer`` selects which hidden state feeds the
multimodal projector (llava uses -2, the penultimate layer, PRE final
layernorm — HF hidden_states semantics)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.normalization import layer_norm

VIT_ACTS = {
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "silu": jax.nn.silu,
}


@dataclass(frozen=True)
class VitSpec:
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    patch_size: int
    image_size: int
    num_channels: int = 3
    use_cls_token: bool = True
    pre_layernorm: bool = True
    # SigLIP (gemma3 tower): biased patch conv + final post_layernorm
    patch_bias: bool = False
    post_layernorm: bool = False
    act: str = "quick_gelu"
    eps: float = 1e-5
    # which hidden state feeds downstream (HF hidden_states indexing:
    # 0 = embeddings, i = after layer i; negatives from the end)
    feature_layer: int = -1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_tokens(self) -> int:
        return self.num_patches + (1 if self.use_cls_token else 0)


def vit_spec_from_hf(cfg, feature_layer: int = -1) -> VitSpec:
    return VitSpec(
        hidden_size=cfg["hidden_size"],
        num_layers=cfg["num_hidden_layers"],
        num_heads=cfg["num_attention_heads"],
        intermediate_size=cfg["intermediate_size"],
        patch_size=cfg["patch_size"],
        image_size=cfg["image_size"],
        num_channels=cfg.get("num_channels", 3),
        act=cfg.get("hidden_act", "quick_gelu"),
        eps=cfg.get("layer_norm_eps", 1e-5),
        feature_layer=feature_layer,
    )


def vit_forward(spec: VitSpec, params, pixel_values) -> jnp.ndarray:
    """pixel_values (B, C, H, W) -> features (B, tokens, hidden) at
    ``feature_layer`` (pre final-LN, matching HF hidden_states)."""
    dn = ("NCHW", "OIHW", "NCHW")
    p = spec.patch_size
    x = jax.lax.conv_general_dilated(
        pixel_values, params["patch_embed"], (p, p), "VALID",
        dimension_numbers=dn)                       # (B, H, gh, gw)
    if spec.patch_bias:
        x = x + params["patch_embed_b"][None, :, None, None]
    b, h, gh, gw = x.shape
    x = x.reshape(b, h, gh * gw).transpose(0, 2, 1)  # (B, T, H)
    if spec.use_cls_token:
        cls = jnp.broadcast_to(params["cls"], (b, 1, h))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos"][: x.shape[1]]
    if spec.pre_layernorm:
        x = layer_norm(x, params["ln_pre_w"], params["ln_pre_b"], spec.eps)

    act = VIT_ACTS[spec.act]
    scale = spec.head_dim ** -0.5
    nh = spec.num_heads

    def body(hh, lw):
        r = layer_norm(hh, lw["ln1_w"], lw["ln1_b"], spec.eps)
        q = (r @ lw["q_w"] + lw["q_b"]) * scale
        k = r @ lw["k_w"] + lw["k_b"]
        v = r @ lw["v_w"] + lw["v_b"]
        t = r.shape[1]
        qf = q.reshape(b, t, nh, -1).astype(jnp.float32)
        kf = k.reshape(b, t, nh, -1).astype(jnp.float32)
        vf = v.reshape(b, t, nh, -1).astype(jnp.float32)
        s = jnp.einsum("bthd,bshd->bhts", qf, kf)
        pr = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhts,bshd->bthd", pr, vf).reshape(b, t, -1)
        hh = hh + (a.astype(hh.dtype) @ lw["o_w"] + lw["o_b"])
        r = layer_norm(hh, lw["ln2_w"], lw["ln2_b"], spec.eps)
        m = act(r @ lw["fc1_w"] + lw["fc1_b"])
        hh = hh + (m @ lw["fc2_w"] + lw["fc2_b"])
        return hh, hh

    x, states = jax.lax.scan(body, x, params["layers"])
    # hidden_states list = [embeddings] + per-layer outputs
    fl = spec.feature_layer % (spec.num_layers + 1)
    feats = states[fl - 1] if fl else x * 0 + x
    if spec.post_layernorm and fl == spec.num_layers:
        # SigLIP last_hidden_state semantics: final LN applied
        feats = layer_norm(feats, params["ln_post_w"], params["ln_post_b"],
                           spec.eps)
    return feats


def convert_clip_vision_tower(sd: Dict[str, np.ndarray], spec: VitSpec,
                              prefix: str, o_proj_name: str = "out_proj",
                              bare_prefix: bool = False) -> Dict[str, Any]:
    """HF CLIPVisionModel names (``<prefix>.vision_model...``) -> param tree.
    Sub-models with no CLS / no pre-LN skip those keys. ``o_proj_name``:
    the attention output projection module name (janus uses
    "projection_layer"); ``bare_prefix``: the prefix already IS the vision
    model root (no ".vision_model" segment)."""

    def get(n):
        if n in sd:
            return np.asarray(sd[n], np.float32)
        raise KeyError(f"missing checkpoint tensor {n}")

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    vm = prefix if bare_prefix else prefix + ".vision_model"

    def lw(i):
        b = f"{vm}.encoder.layers.{i}"
        return {
            "ln1_w": get(f"{b}.layer_norm1.weight"),
            "ln1_b": get(f"{b}.layer_norm1.bias"),
            "q_w": t(get(f"{b}.self_attn.q_proj.weight")),
            "q_b": get(f"{b}.self_attn.q_proj.bias"),
            "k_w": t(get(f"{b}.self_attn.k_proj.weight")),
            "k_b": get(f"{b}.self_attn.k_proj.bias"),
            "v_w": t(get(f"{b}.self_attn.v_proj.weight")),
            "v_b": get(f"{b}.self_attn.v_proj.bias"),
            "o_w": t(get(f"{b}.self_attn.{o_proj_name}.weight")),
            "o_b": get(f"{b}.self_attn.{o_proj_name}.bias"),
            "ln2_w": get(f"{b}.layer_norm2.weight"),
            "ln2_b": get(f"{b}.layer_norm2.bias"),
            "fc1_w": t(get(f"{b}.mlp.fc1.weight")),
            "fc1_b": get(f"{b}.mlp.fc1.bias"),
            "fc2_w": t(get(f"{b}.mlp.fc2.weight")),
            "fc2_b": get(f"{b}.mlp.fc2.bias"),
        }

    layers = [lw(i) for i in range(spec.num_layers)]
    out: Dict[str, Any] = {
        "patch_embed": get(f"{vm}.embeddings.patch_embedding.weight"),
        "pos": get(f"{vm}.embeddings.position_embedding.weight"),
        "layers": {k: np.stack([d[k] for d in layers]) for k in layers[0]},
    }
    if spec.use_cls_token:
        out["cls"] = get(f"{vm}.embeddings.class_embedding")
    if spec.pre_layernorm:
        # HF CLIP ships this historical typo in the weight name
        out["ln_pre_w"] = get(f"{vm}.pre_layrnorm.weight")
        out["ln_pre_b"] = get(f"{vm}.pre_layrnorm.bias")
    if spec.patch_bias:
        out["patch_embed_b"] = get(f"{vm}.embeddings.patch_embedding.bias")
    if spec.post_layernorm:
        out["ln_post_w"] = get(f"{vm}.post_layernorm.weight")
        out["ln_post_b"] = get(f"{vm}.post_layernorm.bias")
    return out
