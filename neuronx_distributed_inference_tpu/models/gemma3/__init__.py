from .modeling_gemma3 import (Gemma3Family, Gemma3InferenceConfig,
                              TpuGemma3ForCausalLM)
