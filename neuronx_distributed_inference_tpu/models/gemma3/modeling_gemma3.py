"""Gemma3 family (reference: models/gemma3/modeling_gemma3.py
``NeuronGemma3ForCausalLM`` — SURVEY §2.7: sliding-window model).

Gemma3 deltas vs the Llama-shaped base, all expressed as DecoderSpec knobs
(model_base.py) rather than a separate layer implementation:
  * alternating local/global attention — ``layer_pattern`` from HF
    ``layer_types`` (5 sliding : 1 full by default)
  * dual RoPE — global layers use rope_theta (1e6, linear-scaled), local
    layers use ``rope_local_base_freq`` (1e4) via ``local_rope``
  * sandwich norms (post_attn_norm / post_ff_norm) + (1+w) zero-centered
    RMSNorm (``norm_offset=1``)
  * qk-norm over head_dim, query_pre_attn_scalar softmax scale,
    sqrt(hidden) embedding scale, tied embeddings
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config
from ...ops.rope import RopeConfig


class Gemma3InferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "head_dim", "sliding_window"]

    def get_text_config(self) -> "InferenceConfig":
        # multimodal Gemma3 checkpoints nest the text config; text-only ones
        # are flat (reference: models/config.py:946 get_text_config)
        return self


@register_family("gemma3", "gemma3_text")
class Gemma3Family(DecoderFamily):
    config_cls = Gemma3InferenceConfig
    post_norm_src = "pre_feedforward_layernorm"

    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        n_layers = config.num_hidden_layers
        layer_types = getattr(config, "layer_types", None)
        if layer_types is None:
            pattern_n = getattr(config, "sliding_window_pattern", 6)
            layer_types = ["sliding_attention" if (i + 1) % pattern_n else
                           "full_attention" for i in range(n_layers)]
        pattern = tuple(t == "sliding_attention" for t in layer_types)
        local_rope = RopeConfig(
            head_dim=config.head_dim,
            rope_theta=float(getattr(config, "rope_local_base_freq", 10000.0)))
        scalar = float(getattr(config, "query_pre_attn_scalar",
                               config.head_dim))
        return spec_from_config(
            config, tp_degree,
            sliding_window=int(config.sliding_window),
            layer_pattern=pattern,
            local_rope=local_rope,
            sandwich_norm=True,
            norm_offset=1.0,
            qk_norm=True,
            attn_scale=scalar ** -0.5,
            embed_scale=math.sqrt(config.hidden_size),
            logits_soft_cap=getattr(config, "final_logit_softcapping", None),
            attn_soft_cap=getattr(config, "attn_logit_softcapping", None),
            act=getattr(config, "hidden_activation", "gelu_pytorch_tanh"),
            # HF omits default-True values from config.json
            tie_word_embeddings=bool(getattr(config, "tie_word_embeddings",
                                             True)),
        )

    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec: DecoderSpec
                                    ) -> Dict[str, np.ndarray]:
        p = cls.hf_prefix

        def ident(w):
            return np.asarray(w)

        return {
            "post_attn_norm": layer_stack(
                p + ".layers.{i}.post_attention_layernorm.weight", ident),
            "post_ff_norm": layer_stack(
                p + ".layers.{i}.post_feedforward_layernorm.weight", ident),
        }


def TpuGemma3ForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, Gemma3Family)
