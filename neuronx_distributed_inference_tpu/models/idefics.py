"""IDEFICS (HuggingFace's open Flamingo) — CLIP vision tower + Perceiver
resampler + llama decoder with tanh-gated cross-attention every
``cross_layer_interval`` layers (reference: contrib/models/
idefics-9b-instruct).

TPU mapping mirrors the mllama stitching: standard llama segments run
through ``run_layer_slice`` (full KV-cache machinery), the gated cross
blocks sit between segments with their cross K/V precomputed ONCE from the
resampled image latents — decode steps touch only the self-attention
cache. The decoupled additional embeddings (the <image>/<fake_image>
token rows appended at fine-tuning time) are concatenated onto the base
tables at conversion."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig, TpuConfig
from ..modules.kv_cache import KVCacheSpec, cache_len_of, init_cache
from ..ops import attention as attn_ops
from ..ops import sampling as sampling_ops
from ..ops.normalization import layer_norm, rms_norm
from ..utils import checkpoint as ckpt
from ..utils.host_loop import greedy_host_loop
from . import vision
from .family import get_family
from .model_base import (DecoderSpec, _embed, _lm_head, attn_inputs,
                         init_params, param_shardings, run_layer_slice,
                         spec_from_config)


class IdeficsInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "vocab_size", "cross_layer_interval", "vision_config"]

    def get_text_config(self):
        return self


# ---------------------------------------------------------------------------
# Perceiver resampler (reference: HF IdeficsPerceiverResampler — Flamingo
# latents cross-attending the frozen vision sequence)
# ---------------------------------------------------------------------------

def perceiver_forward(params: Dict[str, Any], context: jnp.ndarray,
                      n_heads: int, head_dim: int, eps: float = 1e-5
                      ) -> jnp.ndarray:
    """context (B, S, E) -> latents (B, n_latents, E). Keys/values attend
    over [context ; latents] (Flamingo concat)."""
    b = context.shape[0]
    lat = jnp.broadcast_to(params["latents"],
                           (b,) + params["latents"].shape)
    for blk in params["blocks"]:
        c = layer_norm(context, blk["ctx_ln_w"], blk["ctx_ln_b"], eps)
        q_in = layer_norm(lat, blk["lat_ln_w"], blk["lat_ln_b"], eps)
        kv_in = jnp.concatenate([c, q_in], axis=1)
        q = (q_in @ blk["q_w"]).reshape(b, -1, n_heads, head_dim)
        k = (kv_in @ blk["k_w"]).reshape(b, -1, n_heads, head_dim)
        v = (kv_in @ blk["v_w"]).reshape(b, -1, n_heads, head_dim)
        s = jnp.einsum("bihd,bjhd->bhij", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (head_dim ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhij,bjhd->bihd", p, v.astype(jnp.float32))
        a = a.reshape(b, lat.shape[1], -1).astype(lat.dtype)
        lat = lat + a @ blk["o_w"]
        m = layer_norm(lat, blk["mlp_ln_w"], blk["mlp_ln_b"], eps)
        m = jax.nn.relu(m @ blk["fc_w"]) @ blk["cproj_w"]
        lat = lat + m
    return layer_norm(lat, params["ln_w"], params["ln_b"], eps)


def convert_perceiver(sd, depth: int, prefix="model.perceiver_resampler"):
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    blocks = []
    for i in range(depth):
        a, m = f"blocks.{i}.0", f"blocks.{i}.1"
        blocks.append({
            "ctx_ln_w": get(f"{a}.context_layer_norm.weight"),
            "ctx_ln_b": get(f"{a}.context_layer_norm.bias"),
            "lat_ln_w": get(f"{a}.latents_layer_norm.weight"),
            "lat_ln_b": get(f"{a}.latents_layer_norm.bias"),
            "q_w": t(get(f"{a}.q_proj.weight")),
            "k_w": t(get(f"{a}.k_proj.weight")),
            "v_w": t(get(f"{a}.v_proj.weight")),
            "o_w": t(get(f"{a}.output_proj.weight")),
            "mlp_ln_w": get(f"{m}.ln.weight"),
            "mlp_ln_b": get(f"{m}.ln.bias"),
            "fc_w": t(get(f"{m}.fc.weight")),
            "cproj_w": t(get(f"{m}.c_proj.weight")),
        })
    return {"latents": get("latents"), "blocks": blocks,
            "ln_w": get("layer_norm.weight"), "ln_b": get("layer_norm.bias")}


# ---------------------------------------------------------------------------
# Gated cross-attention block (reference: HF IdeficsGatedCrossAttentionLayer)
# ---------------------------------------------------------------------------

def compute_cross_kv(cross_params, image_states, n_heads: int, head_dim: int):
    """Precompute per-cross-layer K/V from the (static) image latents:
    image_states (B, S_img, E_vis) -> k/v (Lc, B, S_img, H, D)."""
    b, s, _ = image_states.shape

    def one(lw):
        k = (image_states @ lw["k_proj"]).reshape(b, s, n_heads, head_dim)
        v = (image_states @ lw["v_proj"]).reshape(b, s, n_heads, head_dim)
        return k, v

    ks, vs = jax.lax.map(one, cross_params)
    return {"k": ks, "v": vs}


def _cross_block(spec: DecoderSpec, hidden, lw, ck, cv, img_mask):
    """x += tanh(alpha_ca) * cross_attn(ln(x), img) [zeroed for rows
    attending NO image latent — HF's cross_attention_gate is computed on
    the additive mask: any 0.0 entry = attends at least one latent];
    partial masks apply to the scores; x += tanh(alpha_d) * mlp(ln2(x))."""
    b, t, _ = hidden.shape
    nh, hd = spec.gqa.num_q_heads, spec.head_dim
    gate = img_mask.any(axis=-1, keepdims=True)             # (B, T, 1)
    eff_mask = jnp.where(gate, img_mask, True)              # avoid all -inf
    r = rms_norm(hidden, lw["input_norm"], spec.rms_eps)
    q = (r @ lw["q_proj"]).reshape(b, t, nh, hd)
    a = attn_ops.mha(q, ck, cv, eff_mask, spec.scale)
    a = a.reshape(b, t, -1) @ lw["o_proj"]
    a = a * gate.astype(a.dtype)
    hidden = hidden + jnp.tanh(lw["alpha_ca"]) * a
    r = rms_norm(hidden, lw["post_norm"], spec.rms_eps)
    m = (jax.nn.silu(r @ lw["gate_proj"]) * (r @ lw["up_proj"])) \
        @ lw["down_proj"]
    return hidden + jnp.tanh(lw["alpha_d"]) * m


def convert_cross_layers(sd, n_cross: int):
    def get(n):
        return np.asarray(sd[n], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        p = f"model.gated_cross_attn_layers.{i}."
        return {
            "input_norm": get(p + "input_layernorm.weight"),
            "q_proj": t(get(p + "cross_attn.q_proj.weight")),
            "k_proj": t(get(p + "cross_attn.k_proj.weight")),
            "v_proj": t(get(p + "cross_attn.v_proj.weight")),
            "o_proj": t(get(p + "cross_attn.o_proj.weight")),
            "alpha_ca": get(p + "alpha_cross_attn"),
            "alpha_d": get(p + "alpha_dense"),
            "post_norm": get(p + "post_attention_layernorm.weight"),
            "gate_proj": t(get(p + "mlp.gate_proj.weight")),
            "up_proj": t(get(p + "mlp.up_proj.weight")),
            "down_proj": t(get(p + "mlp.down_proj.weight")),
        }

    layers = [lw(i) for i in range(n_cross)]
    return {k: np.stack([d[k] for d in layers]) for k in layers[0]}


# ---------------------------------------------------------------------------
# Interleaved forward
# ---------------------------------------------------------------------------

def idefics_forward(spec: DecoderSpec, interval: int, tcfg: TpuConfig,
                    params, cache, cross_kv, input_ids, position_ids,
                    seq_ids, seq_lens, img_mask, sampling_params, rng,
                    phase: str):
    if phase == "prefill":
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.prefill_causal_mask(
                             input_ids.shape[1], position_ids, window=w,
                             chunk=c))
    else:
        ai = attn_inputs(spec, position_ids,
                         lambda w, c=0: attn_ops.decode_mask(
                             position_ids, cache_len_of(cache), window=w,
                             chunk=c))
    hidden = _embed(spec, params, input_ids)
    kf, vf = cache["k"], cache["v"]
    L = spec.num_layers
    si = 0
    for start in range(0, L, interval):
        ci = start // interval
        lw = jax.tree.map(lambda a: a[ci], params["cross_layers"])
        hidden = _cross_block(spec, hidden, lw, cross_kv["k"][ci],
                              cross_kv["v"][ci], img_mask)
        n_self = min(interval, L - start)
        seg = jax.tree.map(lambda a: a[si:si + n_self], params["layers"])
        hidden, kf, vf, _ = run_layer_slice(
            spec, seg, kf, vf, hidden, ai, cache_offset=si,
            is_local=jnp.zeros((n_self,), bool), rep={}, mlp_kind=None,
            seq_ids=seq_ids, positions=position_ids, phase=phase,
            identity_seq_ids=True, arange_positions=(phase == "prefill"))
        si += n_self
    out: Dict[str, Any] = {"cache": {"k": kf, "v": vf}}
    if phase == "prefill":
        idx = jnp.maximum(seq_lens - 1, 0)
        last_h = jnp.take_along_axis(
            hidden, idx[:, None, None].astype(jnp.int32), axis=1)
        logits = _lm_head(spec, params, last_h)[:, 0, :]
    else:
        full = _lm_head(spec, params, hidden)
        logits = full[:, -1, :]
    if tcfg.output_logits:
        out["logits"] = _lm_head(spec, params, hidden)[..., :spec.vocab_size]
    out["tokens"] = sampling_ops.sample(
        logits, tcfg.on_device_sampling_config, sampling_params, rng)
    return out


# ---------------------------------------------------------------------------
# Application
# ---------------------------------------------------------------------------

class IdeficsApplication:
    """Vision tower + perceiver + gated-cross-attention llama LM."""

    def __init__(self, model_path: Optional[str],
                 config: IdeficsInferenceConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.mesh = mesh
        extra = int(getattr(config, "additional_vocab_size", 0) or 0)
        # decoupled additional embeddings extend the vocab; padded_vocab
        # must cover the concatenated table
        from .model_base import pad_vocab
        v_total = int(config.vocab_size) + extra
        self.spec = spec_from_config(
            config, None,
            vocab_size=v_total,
            padded_vocab=pad_vocab(v_total, config.tpu_config.tp_degree),
            rms_eps=float(getattr(config, "rms_norm_eps", 1e-6)))
        vc = dict(config.vision_config)
        self.vit_spec = vision.VitSpec(
            hidden_size=int(vc.get("embed_dim", vc.get("hidden_size"))),
            num_layers=int(vc["num_hidden_layers"]),
            num_heads=int(vc["num_attention_heads"]),
            intermediate_size=int(vc["intermediate_size"]),
            patch_size=int(vc["patch_size"]),
            image_size=int(vc["image_size"]),
            use_cls_token=True, pre_layernorm=True, post_layernorm=True,
            act=vc.get("hidden_act", "gelu"),
            eps=float(vc.get("layer_norm_eps", 1e-5)),
            feature_layer=-1)
        pc = dict(getattr(config, "perceiver_config", {}) or {})
        self.use_resampler = bool(getattr(config, "use_resampler", False)
                                  or pc.get("use_resampler", False))
        self.perceiver_cfg = pc
        if pc.get("qk_layer_norms_perceiver") or getattr(
                config, "qk_layer_norms", False):
            raise NotImplementedError(
                "idefics qk_layer_norms variants are not supported")
        self.interval = int(config.cross_layer_interval)
        self.params = None
        self.cache = None
        self.vision_params = None
        self.perceiver_params = None
        self._steps: Dict[str, Any] = {}
        self._rng = jax.random.PRNGKey(0)
        self._vit = jax.jit(partial(vision.vit_forward, self.vit_spec))
        self._cross_fn = jax.jit(partial(
            compute_cross_kv, n_heads=self.spec.gqa.num_q_heads,
            head_dim=self.spec.head_dim))

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        fam = get_family("llama")
        text_sd = {k: v for k, v in sd.items()
                   if k.startswith("model.layers.")
                   or k in ("model.norm.weight",)}
        embed = np.asarray(sd["model.embed_tokens.weight"], np.float32)
        head = np.asarray(sd["lm_head.weight"], np.float32)
        if "model.embed_tokens.additional_embedding.weight" in sd:
            embed = np.concatenate([embed, np.asarray(
                sd["model.embed_tokens.additional_embedding.weight"],
                np.float32)])
        if "lm_head.additional_fc.weight" in sd:
            head = np.concatenate([head, np.asarray(
                sd["lm_head.additional_fc.weight"], np.float32)])
        text_sd["model.embed_tokens.weight"] = embed
        text_sd["lm_head.weight"] = head
        host = fam.convert_hf_state_dict(text_sd, self.spec)
        host["cross_layers"] = convert_cross_layers(
            sd, (self.spec.num_layers + self.interval - 1) // self.interval)
        from .model_base import fuse_qkv_host
        host = fuse_qkv_host(host)
        self.params = jax.tree.map(jnp.asarray, host)
        self.vision_params = jax.tree.map(
            jnp.asarray, vision.convert_clip_vision_tower(
                sd, self.vit_spec, "model.vision_model", bare_prefix=True))
        if self.use_resampler:
            self.perceiver_params = jax.tree.map(
                jnp.asarray,
                convert_perceiver(sd, int(self.perceiver_cfg.get(
                    "resampler_depth", 6))))
        return self

    def init_cache(self):
        cfg = self.tpu_config
        self.cache = init_cache(KVCacheSpec(
            num_layers=self.spec.num_layers, batch_size=cfg.batch_size,
            max_seq_len=cfg.seq_len,
            num_kv_heads=self.spec.gqa.num_kv_heads,
            head_dim=self.spec.head_dim, dtype=self.spec.kv_dtype),
            self.mesh)
        return self

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        """(B, N_img, C, H, W) -> image latents (B, N_img * S_img, E_vis)."""
        b, n = pixel_values.shape[:2]
        feats = self._vit(self.vision_params,
                          jnp.asarray(pixel_values).reshape(
                              (b * n,) + pixel_values.shape[2:]))
        if self.use_resampler:
            pc = self.perceiver_cfg
            feats = perceiver_forward(
                self.perceiver_params, feats,
                int(pc.get("resampler_n_heads", 16)),
                int(pc.get("resampler_head_dim", 96)))
        s_img = feats.shape[1]
        return feats.reshape(b, n * s_img, feats.shape[-1]), s_img

    def _step(self, phase):
        if phase not in self._steps:
            self._steps[phase] = jax.jit(
                partial(idefics_forward, self.spec, self.interval,
                        self.tpu_config, phase=phase), donate_argnums=(1,))
        return self._steps[phase]

    def generate(self, input_ids: np.ndarray, pixel_values: np.ndarray,
                 image_attention_mask: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 16,
                 eos_token_id: Optional[int] = None) -> Dict[str, Any]:
        """pixel_values (B, N_img, C, H, W); image_attention_mask
        (B, S_text, N_img) bool/int (True = that token attends that image)
        — defaults to all-on."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = np.ones_like(input_ids)
        seq_lens = attention_mask.astype(np.int32).sum(axis=1)
        if self.cache is None:
            self.init_cache()
        latents, s_img = self.encode_images(pixel_values)
        n_img = pixel_values.shape[1]
        if image_attention_mask is None:
            image_attention_mask = np.ones((b, s, n_img), bool)
        # expand per-image mask over that image's latent slots
        img_mask = np.repeat(image_attention_mask.astype(bool), s_img,
                             axis=2)
        cross_kv = self._cross_fn(self.params["cross_layers"],
                                  latents.astype(self.spec.dtype))

        self._rng, k1 = jax.random.split(self._rng)
        pos = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s))
        out = self._step("prefill")(
            self.params, self.cache, cross_kv, jnp.asarray(input_ids),
            jnp.asarray(pos), jnp.arange(b, dtype=jnp.int32),
            jnp.asarray(seq_lens), jnp.asarray(img_mask), None, k1)
        self.cache = out["cache"]
        logits = [np.asarray(out["logits"])] if "logits" in out else []

        dec_mask = jnp.asarray(img_mask[:, -1:, :])
        eos_ids = (None if eos_token_id is None
                   else np.atleast_1d(np.asarray(eos_token_id)))
        state = {"pos": seq_lens.astype(np.int32)}
        rows = jnp.arange(b, dtype=jnp.int32)

        def step(last):
            self._rng, k1 = jax.random.split(self._rng)
            o = self._step("decode")(
                self.params, self.cache, cross_kv, last[:, None],
                jnp.asarray(state["pos"][:, None]), rows, None, dec_mask,
                None, k1)
            self.cache = o["cache"]
            state["pos"] = state["pos"] + 1
            if "logits" in o:
                logits.append(o["logits"])
            return o["tokens"].reshape(b).astype(jnp.int32)

        first = jnp.asarray(np.asarray(out["tokens"]).reshape(b)
                            .astype(np.int32))
        gen = greedy_host_loop(step, first, max_new_tokens, eos_ids=eos_ids)
        res = {"sequences": np.concatenate([input_ids, gen], axis=1),
               "generated": gen}
        if logits:
            res["logits"] = [np.asarray(lg) for lg in logits]
        return res

    def reset(self):
        self.init_cache()
        return self
