"""Qwen2.5-Omni THINKER — audio + text understanding (reference:
contrib/models/Qwen2.5-Omni-7B, which validated the text backbone only;
this implementation also ships the audio tower with an HF golden,
exceeding the reference's verified surface).

Audio tower (HF Qwen2_5OmniAudioEncoder): mel features are cut into
``n_window*2``-frame chunks, each chunk runs the two-conv gelu stem with
sinusoidal positions restarting per chunk, attention is BIDIRECTIONAL
within a chunk only (here: a chunk-id equality mask over the flattened
token sequence — the mask-based form of HF's cu_seqlens blocks), then
each audio's tokens are avg-pooled 2x, layer-normed and projected to the
text width. The thinker text stack is qwen2 + M-RoPE; audio-only prompts
use plain sequential positions (HF get_rope_index else-branch), so the
features merge through the generic image_embeds/image_mask path of the
text application. Video understanding is not implemented (raises).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..ops.normalization import layer_norm
from ..utils import checkpoint as ckpt
from .application import CausalLMApplication
from .family import register_family
from .qwen2.modeling_qwen2 import Qwen2Family
from .whisper.modeling_whisper import sinusoidal_positions


class OmniThinkerInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "audio_config", "audio_token_id"]

    def get_text_config(self):
        tc = dict(self.text_config)
        return OmniThinkerTextFamily.config_cls(self.tpu_config, **tc)


@register_family("qwen2_5_omni_text", "qwen2_5_omni_thinker_text")
class OmniThinkerTextFamily(Qwen2Family):
    """Thinker text decoder = qwen2 + mrope sections via rope_scaling."""


def audio_encoder_forward(params: Dict[str, Any], chunks: jnp.ndarray,
                          frame_valid: jnp.ndarray, chunk_valid: jnp.ndarray,
                          n_heads: int, eps: float = 1e-5) -> jnp.ndarray:
    """chunks (N_chunks, mel, W2) right-padded mel chunks; frame_valid
    (N_chunks, W2) bool marks live MEL frames (HF zeroes padded frames
    between the convs); chunk_valid (N_chunks, W2//2) bool marks live
    post-conv tokens. Returns per-token states (N_chunks, W2//2, D) BEFORE
    the per-audio pool/proj tail — the host gathers valid tokens and
    applies the tail per audio."""
    w = params["conv1_w"]            # (D, mel, 3)
    x = jax.lax.conv_general_dilated(
        chunks, w, (1,), [(1, 1)],
        dimension_numbers=("NCH", "OIH", "NCH")) + params["conv1_b"][:, None]
    x = jax.nn.gelu(x, approximate=False)
    x = x * frame_valid[:, None, :].astype(x.dtype)
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"], (2,), [(1, 1)],
        dimension_numbers=("NCH", "OIH", "NCH")) + params["conv2_b"][:, None]
    x = jax.nn.gelu(x, approximate=False)
    x = x.transpose(0, 2, 1)                        # (N, T, D)
    x = x + params["pos"][: x.shape[1]][None]
    n, t, d = x.shape
    hd = d // n_heads

    # attention is block-diagonal by construction (cu_seqlens chunks), so
    # keep the (n, t) chunk-batch layout — per-chunk attention does n x
    # fewer score FLOPs than flattening to one (n*t)^2 problem
    mask = chunk_valid[:, None, :] & chunk_valid[:, :, None]   # (N, T, T)
    seq = x
    for lw in params["layers"]:
        r = layer_norm(seq, lw["ln1_w"], lw["ln1_b"], eps)
        q = (r @ lw["q_w"] + lw["q_b"]).reshape(n, t, n_heads, hd)
        k = (r @ lw["k_w"]).reshape(n, t, n_heads, hd)
        v = (r @ lw["v_w"] + lw["v_b"]).reshape(n, t, n_heads, hd)
        s = jnp.einsum("nqhd,nkhd->nhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * (hd ** -0.5)
        s = jnp.where(mask[:, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("nhqk,nkhd->nqhd", p, v.astype(jnp.float32))
        seq = seq + (a.reshape(n, t, d).astype(seq.dtype) @ lw["o_w"]
                     + lw["o_b"])
        r = layer_norm(seq, lw["ln2_w"], lw["ln2_b"], eps)
        m = jax.nn.gelu(r @ lw["fc1_w"] + lw["fc1_b"], approximate=False)
        seq = seq + m @ lw["fc2_w"] + lw["fc2_b"]
    return seq


def convert_audio_encoder(sd, n_layers: int, max_pos: int, d_model: int,
                          prefix="thinker.audio_tower"):
    def get(n):
        return np.asarray(sd[f"{prefix}.{n}"], np.float32)

    def t(w):
        return np.ascontiguousarray(np.asarray(w, np.float32).T)

    def lw(i):
        b = f"layers.{i}"
        return {
            "ln1_w": get(f"{b}.self_attn_layer_norm.weight"),
            "ln1_b": get(f"{b}.self_attn_layer_norm.bias"),
            "q_w": t(get(f"{b}.self_attn.q_proj.weight")),
            "q_b": get(f"{b}.self_attn.q_proj.bias"),
            "k_w": t(get(f"{b}.self_attn.k_proj.weight")),
            "v_w": t(get(f"{b}.self_attn.v_proj.weight")),
            "v_b": get(f"{b}.self_attn.v_proj.bias"),
            "o_w": t(get(f"{b}.self_attn.out_proj.weight")),
            "o_b": get(f"{b}.self_attn.out_proj.bias"),
            "ln2_w": get(f"{b}.final_layer_norm.weight"),
            "ln2_b": get(f"{b}.final_layer_norm.bias"),
            "fc1_w": t(get(f"{b}.fc1.weight")),
            "fc1_b": get(f"{b}.fc1.bias"),
            "fc2_w": t(get(f"{b}.fc2.weight")),
            "fc2_b": get(f"{b}.fc2.bias"),
        }

    return {
        "conv1_w": get("conv1.weight"), "conv1_b": get("conv1.bias"),
        "conv2_w": get("conv2.weight"), "conv2_b": get("conv2.bias"),
        "pos": sinusoidal_positions(max_pos, d_model),
        "layers": [lw(i) for i in range(n_layers)],
        "ln_post_w": get("ln_post.weight"), "ln_post_b": get("ln_post.bias"),
        "proj_w": t(get("proj.weight")), "proj_b": get("proj.bias"),
    }


class OmniThinkerApplication:
    """Audio tower + qwen2/M-RoPE text LM (video raises)."""

    def __init__(self, model_path: Optional[str],
                 config: OmniThinkerInferenceConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        OmniThinkerTextFamily, mesh=mesh)
        ac = dict(config.audio_config)
        self.d_model = int(ac["d_model"])
        self.n_heads = int(ac["encoder_attention_heads"])
        self.n_layers = int(ac["encoder_layers"])
        self.n_window = int(ac.get("n_window", 100))
        self.max_pos = int(ac.get("max_source_positions", 1500))
        self.audio_token_id = int(config.audio_token_id)
        self.audio_params = None
        self._enc = jax.jit(partial(audio_encoder_forward,
                                    n_heads=self.n_heads))


    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            for pre, new in (("thinker.model.", "model."),
                             ("thinker.lm_head.", "lm_head."),
                             ("model.language_model.", "model."),
                             ("model.audio_tower.", "thinker.audio_tower.")):
                if k.startswith(pre):
                    text_sd[new + k[len(pre):]] = v
                    break
            else:
                text_sd[k] = v
        host = self.text.family.convert_hf_state_dict(text_sd,
                                                      self.text.spec)
        self.text._put_params(host)
        prefix = ("thinker.audio_tower" if any(
            k.startswith("thinker.audio_tower.") for k in text_sd)
            else "audio_tower")
        src = text_sd if prefix.startswith("thinker") else sd
        ap = convert_audio_encoder(src, self.n_layers, self.max_pos,
                                   self.d_model, prefix=prefix)
        self.audio_params = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, ap)
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def encode_audio(self, input_features: np.ndarray,
                     feature_lens: np.ndarray) -> List[np.ndarray]:
        """input_features (N_audio, mel, T_max) mel spectrograms;
        feature_lens (N_audio,) true mel lengths. Returns one
        (n_tokens_i, H_text) array per audio (n_tokens = after-conv
        length // 2, HF avg-pool tail)."""
        w2 = self.n_window * 2
        chunks, fvalids, valids, owner = [], [], [], []
        for a in range(input_features.shape[0]):
            L = int(feature_lens[a])
            n_chunks = -(-L // w2)
            for c in range(n_chunks):
                lo = c * w2
                n_frames = min(w2, L - lo)
                seg = input_features[a, :, lo:lo + n_frames]
                pad = w2 - seg.shape[1]
                if pad:
                    seg = np.pad(seg, ((0, 0), (0, pad)))
                chunks.append(seg)
                fvalids.append(np.arange(w2) < n_frames)
                valids.append(np.arange(w2 // 2) < -(-n_frames // 2))
                owner.append(a)
        chunks = np.stack(chunks).astype(np.float32)
        fvalids = np.stack(fvalids)
        valids = np.stack(valids)
        states = np.asarray(self._enc(self.audio_params,
                                      jnp.asarray(chunks),
                                      jnp.asarray(fvalids),
                                      jnp.asarray(valids)))
        ap = self.audio_params
        outs = []
        owner = np.asarray(owner)
        for a in range(input_features.shape[0]):
            toks = np.concatenate(
                [states[i][valids[i]] for i in np.nonzero(owner == a)[0]])
            n2 = toks.shape[0] // 2
            pooled = toks[: n2 * 2].reshape(n2, 2, -1).mean(axis=1)
            h = np.asarray(layer_norm(jnp.asarray(pooled),
                                      ap["ln_post_w"], ap["ln_post_b"],
                                      1e-5))
            outs.append(h @ np.asarray(ap["proj_w"])
                        + np.asarray(ap["proj_b"]))
        return outs

    def generate(self, input_ids: np.ndarray,
                 input_features: Optional[np.ndarray] = None,
                 feature_lens: Optional[np.ndarray] = None,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32, **kw) -> Dict[str, Any]:
        """input_ids contain ``audio_token_id`` placeholders (one per
        post-pool audio token); input_features (N_audio, mel, T) with one
        audio per batch row (multi-audio rows: flatten upstream)."""
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        audio_embeds = audio_mask = None
        if input_features is not None:
            feats = self.encode_audio(np.asarray(input_features),
                                      np.asarray(feature_lens))
            audio_mask = input_ids == self.audio_token_id
            per_row = audio_mask.sum(axis=1)
            if not (per_row == per_row[0]).all():
                raise ValueError("rows must hold equal audio-token counts")
            if len(feats) != b:
                raise ValueError(
                    f"{len(feats)} audios for {b} prompt rows (one audio "
                    "per row; flatten multi-audio rows upstream)")
            stacked = np.stack(feats)
            if stacked.shape[1] != per_row[0]:
                raise ValueError(
                    f"prompt holds {per_row[0]} audio tokens per row but "
                    f"the encoder emitted {stacked.shape[1]}")
            audio_embeds = stacked
        if self.text.cache is None:
            self.text.init_cache()
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens,
            image_embeds=audio_embeds, image_mask=audio_mask, **kw)

    def reset(self):
        self.text.reset()
        return self
