from .modeling_mixtral import (MixtralFamily, MixtralInferenceConfig,
                               TpuMixtralForCausalLM)

__all__ = ["MixtralFamily", "MixtralInferenceConfig", "TpuMixtralForCausalLM"]
