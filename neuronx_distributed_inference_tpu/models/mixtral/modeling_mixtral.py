"""Mixtral family — MoE decoder
(reference: models/mixtral/modeling_mixtral.py ``NeuronMixtralForCausalLM``).

Routing semantics match HF Mixtral: softmax over all experts, top-k, then
renormalize the selected affinities (reference MoE knobs:
models/config.py:798-846 ``MoENeuronConfig`` with
normalize_top_k_affinities=True).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...config import InferenceConfig
from ...modules.moe import MoESpec
from ..family import DecoderFamily, register_family
from ..model_base import DecoderSpec, spec_from_config


class MixtralInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["hidden_size", "num_attention_heads", "num_hidden_layers",
                "num_key_value_heads", "vocab_size", "intermediate_size",
                "rms_norm_eps", "num_local_experts", "num_experts_per_tok"]


@register_family("mixtral")
class MixtralFamily(DecoderFamily):
    config_cls = MixtralInferenceConfig

    @classmethod
    def build_spec(cls, config: InferenceConfig,
                   tp_degree: Optional[int] = None) -> DecoderSpec:
        moe = MoESpec(
            num_experts=config.num_local_experts,
            top_k=config.num_experts_per_tok,
            intermediate_size=config.intermediate_size,
            normalize_topk=True,
            act=getattr(config, "hidden_act", "silu"),
        )
        window = getattr(config, "sliding_window", None) or 0
        return spec_from_config(config, tp_degree, moe=moe,
                                sliding_window=int(window))

    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec: DecoderSpec
                            ) -> Dict[str, np.ndarray]:
        """HF names: block_sparse_moe.gate (E,H) router;
        experts.{e}.w1/w3/w2 = gate/up/down (torch (out,in) layout)."""
        p = cls.hf_prefix
        return cls.convert_moe_weights(
            get, spec,
            router_name=p + ".layers.{i}.block_sparse_moe.gate.weight",
            expert_fmt=p + ".layers.{i}.block_sparse_moe.experts.{e}.{name}.weight",
            gate="w1", up="w3", down="w2")


def TpuMixtralForCausalLM(model_path: str, config: InferenceConfig):
    from ..application import CausalLMApplication
    return CausalLMApplication(model_path, config, MixtralFamily)
