// Native host-runtime component: paged-KV block allocator with content-hash
// prefix caching + LRU eviction (native-equiv of the reference's external
// runtime/allocator components, SURVEY §2.10; mirrors the semantics of
// modules/block_kv_cache.py BlockAllocator exactly — the Python unit tests
// assert identical block-id sequences).
//
// The allocator sits on the per-step host hot path of the paged serving loop
// (begin_sequence/grow/end_sequence per request per token), which is why it
// is native: no Python dict/list overhead, O(1) ops via intrusive free list +
// LRU, 64-bit FNV-1a chained block hashing.
//
// C ABI (ctypes-friendly), no exceptions across the boundary.

#include <cstdint>
#include <cstring>
#include <list>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 1469598103934665603ULL;
constexpr uint64_t kFnvPrime = 1099511628211ULL;

inline uint64_t fnv1a(uint64_t h, const uint8_t* data, size_t n) {
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= kFnvPrime;
  }
  return h;
}

inline uint64_t hash_block(uint64_t parent, const int64_t* tokens, int n) {
  uint64_t h = kFnvOffset;
  h = fnv1a(h, reinterpret_cast<const uint8_t*>(&parent), sizeof(parent));
  h = fnv1a(h, reinterpret_cast<const uint8_t*>(tokens),
            static_cast<size_t>(n) * sizeof(int64_t));
  // avoid the reserved "no hash" sentinel
  return h == 0 ? 1 : h;
}

struct BlockMeta {
  int32_t ref_count = 0;
  uint64_t content_hash = 0;  // 0 = none (mutable / tail block)
};

class Allocator {
 public:
  Allocator(int num_blocks, int block_size, bool prefix_caching)
      : block_size_(block_size),
        prefix_(prefix_caching),
        num_blocks_(num_blocks),
        meta_(num_blocks) {
    free_list_.reserve(num_blocks);
    for (int i = 1; i < num_blocks; ++i) free_list_.push_back(i);
  }

  int num_free() const {
    return static_cast<int>(free_list_.size() + lru_.size());
  }

  // returns number of blocks written to out_blocks, or -1 on OOM/overflow
  int allocate(const int64_t* tokens, int n_tokens, int* out_blocks,
               int max_out, int* out_cached_tokens) {
    int n_blocks = n_tokens <= 0 ? 1 : (n_tokens + block_size_ - 1) / block_size_;
    if (n_blocks > max_out) return -1;
    int cached = 0;
    uint64_t parent = 0;
    bool matching = prefix_;
    for (int bi = 0; bi < n_blocks; ++bi) {
      const int64_t* chunk = tokens + static_cast<int64_t>(bi) * block_size_;
      int chunk_len = n_tokens - bi * block_size_;
      if (chunk_len > block_size_) chunk_len = block_size_;
      bool full = chunk_len == block_size_;
      if (matching && full) {
        uint64_t h = hash_block(parent, chunk, chunk_len);
        auto it = hash_to_block_.find(h);
        if (it != hash_to_block_.end()) {
          int blk = it->second;
          BlockMeta& m = meta_[blk];
          if (m.ref_count == 0) {
            auto li = lru_pos_.find(blk);
            if (li != lru_pos_.end()) {
              lru_.erase(li->second);
              lru_pos_.erase(li);
            }
          }
          m.ref_count += 1;
          out_blocks[bi] = blk;
          cached += block_size_;
          parent = h;
          continue;
        }
      }
      matching = false;
      int blk = pop_block();
      if (blk < 0) {
        // roll back this call's allocations: prefix-HIT blocks keep their
        // (valid, pre-existing) hashes; fresh blocks were hashed before
        // their content was ever written, so their hashes must be purged
        // or later allocations would "hit" garbage KV
        int n_hit = cached / block_size_;
        for (int j = 0; j < bi; ++j) {
          if (j < n_hit) release_one(out_blocks[j]);
          else invalidate_one(out_blocks[j]);
        }
        return -1;
      }
      BlockMeta& m = meta_[blk];
      m.ref_count += 1;
      if (prefix_ && full) {
        uint64_t h = hash_block(parent, chunk, chunk_len);
        m.content_hash = h;
        hash_to_block_[h] = blk;
        parent = h;
      }
      out_blocks[bi] = blk;
    }
    *out_cached_tokens = cached;
    return n_blocks;
  }

  // READ-ONLY prefix-warmth probe: how many leading tokens allocate()
  // would serve from the prefix cache right now, writing the hit block
  // ids to out_blocks. Takes no references, touches no LRU order — a
  // scheduler calls this per queued request to order admissions.
  int probe(const int64_t* tokens, int n_tokens, int* out_blocks,
            int max_out) const {
    if (!prefix_) return 0;
    int cached = 0;
    uint64_t parent = 0;
    int n_full = n_tokens / block_size_;
    for (int bi = 0; bi < n_full && bi < max_out; ++bi) {
      const int64_t* chunk = tokens + static_cast<int64_t>(bi) * block_size_;
      parent = hash_block(parent, chunk, block_size_);
      auto it = hash_to_block_.find(parent);
      if (it == hash_to_block_.end()) break;
      out_blocks[bi] = it->second;
      cached += block_size_;
    }
    return cached;
  }

  // grow blocks to cover new_len tokens; returns new count or -1
  // (rolling back this call's additions on OOM)
  int extend(int* blocks, int n_blocks, int new_len, int max_out) {
    int need = new_len <= 0 ? 1 : (new_len + block_size_ - 1) / block_size_;
    if (need > max_out) return -1;
    int start = n_blocks;
    while (n_blocks < need) {
      int blk = pop_block();
      if (blk < 0) {
        for (int j = start; j < n_blocks; ++j) release_one(blocks[j]);
        return -1;
      }
      meta_[blk].ref_count += 1;
      blocks[n_blocks++] = blk;
    }
    return n_blocks;
  }

  // returns 0 ok, -1 double free
  int free_blocks(const int* blocks, int n) {
    for (int i = 0; i < n; ++i) {
      if (release_one(blocks[i]) < 0) return -1;
    }
    return 0;
  }

  // free blocks whose pending content was never written (aborted
  // admission): once unreferenced they go back to the free list with
  // their hash registration dropped, so the prefix cache can never
  // serve their contents. returns 0 ok, -1 double free
  int invalidate_blocks(const int* blocks, int n) {
    for (int i = 0; i < n; ++i) {
      if (invalidate_one(blocks[i]) < 0) return -1;
    }
    return 0;
  }

 private:
  int release_one(int blk) {
    BlockMeta& m = meta_[blk];
    m.ref_count -= 1;
    if (m.ref_count < 0) return -1;
    if (m.ref_count == 0) {
      if (m.content_hash != 0) {
        lru_.push_back(blk);  // stays resident for prefix reuse
        lru_pos_[blk] = std::prev(lru_.end());
      } else {
        free_list_.push_back(blk);
      }
    }
    return 0;
  }

  int invalidate_one(int blk) {
    BlockMeta& m = meta_[blk];
    m.ref_count -= 1;
    if (m.ref_count < 0) return -1;
    if (m.ref_count == 0) {
      if (m.content_hash != 0) {
        auto it = hash_to_block_.find(m.content_hash);
        if (it != hash_to_block_.end() && it->second == blk)
          hash_to_block_.erase(it);
        m.content_hash = 0;
      }
      free_list_.push_back(blk);
    }
    // ref_count > 0: another sequence still references it, so its
    // content predates the aborted call and stays prefix-servable
    return 0;
  }

  int pop_block() {
    if (!free_list_.empty()) {
      int blk = free_list_.back();
      free_list_.pop_back();
      return blk;
    }
    if (!lru_.empty()) {  // evict oldest unreferenced cached block
      int blk = lru_.front();
      lru_.pop_front();
      lru_pos_.erase(blk);
      uint64_t h = meta_[blk].content_hash;
      if (h != 0) hash_to_block_.erase(h);
      meta_[blk] = BlockMeta{};
      return blk;
    }
    return -1;
  }

  int block_size_;
  bool prefix_;
  int num_blocks_;
  std::vector<BlockMeta> meta_;
  std::vector<int> free_list_;
  std::list<int> lru_;
  std::unordered_map<int, std::list<int>::iterator> lru_pos_;
  std::unordered_map<uint64_t, int> hash_to_block_;
};

}  // namespace

extern "C" {

void* nxdi_alloc_create(int num_blocks, int block_size, int prefix_caching) {
  return new Allocator(num_blocks, block_size, prefix_caching != 0);
}

void nxdi_alloc_destroy(void* a) { delete static_cast<Allocator*>(a); }

int nxdi_alloc_allocate(void* a, const int64_t* tokens, int n_tokens,
                        int* out_blocks, int max_out, int* out_cached) {
  return static_cast<Allocator*>(a)->allocate(tokens, n_tokens, out_blocks,
                                              max_out, out_cached);
}

int nxdi_alloc_extend(void* a, int* blocks, int n_blocks, int new_len,
                      int max_out) {
  return static_cast<Allocator*>(a)->extend(blocks, n_blocks, new_len,
                                            max_out);
}

int nxdi_alloc_free(void* a, const int* blocks, int n) {
  return static_cast<Allocator*>(a)->free_blocks(blocks, n);
}

int nxdi_alloc_invalidate(void* a, const int* blocks, int n) {
  return static_cast<Allocator*>(a)->invalidate_blocks(blocks, n);
}

int nxdi_alloc_num_free(void* a) {
  return static_cast<Allocator*>(a)->num_free();
}

int nxdi_alloc_probe(void* a, const int64_t* tokens, int n_tokens,
                     int* out_blocks, int max_out) {
  return static_cast<Allocator*>(a)->probe(tokens, n_tokens, out_blocks,
                                           max_out);
}

}  // extern "C"
