"""Native (C++) host-runtime components, loaded via ctypes
(native-equiv of the reference's external C++ runtime pieces — SURVEY §2.10;
pybind11 is unavailable in this image, so the C ABI + ctypes is the binding).

The shared library is compiled on first use with the system toolchain and
cached next to the sources; set NXDI_TPU_NATIVE=0 to force the pure-Python
fallbacks."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("nxdi_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "_build", "libnxdi_native.so")
_SOURCES = ["block_allocator.cpp"]
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def native_enabled() -> bool:
    return os.environ.get("NXDI_TPU_NATIVE", "1") not in ("0", "false")


def _compile() -> bool:
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if (os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= newest_src):
        return True
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           *srcs, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        logger.info("native: built %s", _LIB_PATH)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired) as e:
        err = getattr(e, "stderr", b"")
        logger.warning("native build failed (%s); using Python fallbacks: %s",
                       e, err.decode() if isinstance(err, bytes) else err)
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and dlopen the native library; None on failure or
    when disabled — callers fall back to Python implementations."""
    global _lib, _load_failed
    if not native_enabled() or _load_failed:
        return None
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _compile():
            _load_failed = True
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.nxdi_alloc_create.restype = ctypes.c_void_p
        lib.nxdi_alloc_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
        lib.nxdi_alloc_destroy.argtypes = [ctypes.c_void_p]
        lib.nxdi_alloc_allocate.restype = ctypes.c_int
        lib.nxdi_alloc_allocate.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.nxdi_alloc_extend.restype = ctypes.c_int
        lib.nxdi_alloc_extend.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.nxdi_alloc_free.restype = ctypes.c_int
        lib.nxdi_alloc_free.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int),
                                        ctypes.c_int]
        lib.nxdi_alloc_invalidate.restype = ctypes.c_int
        lib.nxdi_alloc_invalidate.argtypes = [ctypes.c_void_p,
                                              ctypes.POINTER(ctypes.c_int),
                                              ctypes.c_int]
        lib.nxdi_alloc_num_free.restype = ctypes.c_int
        lib.nxdi_alloc_num_free.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "nxdi_alloc_probe"):  # absent in pre-probe builds
            lib.nxdi_alloc_probe.restype = ctypes.c_int
            lib.nxdi_alloc_probe.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        _lib = lib
        return _lib
