"""Multi-LoRA serving (reference: modules/lora_serving/ — config 224,
lora_checkpoint 412, lora_layer 358, lora_model 682, lora_module 208 LoC;
SURVEY §2.6).

TPU-native design: instead of swapping module classes (the reference's
``LoraModel.inject_adapter``), every targeted projection carries stacked
adapter weights

    lora_A_<mod>: (L, max_loras, in, r)     lora_B_<mod>: (L, max_loras, r, out)

and the per-request ``adapter_ids`` (B,) gather each row's adapter INSIDE the
graph (reference: LoraWeightManager selecting by adapter_ids). The
``lora_alpha/r`` scale is folded into B at load time. Slot 0 is conventionally
the zero adapter (B=0 → base model behavior).

Dynamic multi-LoRA (reference: models/model_base.py:3349-3356 host-side
adapter swap) = writing a new adapter into a slot of the stacked arrays
between requests (:func:`set_adapter_slot`).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_TARGET_MODULES = ("q_proj", "v_proj")


@dataclass(frozen=True)
class LoraSpec:
    """Static LoRA serving geometry (hashable, closed over by jit)."""

    max_loras: int = 1
    rank: int = 16
    target_modules: Tuple[str, ...] = DEFAULT_TARGET_MODULES

    def targets(self, name: str) -> bool:
        return name in self.target_modules


def lora_spec_from_config(tpu_config) -> Optional["LoraSpec"]:
    lc = getattr(tpu_config, "lora_config", None)
    if lc is None:
        return None
    return LoraSpec(
        max_loras=lc.max_loras,
        rank=lc.max_lora_rank,
        target_modules=tuple(lc.target_modules or DEFAULT_TARGET_MODULES),
    )


def lora_delta(x: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray,
               adapter_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-row adapter delta: x (B,T,in); a (max_loras,in,r);
    b (max_loras,r,out) with scale folded in; adapter_ids (B,)."""
    a_sel = a[adapter_ids].astype(jnp.float32)       # (B,in,r)
    b_sel = b[adapter_ids].astype(jnp.float32)       # (B,r,out)
    d = jnp.einsum("bti,bir->btr", x.astype(jnp.float32), a_sel)
    d = jnp.einsum("btr,bro->bto", d, b_sel)
    return d.astype(x.dtype)


def apply_lora(spec_lora: Optional[LoraSpec], layer_w: Dict[str, Any],
               name: str, x: jnp.ndarray, y: jnp.ndarray,
               adapter_ids) -> jnp.ndarray:
    """y = base(x) plus this module's adapter delta when serving LoRA."""
    if (spec_lora is None or adapter_ids is None
            or not spec_lora.targets(name)):
        return y
    return y + lora_delta(x, layer_w[f"lora_A_{name}"],
                          layer_w[f"lora_B_{name}"], adapter_ids)


# ---------------------------------------------------------------------------
# PEFT checkpoint loading (reference: lora_checkpoint.py)
# ---------------------------------------------------------------------------

def load_peft_adapter(path: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load a PEFT-format adapter dir: adapter_config.json +
    adapter_model.safetensors (or .bin). Returns (state_dict, config)."""
    with open(os.path.join(path, "adapter_config.json")) as f:
        cfg = json.load(f)
    from ..utils.checkpoint import _load_one
    for fname in ("adapter_model.safetensors", "adapter_model.bin"):
        p = os.path.join(path, fname)
        if os.path.exists(p):
            return _load_one(p), cfg
    raise FileNotFoundError(f"no adapter weights under {path}")


def adapter_layer_arrays(sd: Dict[str, np.ndarray], cfg: Dict[str, Any],
                         num_layers: int, module: str, in_dim: int,
                         out_dim: int, max_rank: int,
                         out_transform=None,
                         in_transform=None) -> Tuple[np.ndarray, np.ndarray]:
    """Stack one module's A/B across layers from a PEFT state dict, padding
    rank to ``max_rank`` (zero-padded rank columns are exact no-ops) and
    folding alpha/r into B. out_transform / in_transform: head pad/replicate
    hooks applied to B's out axis / A's in axis (same GQA transforms as the
    base weights, gqa.py:679+).

    Returns A (L, in, max_rank), B (L, max_rank, out).
    """
    r = int(cfg.get("r", max_rank))
    alpha = float(cfg.get("lora_alpha", r))
    scale = alpha / r
    a_out = np.zeros((num_layers, in_dim, max_rank), np.float32)
    b_out = np.zeros((num_layers, max_rank, out_dim), np.float32)
    found = False
    for i in range(num_layers):
        cand_a = [k for k in sd if f"layers.{i}." in k and module in k
                  and "lora_A" in k]
        cand_b = [k for k in sd if f"layers.{i}." in k and module in k
                  and "lora_B" in k]
        if not cand_a:
            continue
        found = True
        a = np.asarray(sd[cand_a[0]], np.float32)     # torch layout (r, in)
        b = np.asarray(sd[cand_b[0]], np.float32)     # (out, r)
        at = np.ascontiguousarray(a.T)                # (in, r)
        bt = np.ascontiguousarray(b.T) * scale        # (r, out)
        if in_transform is not None:
            at = in_transform(at)
        if out_transform is not None:
            bt = out_transform(bt)
        a_out[i, :at.shape[0], :at.shape[1]] = at
        b_out[i, :bt.shape[0], :bt.shape[1]] = bt
    if not found:
        raise KeyError(f"adapter has no weights for module {module!r}")
    return a_out, b_out


def set_adapter_slot(params: Dict[str, Any], layers_key: str, slot: int,
                     module: str, a: np.ndarray, b: np.ndarray) -> None:
    """Dynamic multi-LoRA: write adapter (a, b) into ``slot`` of the stacked
    device arrays in-place (functional update on the param tree)."""
    lw = params[layers_key]
    lw[f"lora_A_{module}"] = lw[f"lora_A_{module}"].at[:, slot].set(
        jnp.asarray(a, lw[f"lora_A_{module}"].dtype))
    lw[f"lora_B_{module}"] = lw[f"lora_B_{module}"].at[:, slot].set(
        jnp.asarray(b, lw[f"lora_B_{module}"].dtype))
