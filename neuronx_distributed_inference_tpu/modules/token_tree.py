"""Static token-tree speculation structures (reference: modules/eagle/
token_tree.py (646 LoC) + utils.py — precomputed per-level attention masks,
position offsets, paths; SURVEY §2.6).

A tree is defined by a list of paths (mc_sim-style): each path is a list of
branch indices from the root, e.g. ``[[0], [1], [0, 0], [0, 1]]`` = two
children of the root, plus two children of the first child. Node 0 is the
implicit root (the last committed token). Everything here is host-side
numpy precomputation; the arrays feed the jitted tree-verify graph as
constants."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class TokenTree:
    """Precomputed tree layout.

    Node 0 = root. Nodes are sorted by (depth, path) so siblings are
    adjacent and depth levels are contiguous.
    """

    paths: List[Tuple[int, ...]]
    num_nodes: int = field(init=False)
    depth: np.ndarray = field(init=False)        # (N,) root = 0
    parent: np.ndarray = field(init=False)       # (N,) root's parent = -1
    branch: np.ndarray = field(init=False)       # (N,) child index at parent
    ancestor_mask: np.ndarray = field(init=False)  # (N, N) bool, incl. self
    max_depth: int = field(init=False)
    # per (depth-1) level: how many distinct branch slots (top-k width the
    # proposer must produce for that level)
    level_widths: np.ndarray = field(init=False)

    def __post_init__(self):
        norm = [tuple(p) for p in self.paths]
        if () not in norm:
            norm.append(())
        norm = sorted(set(norm), key=lambda p: (len(p), p))
        for p in norm:
            if p and p[:-1] not in norm:
                raise ValueError(f"path {p} missing its parent {p[:-1]}")
        self.paths = norm
        n = len(norm)
        self.num_nodes = n
        idx = {p: i for i, p in enumerate(norm)}
        self.depth = np.array([len(p) for p in norm], np.int32)
        self.parent = np.array(
            [idx[p[:-1]] if p else -1 for p in norm], np.int32)
        self.branch = np.array([p[-1] if p else 0 for p in norm], np.int32)
        self.max_depth = int(self.depth.max())
        anc = np.zeros((n, n), bool)
        for i, p in enumerate(norm):
            anc[i, i] = True
            for d in range(len(p)):
                anc[i, idx[p[:d]]] = True
        self.ancestor_mask = anc
        widths = np.zeros((self.max_depth,), np.int32)
        for p in norm:
            if p:
                widths[len(p) - 1] = max(widths[len(p) - 1], p[-1] + 1)
        self.level_widths = widths

    @classmethod
    def from_config(cls, cfg) -> "TokenTree":
        """Accept a list of paths or a dict {"paths": [...]} (reference:
        token_tree_config JSON, models/config.py:243-274)."""
        if isinstance(cfg, dict):
            cfg = cfg.get("paths", cfg.get("tree", cfg))
        return cls(list(cfg))

    # -- verify-time helpers -------------------------------------------------

    def leaf_path_matrix(self) -> Tuple[np.ndarray, np.ndarray]:
        """(P, max_depth+1) node-index matrix of every root->node chain usable
        as an acceptance path (every node defines one), padded with -1, and
        (P,) path lengths (in nodes, incl. root)."""
        chains = []
        idx = {p: i for i, p in enumerate(self.paths)}
        for p in self.paths:
            chain = [idx[p[:d]] for d in range(len(p) + 1)]
            chains.append(chain)
        P = len(chains)
        out = np.full((P, self.max_depth + 1), -1, np.int32)
        lens = np.zeros((P,), np.int32)
        for i, c in enumerate(chains):
            out[i, :len(c)] = c
            lens[i] = len(c)
        return out, lens

    def node_positions(self, base_pos: np.ndarray) -> np.ndarray:
        """(B, N) absolute position of each node: base_pos + depth."""
        return np.asarray(base_pos)[:, None] + self.depth[None, :]

    def attention_mask(self, base_pos: np.ndarray, cache_len: int) -> np.ndarray:
        """(B, N, S) bool mask for tree verification over a contiguous cache:
        node i attends every real cache slot (< base_pos) plus the slots of
        its ancestors (written at base_pos + node index ordering).

        Node j is written at cache slot base_pos + j (node-index order), so
        ancestor visibility = ancestor_mask columns shifted by base_pos
        (reference: per-level tree masks, modules/eagle/token_tree.py)."""
        base_pos = np.asarray(base_pos)
        b = base_pos.shape[0]
        n = self.num_nodes
        kv = np.arange(cache_len, dtype=np.int64)[None, None, :]
        mask = kv < base_pos[:, None, None]                 # committed tokens
        slot = base_pos[:, None, None] + np.arange(n)[None, None, :]
        anc = np.broadcast_to(self.ancestor_mask[None], (b, n, n))
        tree_part = np.zeros((b, n, cache_len), bool)
        rows = np.arange(n)
        for i in range(b):
            cols = slot[i, 0]
            valid = cols < cache_len
            tree_part[i][:, cols[valid]] = anc[i][:, valid]
        return mask | tree_part


# the default medusa tree shipped as mc_sim_7b_63 in the reference ecosystem,
# trimmed to a small generic default
DEFAULT_TREE = [[0], [1], [2], [0, 0], [0, 1], [1, 0], [0, 0, 0]]
