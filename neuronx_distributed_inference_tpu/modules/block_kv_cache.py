"""Paged (block) KV cache — TPU-native analog of the reference's
``BlockKVCacheManager`` (reference: modules/kvcache/block_kv_cache_manager.py,
431 LoC) plus the host-side block allocator with vLLM-style prefix caching
(the reference exposes the same surface to vLLM via ``slot_mapping`` /
``active_block_table`` inputs).

Device layout:
  k, v : (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
  sharded P(None, None, None, ("ep","tp"), None) — heads sharded, blocks
  replicated across dp (each dp shard could own a block range; that variant
  arrives with attention-DP decode).

In-graph ops (pure, used inside the jitted step):
  * ``write_slots``       — scatter new K/V at flat slot ids
    (reference: write via slot_mapping, block_kv_cache_manager.py:268-375)
  * ``gather_block_kv``   — assemble a per-request (B, S, H, D) view from an
    ``active_block_table`` (reference: :183-267 gather via block table)

Host side:
  * ``BlockAllocator`` — free-list allocator + content-hash prefix cache
    (reference analog: vLLM's block manager; prefix-caching bucket logic
    model_wrapper.py:923-1045 selects buckets from cached-prefix length).

Block 0 is reserved as the NULL block: slot_mapping entries < 0 drop writes,
block_table entries 0 read zeros (masked out by the position mask anyway).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_MP
from ..resilience.errors import (CapacityError, ConfigurationError,
                                 KVCacheStateError)
from ..resilience.faults import FAULTS as _FAULTS
from ..telemetry import get_registry, metrics as tmetrics


@dataclass(frozen=True)
class BlockKVSpec:
    num_layers: int
    num_blocks: int            # includes the reserved null block 0
    block_size: int
    num_kv_heads: int          # padded/replicated per GQASharding
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.num_layers, self.num_blocks, self.block_size,
                self.num_kv_heads, self.head_dim)

    def blocks_for(self, seq_len: int) -> int:
        return -(-seq_len // self.block_size)


def block_cache_pspec() -> P:
    return P(None, None, None, AXIS_MP, None)


def init_block_cache(spec: BlockKVSpec, mesh: Optional[Mesh] = None):
    if mesh is not None:
        sharding = NamedSharding(mesh, block_cache_pspec())
        zeros = lambda: jax.device_put(jnp.zeros(spec.shape, spec.dtype), sharding)
    else:
        zeros = lambda: jnp.zeros(spec.shape, spec.dtype)
    return {"k": zeros(), "v": zeros()}


# ---------------------------------------------------------------------------
# In-graph ops (operate on ONE layer's cache, called inside the layer scan)
# ---------------------------------------------------------------------------

def write_slots(cache_layer: jnp.ndarray, new: jnp.ndarray,
                slot_mapping: jnp.ndarray) -> jnp.ndarray:
    """Scatter tokens into flat slots.

    cache_layer (N, Bs, H, D); new (B, T, H, D); slot_mapping (B, T) flat slot
    ids (block*block_size + offset), negative = drop (padding).
    """
    return write_slots_at_layer(cache_layer[None], new, 0, slot_mapping)[0]


def write_slots_at_layer(cache: jnp.ndarray, new: jnp.ndarray, layer,
                         slot_mapping: jnp.ndarray) -> jnp.ndarray:
    """In-place slot write into the FULL stacked cache (L, N, Bs, H, D) at
    ``layer`` (traced scalar inside the layer scan) — see
    kv_cache.write_tokens_at_layer for the carry-aliasing rationale."""
    L, n, bs, h, d = cache.shape
    flat = cache.reshape(L, n * bs, h, d)
    slots = slot_mapping.reshape(-1)
    # negative indices WRAP in jax scatter (slot -1 = last flat slot, which is
    # a real allocated block) — remap them past the end so mode="drop"
    # actually drops them
    slots = jnp.where(slots < 0, n * bs, slots)
    vals = new.astype(cache.dtype).reshape(-1, h, d)
    li = jnp.asarray(layer, jnp.int32)
    flat = flat.at[li, slots].set(vals, mode="drop", unique_indices=False)
    return flat.reshape(L, n, bs, h, d)


def read_layer(cache: jnp.ndarray, layer) -> jnp.ndarray:
    """Dynamic-slice one layer (N, Bs, H, D) out of the stacked paged cache
    (the paged layout keeps heads minor — the block gather is row-indexed,
    not head-sliced, so the contiguous-cache head-leading layout rationale
    does not apply here)."""
    return jax.lax.dynamic_index_in_dim(cache, jnp.asarray(layer, jnp.int32),
                                        0, keepdims=False)


def gather_block_kv(cache_layer: jnp.ndarray, block_table: jnp.ndarray
                    ) -> jnp.ndarray:
    """Assemble per-request contiguous KV from the block table.

    cache_layer (N, Bs, H, D); block_table (B, max_blocks) int32 →
    (B, max_blocks*Bs, H, D). Table entries 0 = null block (zeros).
    """
    g = cache_layer[block_table]               # (B, max_blocks, Bs, H, D)
    b, mb, bs, h, d = g.shape
    return g.reshape(b, mb * bs, h, d)


# ---------------------------------------------------------------------------
# Host-side slot-mapping construction
# ---------------------------------------------------------------------------

def slots_from_table(block_table: np.ndarray, positions: np.ndarray,
                     block_size: int) -> np.ndarray:
    """positions (B, T) in-sequence token positions -> flat slot ids (B, T)
    using each row's block table. Negative positions stay negative (drop)."""
    blk_idx = positions // block_size
    offs = positions % block_size
    blocks = np.take_along_axis(
        np.asarray(block_table), np.maximum(blk_idx, 0), axis=1)
    slots = blocks * block_size + offs
    return np.where(positions < 0, -1, slots).astype(np.int32)


def cut_cached_at_unwritten(blocks: Sequence[int], cached_tokens: int,
                            block_size: int, unwritten) -> int:
    """Clamp a prefix-cache hit against blocks whose contents are not
    fully written yet: a hit on a block freshly allocated by a sibling in
    the same batch — or by a still-in-flight chunked prefill — may read
    slots the writer's chunk has not landed. Cut the cached prefix at the
    first such block and recompute from there (recomputing a shared block
    writes identical values, so the cut is always safe). ``unwritten`` is
    any container of block ids supporting ``in``."""
    for bi in range(cached_tokens // block_size):
        if blocks[bi] in unwritten:
            return bi * block_size
    return cached_tokens


def slots_from_table_into(out: np.ndarray, block_table: np.ndarray,
                          positions: np.ndarray, block_size: int) -> None:
    """In-place :func:`slots_from_table` for the serving adapters' per-step
    scratch buffers: same slot values, no fresh (B, T) allocations on the
    decode hot path (positions here are always real — the negative-drop
    branch of the allocating variant is not needed)."""
    np.floor_divide(positions, block_size, out=out)
    out[:] = np.take_along_axis(block_table, out, axis=1)
    out *= block_size
    out += positions % block_size


# ---------------------------------------------------------------------------
# Block allocator + prefix cache (host)
# ---------------------------------------------------------------------------

def _hash_block(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(parent)
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.digest()


@dataclass
class _BlockMeta:
    ref_count: int = 0
    content_hash: Optional[bytes] = None   # set only for FULL immutable blocks


class BlockAllocator:
    """Free-list block allocator with content-hash prefix caching.

    * ``allocate(seq)`` returns (block_ids, num_cached_tokens): full prompt
      blocks whose content hash is already resident are reused (ref_count++)
      and need no recompute; the remainder are fresh blocks.
    * ``free(block_ids)`` decrements refs; cached blocks stay resident until
      evicted LRU when the free list runs dry.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = True):
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.num_blocks = num_blocks
        self.meta: Dict[int, _BlockMeta] = {i: _BlockMeta() for i in range(1, num_blocks)}
        self.free_list: List[int] = list(range(1, num_blocks))  # 0 = null block
        self.hash_to_block: Dict[bytes, int] = {}
        self._lru: List[int] = []          # cached, ref_count==0, oldest first
        # eviction hook (host-RAM KV spill tier, serving/fleet/): called
        # with (block_id, chain_hash) just BEFORE an LRU-resident prefix
        # block's hash registration is dropped — the last moment its
        # device payload is still identifiable by content
        self.on_evict = None

    @property
    def num_free(self) -> int:
        return len(self.free_list) + len(self._lru)

    def _pop_block(self) -> int:
        if self.free_list:
            return self.free_list.pop()
        if self._lru:                      # evict the oldest unreferenced cached block
            blk = self._lru.pop(0)
            h = self.meta[blk].content_hash
            if h is not None:
                if self.on_evict is not None:
                    self.on_evict(blk, h)
                self.hash_to_block.pop(h, None)
            self.meta[blk] = _BlockMeta()
            return blk
        raise CapacityError("out of KV cache blocks")

    def allocate(self, token_ids: Sequence[int]) -> Tuple[List[int], int]:
        """Allocate blocks for a prompt. Returns (block_ids, cached_tokens).
        On OOM this call's partial allocations are rolled back."""
        n_blocks = max(1, -(-len(token_ids) // self.block_size))
        blocks: List[int] = []
        cached_tokens = 0
        parent = b""
        matching = self.enable_prefix_caching
        for bi in range(n_blocks):
            chunk = token_ids[bi * self.block_size:(bi + 1) * self.block_size]
            full = len(chunk) == self.block_size
            h = _hash_block(parent, chunk) if (matching and full) else None
            if h is not None and h in self.hash_to_block:
                blk = self.hash_to_block[h]
                m = self.meta[blk]
                if m.ref_count == 0 and blk in self._lru:
                    self._lru.remove(blk)
                m.ref_count += 1
                blocks.append(blk)
                cached_tokens += self.block_size
                parent = h
                continue
            matching = False                # prefix broken; rest are fresh
            try:
                blk = self._pop_block()
            except CapacityError:
                # roll back this call: prefix-HIT blocks keep their valid
                # hashes; fresh blocks were hashed before their content was
                # written, so the hashes must go or later allocations would
                # prefix-"hit" garbage KV
                n_hit = cached_tokens // self.block_size
                self.free(blocks[:n_hit])
                self.invalidate(blocks[n_hit:])
                raise
            m = self.meta[blk]
            m.ref_count += 1
            if self.enable_prefix_caching and full:
                hh = _hash_block(parent, chunk)
                m.content_hash = hh
                self.hash_to_block[hh] = blk
                parent = hh
            blocks.append(blk)
        return blocks, cached_tokens

    def probe(self, token_ids: Sequence[int]) -> Tuple[int, List[int]]:
        """READ-ONLY prefix-warmth probe: (cached_tokens, hit block ids)
        that :meth:`allocate` WOULD serve from the prefix cache right now.
        Unlike allocate it takes no references, touches no LRU order and
        registers no hashes — schedulers call it per queued request to
        order admissions warm-first, so it must not perturb cache state."""
        if not self.enable_prefix_caching:
            return 0, []
        parent = b""
        blocks: List[int] = []
        cached = 0
        for bi in range(len(token_ids) // self.block_size):
            chunk = token_ids[bi * self.block_size:
                              (bi + 1) * self.block_size]
            parent = _hash_block(parent, chunk)
            blk = self.hash_to_block.get(parent)
            if blk is None:
                break
            blocks.append(blk)
            cached += self.block_size
        return cached, blocks

    def extend(self, blocks: List[int], new_len: int) -> List[int]:
        """Grow a running sequence's block list to cover ``new_len`` tokens.
        On OOM the blocks added by this call are rolled back."""
        need = max(1, -(-new_len // self.block_size))
        added: List[int] = []
        while len(blocks) + len(added) < need:
            try:
                blk = self._pop_block()
            except CapacityError:
                self.free(added)
                raise
            self.meta[blk].ref_count += 1
            added.append(blk)
        blocks.extend(added)
        return blocks

    def free(self, blocks: Sequence[int]):
        for blk in blocks:
            m = self.meta[blk]
            m.ref_count -= 1
            if m.ref_count < 0:
                raise KVCacheStateError(f"double free of block {blk}")
            if m.ref_count == 0:
                if m.content_hash is not None:
                    self._lru.append(blk)  # keep resident for prefix reuse
                else:
                    self.free_list.append(blk)

    def invalidate(self, blocks: Sequence[int]):
        """Free blocks whose pending content was never written (aborted
        admission): drop their hash registration once unreferenced so the
        prefix cache can never serve them. Blocks still referenced by
        another sequence keep their hash — that content predates the
        aborted call and is valid."""
        for blk in blocks:
            m = self.meta[blk]
            m.ref_count -= 1
            if m.ref_count < 0:
                raise KVCacheStateError(f"double free of block {blk}")
            if m.ref_count == 0:
                if m.content_hash is not None:
                    if self.hash_to_block.get(m.content_hash) == blk:
                        del self.hash_to_block[m.content_hash]
                    m.content_hash = None
                self.free_list.append(blk)


class NativeBlockAllocator:
    """ctypes wrapper over the C++ allocator (native/block_allocator.cpp) —
    same interface and identical block-id sequences as :class:`BlockAllocator`
    (asserted by tests). Used automatically when the native library builds."""

    MAX_BLOCKS = 65536

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = True):
        from .. import native
        import ctypes
        self._ct = ctypes
        self._lib = native.load_library()
        if self._lib is None:
            raise ImportError("native library unavailable")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.enable_prefix_caching = enable_prefix_caching
        self._h = self._lib.nxdi_alloc_create(num_blocks, block_size,
                                              int(enable_prefix_caching))

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.nxdi_alloc_destroy(h)
            self._h = None

    @property
    def num_free(self) -> int:
        return self._lib.nxdi_alloc_num_free(self._h)

    def allocate(self, token_ids: Sequence[int]) -> Tuple[List[int], int]:
        ct = self._ct
        toks = np.ascontiguousarray(np.asarray(token_ids, np.int64))
        max_out = max(1, -(-len(toks) // self.block_size))
        out = (ct.c_int * max_out)()
        cached = ct.c_int(0)
        n = self._lib.nxdi_alloc_allocate(
            self._h, toks.ctypes.data_as(ct.POINTER(ct.c_int64)), len(toks),
            out, max_out, ct.byref(cached))
        if n < 0:
            raise CapacityError("out of KV cache blocks")
        return list(out[:n]), int(cached.value)

    def probe(self, token_ids: Sequence[int]) -> Tuple[int, List[int]]:
        """Read-only prefix-warmth probe (see :meth:`BlockAllocator.probe`).
        Returns cold (0, []) under a pre-probe ``libnxdi_native.so`` that
        was built before ``nxdi_alloc_probe`` existed — warmth ordering is
        an optimization, never a correctness dependency."""
        if not self.enable_prefix_caching:
            return 0, []
        fn = getattr(self._lib, "nxdi_alloc_probe", None)
        if fn is None:  # pragma: no cover - stale cached library
            return 0, []
        ct = self._ct
        toks = np.ascontiguousarray(np.asarray(token_ids, np.int64))
        max_out = max(1, len(toks) // self.block_size)
        out = (ct.c_int * max_out)()
        cached = fn(self._h, toks.ctypes.data_as(ct.POINTER(ct.c_int64)),
                    len(toks), out, max_out)
        return int(cached), list(out[:cached // self.block_size])

    def extend(self, blocks: List[int], new_len: int) -> List[int]:
        ct = self._ct
        need = max(1, -(-new_len // self.block_size))
        buf = (ct.c_int * max(need, len(blocks)))(*blocks)
        n = self._lib.nxdi_alloc_extend(self._h, buf, len(blocks), new_len,
                                        max(need, len(blocks)))
        if n < 0:
            raise CapacityError("out of KV cache blocks")
        return list(buf[:n])

    def free(self, blocks: Sequence[int]):
        ct = self._ct
        arr = (ct.c_int * len(blocks))(*blocks)
        if self._lib.nxdi_alloc_free(self._h, arr, len(blocks)) < 0:
            raise KVCacheStateError("double free of a KV block")

    def invalidate(self, blocks: Sequence[int]):
        ct = self._ct
        arr = (ct.c_int * len(blocks))(*blocks)
        if self._lib.nxdi_alloc_invalidate(self._h, arr, len(blocks)) < 0:
            raise KVCacheStateError("double free of a KV block")


def make_block_allocator(num_blocks: int, block_size: int,
                         enable_prefix_caching: bool = True):
    """Prefer the native C++ allocator; fall back to the Python one
    (NXDI_TPU_NATIVE=0 forces the fallback)."""
    from .. import native
    if native.native_enabled() and native.load_library() is not None:
        return NativeBlockAllocator(num_blocks, block_size,
                                    enable_prefix_caching)
    return BlockAllocator(num_blocks, block_size, enable_prefix_caching)


class BlockKVCacheManager:
    """Host-side owner: spec + cache pytree + allocator + per-seq block tables
    (reference: BlockKVCacheManager + the vLLM-facing surface).

    Telemetry (host-side, no-op while disabled): blocks in-use/total gauges,
    allocation-failure counter, prefix-cache hit-token counter."""

    def __init__(self, spec: BlockKVSpec, mesh: Optional[Mesh] = None,
                 enable_prefix_caching: bool = True):
        self.spec = spec
        self.mesh = mesh
        self.cache = init_block_cache(spec, mesh)
        self.allocator = make_block_allocator(spec.num_blocks, spec.block_size,
                                              enable_prefix_caching)
        self.tables: Dict[int, List[int]] = {}     # seq_id -> block list
        self.lens: Dict[int, int] = {}
        self._hit_blocks: Dict[int, int] = {}      # leading prefix-HIT blocks
        self._tel_occupancy()

    def _tel_registry(self):
        reg = get_registry()
        return reg if reg.enabled else None

    def _tel_occupancy(self, reg=None):
        reg = reg if reg is not None else self._tel_registry()
        if reg is None:
            return
        usable = self.spec.num_blocks - 1          # null block excluded
        tmetrics.kv_blocks_total_gauge(reg).set(usable)
        # num_free counts free-list + unreferenced prefix-cached residents;
        # in-use = blocks some live sequence still references
        tmetrics.kv_blocks_in_use_gauge(reg).set(
            usable - self.allocator.num_free)

    def begin_sequence(self, seq_id: int, token_ids: Sequence[int]
                       ) -> Tuple[List[int], int]:
        if seq_id in self.tables:      # stale table from an unreleased run
            self.end_sequence(seq_id)  # (would otherwise leak its blocks)
        reg = self._tel_registry()
        try:
            if _FAULTS.active:
                _FAULTS.fire("paged_alloc")
            blocks, cached = self.allocator.allocate(token_ids)
        except CapacityError:
            if reg is not None:
                tmetrics.kv_alloc_failures_counter(reg).inc()
            raise
        self.tables[seq_id] = blocks
        self.lens[seq_id] = len(token_ids)
        self._hit_blocks[seq_id] = cached // self.spec.block_size
        if reg is not None:
            if cached:
                tmetrics.prefix_hit_tokens_counter(reg).inc(cached)
            self._tel_occupancy(reg)
        return blocks, cached

    def grow(self, seq_id: int, n_new: int = 1) -> List[int]:
        self.lens[seq_id] += n_new
        try:
            if _FAULTS.active:
                _FAULTS.fire("paged_alloc")
            self.tables[seq_id] = self.allocator.extend(
                self.tables[seq_id], self.lens[seq_id])
        except CapacityError:
            self.lens[seq_id] -= n_new
            reg = self._tel_registry()
            if reg is not None:
                tmetrics.kv_alloc_failures_counter(reg).inc()
            raise
        self._tel_occupancy()
        return self.tables[seq_id]

    def shrink(self, seq_id: int, n_tokens: int = 1) -> List[int]:
        """Inverse of :meth:`grow`: forget the last ``n_tokens`` and free
        blocks no longer covered. Used to roll a sequence back to its
        pre-step state when a decode step fails after growth."""
        if seq_id not in self.tables:
            raise KVCacheStateError(f"shrink of unknown seq_id {seq_id}")
        new_len = self.lens[seq_id] - n_tokens
        if new_len < 0:
            raise KVCacheStateError(
                f"shrink below zero for seq_id {seq_id} "
                f"({self.lens[seq_id]} - {n_tokens})")
        need = max(1, self.spec.blocks_for(new_len))
        blocks = self.tables[seq_id]
        if len(blocks) > need:
            extra = blocks[need:]
            del blocks[need:]
            self.allocator.free(extra)
        self.lens[seq_id] = new_len
        self._tel_occupancy()
        return blocks

    def end_sequence(self, seq_id: int):
        self.allocator.free(self.tables.pop(seq_id))
        self.lens.pop(seq_id)
        self._hit_blocks.pop(seq_id, None)
        self._tel_occupancy()

    def abort_sequence(self, seq_id: int, unwritten=None):
        """End a sequence admitted by a transaction that failed before (or
        while) its prefill wrote KV: prefix-HIT blocks — whose content
        predates the aborted call — are freed normally, but fresh blocks
        are :meth:`~BlockAllocator.invalidate`\\ d so their never-written
        contents can never be served as prefix hits.

        ``unwritten`` (chunked-prefill teardown) overrides the allocator's
        hit/fresh split with an explicit container of block ids whose
        content never fully landed: a prefix HIT on a block another
        still-pending sequence allocated (and hashed) but has not written
        yet is itself unwritten, and must be invalidated — not freed as
        valid — or its garbage KV becomes servable once the last holder
        lets go."""
        blocks = self.tables.pop(seq_id)
        n_hit = self._hit_blocks.pop(seq_id, 0)
        self.lens.pop(seq_id)
        if unwritten is None:
            self.allocator.free(blocks[:n_hit])
            self.allocator.invalidate(blocks[n_hit:])
        else:
            self.allocator.free([b for b in blocks if b not in unwritten])
            self.allocator.invalidate(
                [b for b in blocks if b in unwritten])
        self._tel_occupancy()

    def set_spill_hook(self, hook) -> None:
        """Install ``hook(block_id, chain_hash)`` to run just before a
        prefix-cached resident block is LRU-evicted (the moment its
        content would otherwise become unreachable) — the attach point of
        the host-RAM KV spill tier (serving/fleet/kv_tier.py).

        The hook is keyed by the PYTHON allocator's blake2b chain hashes
        (the same :func:`_hash_block` chain the spill tier and the
        handoff records use), so it requires the Python
        :class:`BlockAllocator`. A native (C++) allocator is swapped for
        an equivalent fresh Python one when NOTHING live depends on it —
        no sequence tables and every block free. The swap may still
        discard unreferenced prefix-cache residency (warm prompts
        recompute once); it can never discard live sequence state —
        swapping with live tables (or referenced blocks) raises typed
        instead. The hook must not raise — the adapter's spill hook
        swallows and counts its own failures (``kv_spill`` fault-point
        contract)."""
        alloc = self.allocator
        if not isinstance(alloc, BlockAllocator):
            if self.tables or alloc.num_free != self.spec.num_blocks - 1:
                raise ConfigurationError(
                    "set_spill_hook needs the Python BlockAllocator's "
                    "eviction callback, and this manager's native "
                    "allocator holds live state — attach the spill "
                    "tier before the first admission (or build with "
                    "NXDI_TPU_NATIVE=0)")
            alloc = BlockAllocator(self.spec.num_blocks,
                                   self.spec.block_size,
                                   alloc.enable_prefix_caching)
            self.allocator = alloc
        alloc.on_evict = hook

    def probe_cached_tokens(self, token_ids: Sequence[int]
                            ) -> Tuple[int, List[int]]:
        """READ-ONLY prefix-warmth probe: (cached_tokens, hit block ids)
        a :meth:`begin_sequence` of ``token_ids`` would currently serve
        from the prefix cache. No references are taken and no LRU/hash
        state moves — safe to call per queued request. The serving engine
        uses it to admit warm-prefix requests first; callers holding
        pending (unwritten) admissions must additionally cut the count at
        the first unwritten block (:func:`cut_cached_at_unwritten`)."""
        return self.allocator.probe(list(token_ids))

    def block_table_array(self, seq_ids: Sequence[int], max_blocks: int
                          ) -> np.ndarray:
        out = np.zeros((len(seq_ids), max_blocks), np.int32)
        for i, sid in enumerate(seq_ids):
            blks = self.tables.get(sid, [])[:max_blocks]
            out[i, :len(blks)] = blks
        return out

    def fill_block_table(self, out: np.ndarray, seq_ids: Sequence[int],
                         counts: List[int]) -> None:
        """Incrementally refresh a cached block-table array IN PLACE:
        rewrite only rows whose block list length differs from the
        ``counts`` snapshot (updated in place too). Valid while tables
        only grow append-only between calls — every serving path that
        shrinks or rebuilds a table (step rollback, preemption,
        end/begin_sequence) drops its scratch and rebuilds from
        :meth:`block_table_array`. Entries past a row's block count are
        left as-is: readers mask them out by position, so their values
        never reach a live attention weight or cache write."""
        for i, sid in enumerate(seq_ids):
            blks = self.tables.get(sid, ())
            n = min(len(blks), out.shape[1])
            if n != counts[i]:
                out[i, :n] = blks[:n]
                counts[i] = n

    @property
    def max_blocks_per_seq(self) -> int:
        return max((len(b) for b in self.tables.values()), default=1)
