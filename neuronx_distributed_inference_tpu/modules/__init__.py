"""modules subpackage."""
