"""Mixture-of-Experts block — TPU-native replacement for the reference's NxD
MoE stack (reference: modules/moe_v2.py ``initialize_moe_module`` building
RouterTopK + ExpertMLPsV2 + SharedExperts, and the all-experts decode MoE
kernel ``moe_token_gen`` noted in SURVEY §2.10).

Design:
  * Router: replicated (H, E) matmul in fp32, softmax (or sigmoid for
    DeepSeek-style routers), top-k, optional renormalization and routed
    scaling (reference: MoENeuronConfig knobs, models/config.py:798-846).
  * Experts, dense path: ALL experts compute on all tokens, outputs combined
    with the (B,T,E) routing weights. This mirrors the reference's decode
    all-experts kernel; for the small T of token generation the expert matmuls
    are batched into one einsum that XLA maps onto the MXU. Expert dim shards
    on mesh axis "ep" (moe_ep), intermediate dim on "tp" (moe_tp) — the
    combine-sum over E emits a psum over "ep" automatically.
  * Experts, ragged path (prefill): tokens are sorted by expert and run
    through grouped matmuls via ``jax.lax.ragged_dot`` — the dropless
    TPU-native analog of the reference's blockwise matmul
    (MoENeuronConfig blockwise configs). Used when T is large enough that
    all-experts compute would dominate.
  * Shared experts (reference: SharedExperts in moe_v2.py:104) are a plain
    dense MLP added to the routed output.

All routing math in fp32 (router logits decide tokens; bf16 tie-breaks
diverge from HF goldens).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..parallel.mesh import (AXIS_DP, AXIS_EP, AXIS_MP, AXIS_TP,
                             shard_constraint)
from .quantization import dequantize, is_quantized_leaf, qeinsum, qlinear


@dataclass(frozen=True)
class MoESpec:
    """Static MoE architecture description (hashable; closed over by jit)."""

    num_experts: int
    top_k: int
    intermediate_size: int           # per-expert intermediate
    normalize_topk: bool = True      # renormalize top-k affinities
    routed_scaling: Optional[float] = None
    # "softmax" | "sigmoid" | "sparsemixer" (phimoe inference routing)
    router_act: str = "softmax"
    sparsemixer_eps: float = 0.01    # phimoe router_jitter_noise
    pre_softmax_topk: bool = False   # top-k on raw logits, then act over k
    shared_intermediate: int = 0     # 0 = no shared experts
    act: str = "silu"
    # bias added to router scores for expert selection only (DeepSeek-V3
    # e_score_correction_bias); affinity weights still use raw scores
    has_router_bias: bool = False
    # "select": bias affects only which experts win (deepseek);
    # "logits": bias is part of the logits — affects affinities too (gpt-oss
    # router = linear with bias, topk, softmax over the k biased logits)
    router_bias_mode: str = "select"
    # per-expert projection biases (gpt-oss gate_up/down biases)
    expert_bias: bool = False
    # GLU form: "gated" = act(gate)*up; "oss_clamp" = gpt-oss clamped swiglu
    # glu = gate*sigmoid(alpha*gate) with gate<=limit, |up|<=limit,
    # out = (up+1)*glu
    glu_style: str = "gated"
    glu_alpha: float = 1.702
    glu_limit: float = 7.0
    # group-limited routing (DeepSeek-V3: experts split into n_group groups,
    # only the topk_group best groups — by sum of their top-2 biased scores —
    # are eligible for expert selection)
    n_group: int = 1
    topk_group: int = 1
    # llama4 routing: the routing weight scales the expert INPUT
    # (routed_in = hidden * sigmoid(score); reference llama4 Llama4TextMoe)
    # instead of the expert output — not equivalent through the gated
    # nonlinearity, so it is its own mode
    input_scaled: bool = False
    # TOTAL-token-count (B*T) threshold at or below which the dense
    # all-experts path is used; above it the ragged sorted-grouped-matmul
    # path runs. Decode (B*1 tokens) stays dense up to batch 64 by default.
    dense_max_tokens: int = 64
    # hybrid CTE/TKG expert sharding (reference: moe_v2.py:135-161
    # HybridShardingConfig — moe_tkg_ep_degree=1): prefill keeps experts
    # sharded on "ep" (token-parallel experts, all-to-all-free combine via
    # psum); DECODE re-constrains the expert weights so every device holds
    # ALL experts with the intermediate dim split over ("ep","tp") — the
    # all-gather of the weights is loop-invariant, so XLA hoists it out of
    # the fused decode scan (the GSPMD analog of the reference's
    # relayout-once-at-load into the TKG process group)
    tkg_experts_local: bool = False


def _act_fn(name: str):
    from ..models.model_base import ACT_FNS
    return ACT_FNS[name]


def route(moe: MoESpec, h: jnp.ndarray, router_w: jnp.ndarray,
          router_bias: Optional[jnp.ndarray] = None
          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compute routing: h (B,T,H), router_w (H,E) ->
    (top_vals (B,T,k) fp32 affinities, top_idx (B,T,k) expert ids).

    Reference: RouterTopK (moe_v2.py:5-15) with the affinity knobs of
    MoENeuronConfig (normalize_top_k_affinities, routed_scaling_factor).
    """
    logits = h.astype(jnp.float32) @ router_w.astype(jnp.float32)  # (B,T,E)
    if moe.router_act == "sparsemixer":
        return _sparsemixer_route(moe, logits)
    if router_bias is not None and moe.router_bias_mode == "logits":
        logits = logits + router_bias
        router_bias = None
    if moe.router_act == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    elif moe.pre_softmax_topk:
        scores = logits
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    select = scores + router_bias if router_bias is not None else scores
    if moe.n_group > 1:
        # group-limited greedy (DeepSeek-V3 get_topk_indices): rank groups by
        # the sum of their top-2 biased scores, zero out losing groups
        b, t, e = select.shape
        g = moe.n_group
        grouped = select.reshape(b, t, g, e // g)
        top2, _ = jax.lax.top_k(grouped, 2)
        group_scores = top2.sum(axis=-1)                           # (B,T,G)
        _, group_idx = jax.lax.top_k(group_scores, moe.topk_group)
        group_mask = jnp.zeros((b, t, g), bool).at[
            jnp.arange(b)[:, None, None], jnp.arange(t)[None, :, None],
            group_idx].set(True)
        mask = jnp.broadcast_to(group_mask[..., None],
                                grouped.shape).reshape(b, t, e)
        select = jnp.where(mask, select, 0.0)
    _, top_idx = jax.lax.top_k(select, moe.top_k)                  # (B,T,k)
    top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)
    if moe.pre_softmax_topk and moe.router_act != "sigmoid":
        top_vals = jax.nn.softmax(top_vals, axis=-1)
    if moe.normalize_topk:
        top_vals = top_vals / jnp.maximum(
            jnp.sum(top_vals, axis=-1, keepdims=True), 1e-20)
    if moe.routed_scaling is not None:
        top_vals = top_vals * moe.routed_scaling
    return top_vals, top_idx


def _sparsemixer_route(moe: MoESpec, logits: jnp.ndarray
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phi-3.5-MoE sparsemixer routing, inference path (reference:
    contrib/models/Phi-3.5-MoE-instruct — HF modeling_phimoe.sparsemixer
    eval branch): expert i is the argmax of the remaining scores; its
    affinity is a softmax over the scores with entries masked out where
    (max - s) / max(|s|, max) > 2·jitter_eps. top_k must be 2."""
    if moe.top_k != 2:
        raise NotImplementedError(
            f"sparsemixer routing is defined for top_k=2 (got {moe.top_k})")
    eps = moe.sparsemixer_eps

    def pick(scores, ref):
        """One sparsemixer selection over ``scores``. The jitter threshold is
        measured against — and the |.| stabilizer taken from — ``ref``, the
        ORIGINAL logits (HF keeps ``scores.abs()`` across both passes), while
        the max/argmax/softmax all run on ``scores``. Taking both as
        parameters (no closure reads) keeps any call site honest about which
        tensor plays which role."""
        mx = jnp.max(scores, axis=-1, keepdims=True)
        factor = jnp.maximum(jnp.abs(ref), mx)
        masked = jnp.where((mx - ref) / factor > 2 * eps, -jnp.inf, scores)
        idx = jnp.argmax(scores, axis=-1)
        gates = jax.nn.softmax(masked, axis=-1)
        val = jnp.take_along_axis(gates, idx[..., None], axis=-1)
        return val[..., 0], idx

    v1, i1 = pick(logits, logits)
    # second pass: mask out the winner, re-pick over the remainder (threshold
    # vs the REMAINING max, stabilizer still |original logits|)
    masked_scores = jnp.where(
        jax.nn.one_hot(i1, logits.shape[-1], dtype=bool), -jnp.inf, logits)
    v2, i2 = pick(masked_scores, logits)
    return (jnp.stack([v1, v2], axis=-1),
            jnp.stack([i1, i2], axis=-1).astype(jnp.int32))


def combine_matrix(num_experts: int, top_vals: jnp.ndarray,
                   top_idx: jnp.ndarray) -> jnp.ndarray:
    """Scatter (B,T,k) affinities into a dense (B,T,E) combine matrix."""
    b, t, _ = top_vals.shape
    return jnp.zeros((b, t, num_experts), jnp.float32).at[
        jnp.arange(b)[:, None, None], jnp.arange(t)[None, :, None],
        top_idx].add(top_vals)


def _glu(moe: MoESpec, gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    if moe.glu_style == "oss_clamp":
        gate = jnp.minimum(gate, moe.glu_limit)
        up = jnp.clip(up, -moe.glu_limit, moe.glu_limit)
        return (up + 1.0) * (gate * jax.nn.sigmoid(gate * moe.glu_alpha))
    return _act_fn(moe.act)(gate) * up


def experts_dense(moe: MoESpec, x: jnp.ndarray, top_vals: jnp.ndarray,
                  top_idx: jnp.ndarray, wg: jnp.ndarray, wu: jnp.ndarray,
                  wd: jnp.ndarray, bg=None, bu=None, bd=None,
                  local_experts: bool = False) -> jnp.ndarray:
    """All-experts dense compute (reference: moe_token_gen all-experts decode
    kernel). x (B,T,H); wg/wu (E,H,I); wd (E,I,H); b* optional (E,·) biases.

    ``local_experts``: the weights were re-constrained all-experts-local
    with the intermediate dim split tp-major over ("tp","ep")
    (tkg_experts_local decode) — the intermediate activation must follow
    the same layout, or GSPMD reshards the freshly gathered weights
    straight back to expert-parallel (the involuntary-full-remat warning
    MULTICHIP r05 flagged)."""
    dt = x.dtype
    combine = combine_matrix(moe.num_experts, top_vals, top_idx)  # (B,T,E)
    if moe.input_scaled:
        # llama4: scale the expert INPUT by the affinity, combine with 1s
        xe = (x[:, :, None, :].astype(jnp.float32)
              * combine[..., None]).astype(dt)          # (B,T,E,H)
        gate = qeinsum("bteh,ehi->btei", xe, wg)
        up = qeinsum("bteh,ehi->btei", xe, wu)
        combine = (combine > 0).astype(jnp.float32)
    else:
        # (B,T,E,I): expert axis sharded on ep, intermediate on tp
        gate = qeinsum("bth,ehi->btei", x, wg)
        up = qeinsum("bth,ehi->btei", x, wu)
    if bg is not None:
        gate = gate + bg
        up = up + bu
    inter_spec = ((AXIS_DP, None, None, (AXIS_TP, AXIS_EP)) if local_experts
                  else (AXIS_DP, None, AXIS_EP, AXIS_TP))
    inter = shard_constraint(_glu(moe, gate, up), *inter_spec)
    outs = qeinsum("btei,eih->bteh", inter, wd)
    if bd is not None:
        outs = outs + bd
    # combine-weighted sum over E — psum over "ep" + "tp" partial sums
    y = jnp.einsum("bteh,bte->bth", outs.astype(jnp.float32), combine)
    return shard_constraint(y.astype(dt), AXIS_DP, None, None)


def experts_ragged(moe: MoESpec, x: jnp.ndarray, top_vals: jnp.ndarray,
                   top_idx: jnp.ndarray, wg: jnp.ndarray, wu: jnp.ndarray,
                   wd: jnp.ndarray, bg=None, bu=None, bd=None) -> jnp.ndarray:
    """Dropless grouped-matmul path: sort token copies by expert, run
    ``jax.lax.ragged_dot`` per projection, unsort and combine.

    TPU-native analog of the reference's blockwise MoE matmul
    (MoENeuronConfig blockwise configs; SURVEY §2.2). Static shapes: the
    sorted token-copy count is exactly B*T*k.
    """
    b, t, h = x.shape
    k = moe.top_k
    dt = x.dtype
    # ragged_dot needs materialized fp expert weights; dequantize per call
    # (prefill is compute-bound, the dequant is amortized over many tokens)
    wg, wu, wd = (dequantize(w, dt) if is_quantized_leaf(w) else w
                  for w in (wg, wu, wd))

    flat_x = x.reshape(b * t, h)
    flat_expert = top_idx.reshape(-1)                       # (N,) expert ids
    flat_weight = top_vals.reshape(-1)                      # (N,) fp32

    order = jnp.argsort(flat_expert)                        # stable
    inv = jnp.argsort(order)
    sorted_expert = flat_expert[order]
    sorted_tokens = flat_x[order // k]                      # (N, H)
    group_sizes = jnp.bincount(flat_expert, length=moe.num_experts
                               ).astype(jnp.int32)

    if moe.input_scaled:
        # llama4: affinity scales the expert input; outputs combine with 1s
        sorted_tokens = (sorted_tokens.astype(jnp.float32)
                         * flat_weight[order][:, None]).astype(dt)
        flat_weight = jnp.ones_like(flat_weight)
    gate = jax.lax.ragged_dot(sorted_tokens, wg, group_sizes)
    up = jax.lax.ragged_dot(sorted_tokens, wu, group_sizes)
    if bg is not None:
        gate = gate + bg[sorted_expert]
        up = up + bu[sorted_expert]
    inter = _glu(moe, gate, up)                             # (N, I)
    outs = jax.lax.ragged_dot(inter, wd, group_sizes)       # (N, H)
    if bd is not None:
        outs = outs + bd[sorted_expert]

    outs = outs[inv].astype(jnp.float32) * flat_weight[:, None]
    y = outs.reshape(b * t, k, h).sum(axis=1).reshape(b, t, h)
    return y.astype(dt)


def moe_block(moe: MoESpec, x: jnp.ndarray, layer_w: Dict[str, Any],
              phase: str = "prefill") -> jnp.ndarray:
    """Full MoE block: route + experts (+ shared experts). x (B,T,H)."""
    router_bias = layer_w.get("router_bias") if moe.has_router_bias else None
    top_vals, top_idx = route(moe, x, layer_w["router"], router_bias)
    experts = (experts_dense if x.shape[0] * x.shape[1] <= moe.dense_max_tokens
               else experts_ragged)
    biases = ((layer_w["expert_gate_bias"], layer_w["expert_up_bias"],
               layer_w["expert_down_bias"]) if moe.expert_bias
              else (None, None, None))
    wg, wu, wd = (layer_w["expert_gate"], layer_w["expert_up"],
                  layer_w["expert_down"])
    if (moe.tkg_experts_local and phase == "decode"
            and experts is experts_dense and not is_quantized_leaf(wg)):
        # hybrid TKG sharding: all experts local, intermediate split over
        # BOTH model axes (see MoESpec.tkg_experts_local). DENSE path
        # only: the ragged grouped-matmul fallthrough (decode batch above
        # dense_max_tokens) has no matching intermediate constraint, so
        # re-laid weights would just be resharded back per step — it keeps
        # the stored expert-parallel layout instead.
        #
        # Two-step reshard, tp-MAJOR on the intermediate dim: the sliced
        # layer weight can reach the decode layout by an ep all-gather of
        # the expert dim plus a LOCAL slice of the intermediate shard each
        # device already holds. The previous one-shot constraint (ep-major
        # intermediate split, against a producer whose tp annotation the
        # layer-scan slice had dropped) forced GSPMD into "involuntary
        # full rematerialization" — replicate-then-repartition — on every
        # decode step (MULTICHIP r05 spmd_partitioner warnings). The first
        # constraint re-pins the STORED layout (pure annotation, no data
        # motion); the second is then all-gather + slice.
        def recon(w, stored, target):
            return shard_constraint(shard_constraint(w, *stored), *target)
        wg = recon(wg, (AXIS_EP, None, AXIS_TP),
                   (None, None, (AXIS_TP, AXIS_EP)))
        wu = recon(wu, (AXIS_EP, None, AXIS_TP),
                   (None, None, (AXIS_TP, AXIS_EP)))
        wd = recon(wd, (AXIS_EP, AXIS_TP, None),
                   (None, (AXIS_TP, AXIS_EP), None))
        # the dense compute must KEEP the local-expert layout for its
        # intermediate, or GSPMD reshards the weights back (see
        # experts_dense.local_experts)
        y = experts_dense(moe, x, top_vals, top_idx, wg, wu, wd,
                          *biases, local_experts=True)
        return _shared_experts(moe, x, y, layer_w)
    y = experts(moe, x, top_vals, top_idx, wg, wu, wd, *biases)
    return _shared_experts(moe, x, y, layer_w)


def _shared_experts(moe: MoESpec, x: jnp.ndarray, y: jnp.ndarray,
                    layer_w: Dict[str, Any]) -> jnp.ndarray:
    """Add the always-on shared-expert branch (DeepSeek/GLM style)."""
    if moe.shared_intermediate > 0:
        act = _act_fn(moe.act)
        s = act(qlinear(x, layer_w["shared_gate"])) * qlinear(x, layer_w["shared_up"])
        s = shard_constraint(s, AXIS_DP, None, AXIS_MP)
        y = y + qlinear(s, layer_w["shared_down"])
    return y
