"""Bucket ladder generation (reference: modules/autobucketing.py).

Buckets are the static shapes we AOT-compile; the host pads each request to
the smallest bucket that fits (reference: generate_buckets :8-20 — powers of
two between min and max)."""

from __future__ import annotations

from typing import List, Optional

from ..telemetry import get_registry
from ..telemetry.metrics import bucket_selected_counter


def generate_buckets(min_len: int, max_len: int) -> List[int]:
    """Powers-of-2 ladder from min to max, always including max
    (reference: autobucketing.py:8-20)."""
    if min_len >= max_len:
        return [max_len]
    buckets = []
    b = max(min_len, 1)
    # round min up to a power of two
    while b & (b - 1):
        b += b & -b
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


def context_encoding_buckets(tpu_config) -> List[int]:
    """Prefill bucket ladder (reference: autobucketing.py:149)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.max_context_length]
    if tpu_config.context_encoding_buckets:
        return sorted(tpu_config.context_encoding_buckets)
    return generate_buckets(128, tpu_config.max_context_length)


def token_generation_buckets(tpu_config) -> List[int]:
    """Decode-side bucket ladder over total sequence length
    (reference: autobucketing.py:226). The decode graph compiled for bucket
    ``b`` READS only cache slots [0, b) — early decode streams a fraction of
    the allocated cache (the decode step is HBM-bound, so this is a direct
    throughput win; the reference's TKG seq buckets serve the same role)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.seq_len]
    if tpu_config.token_generation_buckets:
        return sorted(tpu_config.token_generation_buckets)
    return generate_buckets(128, tpu_config.seq_len)


def get_target_bucket(buckets: List[int], length: int,
                      kind: Optional[str] = None) -> int:
    """Smallest bucket >= length (reference: model_wrapper.py:831-921).

    ``kind`` tags the selection for telemetry ("ctx"/"tkg"/"batch"/
    "block_table"); host-side only, a no-op while telemetry is disabled."""
    for b in buckets:
        if b >= length:
            if kind is not None:
                reg = get_registry()
                if reg.enabled:
                    bucket_selected_counter(reg).inc(kind=kind, bucket=str(b))
            return b
    raise ValueError(f"length {length} exceeds largest bucket {buckets[-1]}")


def batch_buckets(tpu_config) -> List[int]:
    """TKG batch-bucket ladder (reference: 2-D batch x seq TKG buckets,
    autobucketing.py:203): with 2-D bucketing a short batch pads to the
    smallest BATCH bucket instead of the full compiled batch — fewer pad
    rows, at the cost of extra compiled graphs. 1-D mode keeps the single
    full-batch bucket."""
    full = tpu_config.batch_size
    if not (tpu_config.enable_bucketing and tpu_config.enable_2d_bucketing):
        return [full]
    if tpu_config.tkg_batch_buckets:
        out = sorted(set(tpu_config.tkg_batch_buckets))
        if out[-1] != full:
            raise ValueError("tkg_batch_buckets must end at batch_size")
        return out
    return generate_buckets(1, full)


def prefill_chunk_buckets(ctx_buckets: List[int],
                          chunk_tokens: Optional[int] = None) -> List[int]:
    """Width ladder for packed prefill-chunk dispatches (serving.py
    ``PagedEngineAdapter``): the ctx buckets up to (and including) the
    smallest bucket covering ``chunk_tokens`` — chunk dispatches then only
    ever run at already-compiled ctx-bucket widths, never a fresh shape.
    ``None`` keeps the full ladder (chunk = largest ctx bucket, the
    monolithic-equivalent default)."""
    if chunk_tokens is None:
        return list(ctx_buckets)
    cap = get_target_bucket(ctx_buckets, min(chunk_tokens, ctx_buckets[-1]))
    return [b for b in ctx_buckets if b <= cap]


def spec_width_buckets(max_width: int) -> List[int]:
    """Verify-width ladder for speculative serving dispatches
    (serving/speculation/): per-row candidate widths (accepted-token root
    + drafts, clamped by seq_len headroom and token budgets) pad to the
    smallest bucket, so the k+1-wide verify graph and its matching draft
    loop only ever compile a bounded set of shapes. ``max_width`` =
    speculation k + 1; the ladder always starts at 1 (a fully clamped
    batch degenerates to an eager decode step through the same graph)."""
    if max_width < 1:
        raise ValueError(f"spec width must be >= 1, got {max_width}")
    return generate_buckets(1, max_width)


def block_table_buckets(tpu_config, max_blocks: int) -> List[int]:
    """Paged-app block-table width ladder (reference: 2-D prefix x prefill
    buckets, autobucketing.py:22-64 + selection model_wrapper.py:923-1045):
    each paged call sizes its table to the smallest bucket covering the
    live blocks instead of always max_blocks — the attention gather /
    ragged kernel grid shrink with it."""
    if not (tpu_config.enable_bucketing and tpu_config.enable_2d_bucketing):
        return [max_blocks]
    return generate_buckets(1, max_blocks)


