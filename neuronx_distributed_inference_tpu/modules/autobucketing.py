"""Bucket ladder generation (reference: modules/autobucketing.py).

Buckets are the static shapes we AOT-compile; the host pads each request to
the smallest bucket that fits (reference: generate_buckets :8-20 — powers of
two between min and max)."""

from __future__ import annotations

from typing import List, Optional

from ..telemetry import get_registry
from ..telemetry.metrics import bucket_selected_counter


def generate_buckets(min_len: int, max_len: int) -> List[int]:
    """Powers-of-2 ladder from min to max, always including max
    (reference: autobucketing.py:8-20)."""
    if min_len >= max_len:
        return [max_len]
    buckets = []
    b = max(min_len, 1)
    # round min up to a power of two
    while b & (b - 1):
        b += b & -b
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


def context_encoding_buckets(tpu_config) -> List[int]:
    """Prefill bucket ladder (reference: autobucketing.py:149)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.max_context_length]
    if tpu_config.context_encoding_buckets:
        return sorted(tpu_config.context_encoding_buckets)
    return generate_buckets(128, tpu_config.max_context_length)


def token_generation_buckets(tpu_config) -> List[int]:
    """Decode-side bucket ladder over total sequence length
    (reference: autobucketing.py:226). The decode graph compiled for bucket
    ``b`` READS only cache slots [0, b) — early decode streams a fraction of
    the allocated cache (the decode step is HBM-bound, so this is a direct
    throughput win; the reference's TKG seq buckets serve the same role)."""
    if not tpu_config.enable_bucketing:
        return [tpu_config.seq_len]
    if tpu_config.token_generation_buckets:
        return sorted(tpu_config.token_generation_buckets)
    return generate_buckets(128, tpu_config.seq_len)


def get_target_bucket(buckets: List[int], length: int,
                      kind: Optional[str] = None) -> int:
    """Smallest bucket >= length (reference: model_wrapper.py:831-921).

    ``kind`` tags the selection for telemetry ("ctx"/"tkg"/"batch"/
    "block_table"); host-side only, a no-op while telemetry is disabled."""
    for b in buckets:
        if b >= length:
            if kind is not None:
                reg = get_registry()
                if reg.enabled:
                    bucket_selected_counter(reg).inc(kind=kind, bucket=str(b))
            return b
    raise ValueError(f"length {length} exceeds largest bucket {buckets[-1]}")


def batch_buckets(tpu_config) -> List[int]:
    """TKG batch-bucket ladder (reference: 2-D batch x seq TKG buckets,
    autobucketing.py:203): with 2-D bucketing a short batch pads to the
    smallest BATCH bucket instead of the full compiled batch — fewer pad
    rows, at the cost of extra compiled graphs. 1-D mode keeps the single
    full-batch bucket."""
    full = tpu_config.batch_size
    if not (tpu_config.enable_bucketing and tpu_config.enable_2d_bucketing):
        return [full]
    if tpu_config.tkg_batch_buckets:
        out = sorted(set(tpu_config.tkg_batch_buckets))
        if out[-1] != full:
            raise ValueError("tkg_batch_buckets must end at batch_size")
        return out
    return generate_buckets(1, full)


def ragged_row_buckets(ctx_buckets: List[int],
                       chunk_tokens: Optional[int] = None) -> List[int]:
    """THE unified per-row width ladder of the ragged mixed dispatch
    (serving/ragged/, README "Ragged dispatch"): one ladder covers every
    row shape a ``paged_ragged_step`` dispatch can carry — decode steps
    (width 1), speculative verify windows (width k+1) and prefill chunks
    (width up to the chunk cap) — so mixed load warms ONE set of shapes
    instead of the three separate ctx / prefill-chunk / spec-width
    ladders it used to pay.

    The ladder is the powers-of-2 ramp from 1 up to the smallest ctx
    bucket, merged with the ctx buckets themselves (so chunk dispatches
    keep running at already-compiled ctx-bucket widths), capped at the
    smallest ctx bucket covering ``chunk_tokens`` (``None`` = the full
    ctx ladder)."""
    if not ctx_buckets:
        raise ValueError("ragged_row_buckets needs a non-empty ctx ladder")
    if chunk_tokens is None:
        cap = ctx_buckets[-1]
    else:
        cap = get_target_bucket(ctx_buckets,
                                min(chunk_tokens, ctx_buckets[-1]))
    low = generate_buckets(1, ctx_buckets[0])
    return sorted({b for b in low if b <= cap}
                  | {b for b in ctx_buckets if b <= cap})


def prefill_chunk_buckets(ctx_buckets: List[int],
                          chunk_tokens: Optional[int] = None) -> List[int]:
    """DEPRECATED — thin wrapper over :func:`ragged_row_buckets`, kept so
    external callers and existing tests keep working: the old standalone
    prefill-chunk width ladder is the ctx-bucket slice of the unified
    ragged ladder (chunk dispatches only ever ran at already-compiled
    ctx-bucket widths). New code should consume ``ragged_row_buckets``
    directly — the ragged dispatch pads prefill rows, decode rows and
    verify windows to the SAME ladder."""
    ctx = set(ctx_buckets)
    return [b for b in ragged_row_buckets(ctx_buckets, chunk_tokens)
            if b in ctx]


def spec_width_buckets(max_width: int) -> List[int]:
    """DEPRECATED — thin wrapper over :func:`ragged_row_buckets`, kept so
    external callers and existing tests keep working: the old standalone
    verify-width ladder is the unified ragged ladder of a one-bucket
    "ctx" ladder at ``max_width`` (= speculation k + 1; always starts at
    1, so a fully clamped batch degenerates to an eager-width verify).
    New code should consume ``ragged_row_buckets`` directly."""
    if max_width < 1:
        raise ValueError(f"spec width must be >= 1, got {max_width}")
    return ragged_row_buckets([max_width])


def block_table_buckets(tpu_config, max_blocks: int) -> List[int]:
    """Paged-app block-table width ladder (reference: 2-D prefix x prefill
    buckets, autobucketing.py:22-64 + selection model_wrapper.py:923-1045):
    each paged call sizes its table to the smallest bucket covering the
    live blocks instead of always max_blocks — the attention gather /
    ragged kernel grid shrink with it."""
    if not (tpu_config.enable_bucketing and tpu_config.enable_2d_bucketing):
        return [max_blocks]
    return generate_buckets(1, max_blocks)


