"""Low-rank (SVD-compressed) MLP factors — the NeuronMLP decode lever
(arxiv 2510.25977: SVD-compressed tiled MLPs for memory-bound decode).

Decode is HBM-bandwidth-bound: every step streams the full gate/up/down
weights for one token's worth of FLOPs. Factorizing a (K, N) projection
into rank-r factors U (K, r), V (r, N) cuts the streamed bytes AND the
matmul FLOPs by r*(K+N)/(K*N) — at rank 1/4·min(K, N) that is roughly a
2x reduction, measured here as a graph-report bytes/flops delta before
any hardware does (ROADMAP item 5).

Design mirrors ``modules/quantization.py``: a *pytree transform* run
host-side before ``device_put``. A factorized weight is a dict
leaf-group

    {"lr_u": (..., K, r), "lr_v": (..., r, N)}

consumed in-graph by :func:`~.quantization.qlinear` (two skinny matmuls
through the rank-r bottleneck). Each factor may itself be a quantized
leaf-group — low-rank composes with the blockwise int8/fp8 stack by
factorizing FIRST (SVD needs the fp weight) and quantizing the factors:
``sqrt(singular value)`` is split across U and V so both factors see
balanced dynamic ranges.

Sharding (``quantization.quantized_shardings``): lr_u keeps the dense
weight's contraction-axis sharding (rank dim replicated), lr_v keeps the
out-axis sharding (rank dim replicated) — so a column-parallel gate/up
shards V, the row-parallel down shards U, and down's tp all-reduce lands
on the tiny rank-r intermediate instead of the hidden dim (a ~H/r
smaller wire; see ``model_base._row_parallel_out``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .quantization import (BLOCKWISE, MXFP4, PER_CHANNEL, QuantSpec,
                           dequantize, is_quantized_leaf, quantize_tensor)

# projections eligible for factorization: the MLP family only — attention
# projections are small relative to the MLP and rank-sensitive (the
# reference NeuronMLP compresses the MLP tiles only). Plain (non-GLU)
# stacks route fc1/fc2 through the gate_proj/down_proj slots, so the
# same tuple covers them.
DEFAULT_LOW_RANK_MODULES = ("gate_proj", "up_proj", "down_proj")


@dataclass(frozen=True)
class LowRankSpec:
    """Static low-rank description (hashable; closed over by jit).

    rank: the factor rank r; modules: weight names to factorize."""

    rank: int
    modules: Tuple[str, ...] = DEFAULT_LOW_RANK_MODULES

    def converts(self, name: str) -> bool:
        return name in self.modules


def low_rank_spec_from_config(tpu_config) -> Optional[LowRankSpec]:
    """Resolve a LowRankSpec from the ``TpuConfig.mlp_low_rank`` knob."""
    rank = getattr(tpu_config, "mlp_low_rank", None)
    if not rank:
        return None
    return LowRankSpec(rank=int(rank))


def is_low_rank_leaf(w: Any) -> bool:
    return isinstance(w, dict) and "lr_u" in w


# ---------------------------------------------------------------------------
# host-side factorization (numpy) — run before device_put, like
# quantization.quantize_params
# ---------------------------------------------------------------------------

def factorize_tensor(w: np.ndarray, rank: int) -> Dict[str, np.ndarray]:
    """SVD-factorize one weight (..., K, N) into the best (Eckart–Young)
    rank-``rank`` pair. Leading dims (layer stack L) batch through
    numpy's batched SVD. ``sqrt(singular value)`` lands on both factors
    so their dynamic ranges stay balanced for factor quantization."""
    w = np.asarray(w)
    dt = w.dtype
    wf = w.astype(np.float32)
    r = min(int(rank), min(wf.shape[-2], wf.shape[-1]))
    u, s, vh = np.linalg.svd(wf, full_matrices=False)
    root = np.sqrt(s[..., :r])
    lr_u = (u[..., :, :r] * root[..., None, :]).astype(dt)
    lr_v = (root[..., :, None] * vh[..., :r, :]).astype(dt)
    return {"lr_u": lr_u, "lr_v": lr_v}


def _quantize_factor(factor: np.ndarray, qspec: QuantSpec) -> Any:
    """Quantize one factor, degrading the scheme where the rank-r
    contraction dim can't satisfy it: blockwise falls back to
    per-channel when r doesn't divide into groups, and mxfp4 (whose
    packing needs the group structure) leaves the factor in full
    precision rather than mis-packing it."""
    K = factor.shape[-2]
    if qspec.dtype == MXFP4:
        if K % qspec.group_size:
            return factor
        return quantize_tensor(factor, qspec)
    if qspec.scheme == BLOCKWISE and K % qspec.group_size:
        qspec = dataclasses.replace(qspec, scheme=PER_CHANNEL)
    return quantize_tensor(factor, qspec)


def factorize_params(params: Dict[str, Any], spec: LowRankSpec,
                     quant: Optional[QuantSpec] = None) -> Dict[str, Any]:
    """Transform a param tree: replace eligible MLP weights with
    {"lr_u", "lr_v"} leaf-groups. When ``quant`` also targets the
    weight, each factor is quantized in place — run this BEFORE
    ``quantize_params`` (the SVD needs the fp weight; already-factorized
    leaves carry no convertible names, so the later quantize walk leaves
    them alone)."""

    def convert(tree):
        out = {}
        for name, v in tree.items():
            if isinstance(v, dict) and not is_low_rank_leaf(v) \
                    and not is_quantized_leaf(v):
                out[name] = convert(v)
            elif spec.converts(name) and not isinstance(v, dict):
                leaf = factorize_tensor(np.asarray(v), spec.rank)
                if quant is not None and quant.converts(name):
                    leaf = {k: _quantize_factor(f, quant)
                            for k, f in leaf.items()}
                out[name] = leaf
            else:
                out[name] = v
        return out

    return convert(params)


# ---------------------------------------------------------------------------
# accuracy pin + bytes/flops accounting (the observatory's pre-hardware
# yardsticks)
# ---------------------------------------------------------------------------

def _factor_dense(factor: Any) -> np.ndarray:
    if is_quantized_leaf(factor):
        return np.asarray(dequantize(factor, np.float32))
    return np.asarray(factor, dtype=np.float32)


def reconstruction_error(w: np.ndarray, leaf: Dict[str, Any]) -> float:
    """Relative Frobenius error ||W - U·V|| / ||W|| of one factorized
    (possibly factor-quantized) leaf against the dense weight — the pin
    tests hold at the chosen rank."""
    wf = np.asarray(w, dtype=np.float32)
    approx = _factor_dense(leaf["lr_u"]) @ _factor_dense(leaf["lr_v"])
    denom = float(np.linalg.norm(wf))
    return float(np.linalg.norm(wf - approx)) / max(denom, 1e-30)


def compression_report(hidden_size: int, intermediate_size: int,
                       num_layers: int, rank: int, glu: bool = True,
                       bytes_per_param: float = 4.0) -> Dict[str, Any]:
    """Analytic decode bytes/flops delta of the low-rank MLP: dense
    gate/up/down stream L·n_proj·H·I params per token (decode reads
    every weight once; flops = 2·params), the factor pairs stream
    L·n_proj·r·(H+I). The ratio is the projected HBM-bandwidth win the
    graph report carries until hardware measures it."""
    h, i, r = hidden_size, intermediate_size, int(rank)
    n_proj = 3 if glu else 2
    dense_params = num_layers * n_proj * h * i
    lr_params = num_layers * n_proj * r * (h + i)
    ratio = lr_params / dense_params
    return {
        "rank": r,
        "mlp_projections": num_layers * n_proj,
        "dense_mlp_bytes": int(dense_params * bytes_per_param),
        "low_rank_mlp_bytes": int(lr_params * bytes_per_param),
        "dense_mlp_flops_per_token": 2 * dense_params,
        "low_rank_mlp_flops_per_token": 2 * lr_params,
        "bytes_ratio": round(ratio, 4),
        "flops_ratio": round(ratio, 4),
        "projected_decode_mlp_speedup": round(1.0 / max(ratio, 1e-9), 2),
    }
