"""KV cache management (reference: modules/kvcache/kv_cache_manager.py).

TPU-native design: the cache is a pytree of two stacked arrays
  k, v : (num_layers, batch, num_kv_heads, max_seq, head_dim)
sharded P(None, "dp", "tp", None, None) and **donated** into every jitted
step — ``jax.jit(..., donate_argnums)`` is the direct analog of the
reference's input/output aliasing (reference: models/model_wrapper.py:1578-1627,
noted in SURVEY §1).

Layout rationale: HEAD-LEADING — (seq, head_dim) are the minor (tiled) dims:
head_dim on the 128-lane axis, seq on the sublane axis, heads a leading dim.
This is the layout Pallas kernels want (ops/decode_attention.py streams
per-head (block_s, head_dim) blocks with legal BlockSpecs and no in-kernel
relayout; a head-minor layout would make every per-head slice a cross-tile
sublane gather). The reference's 128-tiling of S for cascaded reductions
(kv_cache_manager.py:29-80) is unnecessary here; XLA handles reduction
tiling, and :func:`read_layer` hands the XLA path a (B, S, H, D) view whose
transpose fuses into the attention einsum.

Supported behaviors mirrored from the reference:
  * CTE write  = batch-row scatter at seq_ids (continuous batching single-seq
    update, kv_cache_manager.py:483-497)
  * TKG write  = scatter at (seq_ids, position_ids) (:431-586)
  * sliding-window rolling write pos % window (:605-606) — wired through
    the model base for uniform-window models (spec.rolling_window): the
    cache holds w slots, decode uses attention.rolling_decode_mask (the
    position-mapping mask), prefill writes only each row's last w positions
  * per-layer cache sizes for mixed local/global attention (gpt-oss manager)
  * fp8 KV quantization, direct-cast mode (:636-692)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_DP, AXIS_MP


@dataclass(frozen=True)
class KVCacheSpec:
    num_layers: int
    batch_size: int
    max_seq_len: int
    num_kv_heads: int     # padded/replicated per GQASharding
    head_dim: int         # K head dim (MLA: qk_nope + qk_rope)
    dtype: jnp.dtype = jnp.bfloat16
    window: int = 0       # >0: rolling sliding-window cache of this length
    v_head_dim: Optional[int] = None   # MLA: v dim != k dim (deepseek)

    @property
    def cache_len(self) -> int:
        return min(self.max_seq_len, self.window) if self.window > 0 else self.max_seq_len

    @property
    def v_dim(self) -> int:
        return self.v_head_dim if self.v_head_dim is not None else self.head_dim

    @property
    def k_shape(self) -> Tuple[int, ...]:
        # K stored TRANSPOSED (L, B, H, D, S): the decode score matmul
        # contracts D with S free, so S lands on the lane axis naturally;
        # V keeps (L, B, H, S, D) for the value matmul (contract S, D on
        # lanes). One layout per consumer = no per-layer relayout copies
        # (the reference ships the same transposed-K option,
        # models/config.py:395-415 "KV tiling + transposed-K").
        return (self.num_layers, self.batch_size, self.num_kv_heads,
                self.head_dim, self.cache_len)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.k_shape

    @property
    def v_shape(self) -> Tuple[int, ...]:
        return (self.num_layers, self.batch_size, self.num_kv_heads,
                self.cache_len, self.v_dim)


def cache_len_of(cache) -> int:
    """Cache sequence capacity from the stacked cache pytree (V layout
    (L, B, H, S, D))."""
    return cache["v"].shape[3]


def k_pspec(flash_decoding: bool = False) -> P:
    """Transposed-K layout (L, B, H, D, S). Flash decoding shards S over
    the "cp" axis — the decode-time sequence sharding of the reference
    (modules/flashdecode/utils.py)."""
    from ..parallel.mesh import AXIS_CP
    return P(None, AXIS_DP, AXIS_MP, None, AXIS_CP if flash_decoding else None)


def v_pspec(flash_decoding: bool = False) -> P:
    from ..parallel.mesh import AXIS_CP
    return P(None, AXIS_DP, AXIS_MP, AXIS_CP if flash_decoding else None, None)


def cache_pspec(flash_decoding: bool = False):
    """Per-leaf cache PartitionSpecs keyed like the cache pytree."""
    return {"k": k_pspec(flash_decoding), "v": v_pspec(flash_decoding)}


def init_cache(spec: KVCacheSpec, mesh: Optional[Mesh] = None,
               flash_decoding: bool = False):
    """Zero-initialized {'k','v'} cache, device-placed with the cache sharding."""
    def zeros(shape, pspec):
        x = jnp.zeros(shape, spec.dtype)
        if mesh is not None:
            x = jax.device_put(x, NamedSharding(mesh, pspec))
        return x

    return {"k": zeros(spec.k_shape, k_pspec(flash_decoding)),
            "v": zeros(spec.v_shape, v_pspec(flash_decoding))}


def init_mixed_cache(spec: KVCacheSpec, layer_pattern, window: int,
                     mesh: Optional[Mesh] = None):
    """MIXED per-layer cache sizes (reference: gpt-oss per-layer KV,
    modules/kvcache/gpt_oss_kv_cache_manager.py): global layers get
    full-seq rows in {"k","v"}; LOCAL layers (layer_pattern[i] True) get
    ROLLING window-sized rows in {"k_l","v_l"} — decode KV bytes scale
    with W on local layers instead of seq_len."""
    import dataclasses
    n_local = sum(bool(x) for x in layer_pattern)
    n_global = spec.num_layers - n_local
    g_spec = dataclasses.replace(spec, num_layers=max(n_global, 1))
    l_spec = dataclasses.replace(spec, num_layers=max(n_local, 1),
                                 window=window)
    cache = init_cache(g_spec, mesh)
    local = init_cache(l_spec, mesh)
    cache["k_l"] = local["k"]
    cache["v_l"] = local["v"]
    return cache


def mixed_layer_map(layer_pattern):
    """Absolute layer index -> index within its own (local/global) stack."""
    idx = []
    n_l = n_g = 0
    for is_local in layer_pattern:
        if is_local:
            idx.append(n_l)
            n_l += 1
        else:
            idx.append(n_g)
            n_g += 1
    return idx


def fold_rolling_prefill(scratch: jnp.ndarray, seq_lens: jnp.ndarray,
                         window: int, k_transposed: bool = False
                         ) -> jnp.ndarray:
    """Convert a full-length prefill scratch cache (L', B, H, D, S)/(...,
    S, D) into the rolling layout (W slots, slot j holds the LATEST
    position p <= seq_len-1 with p % W == j; unwritten slots zero) —
    the mixed-cache prefill epilogue (reference: gpt-oss manager CTE
    write path)."""
    s_axis = 4 if k_transposed else 3
    last = seq_lens.astype(jnp.int32) - 1                       # (B,)
    j = jnp.arange(window, dtype=jnp.int32)                     # (W,)
    q = last[:, None] - ((last[:, None] - j[None, :]) % window)  # (B, W)
    valid = q >= 0
    qc = jnp.clip(q, 0, scratch.shape[s_axis] - 1)
    if k_transposed:
        idx = qc[None, :, None, None, :]                        # (1,B,1,1,W)
        gathered = jnp.take_along_axis(
            scratch, jnp.broadcast_to(
                idx, scratch.shape[:4] + (window,)), axis=4)
        return jnp.where(valid[None, :, None, None, :], gathered, 0)
    idx = qc[None, :, None, :, None]                            # (1,B,1,W,1)
    gathered = jnp.take_along_axis(
        scratch, jnp.broadcast_to(
            idx, scratch.shape[:3] + (window, scratch.shape[4])), axis=3)
    return jnp.where(valid[None, :, None, :, None], gathered, 0)


def quantize_kv(x: jnp.ndarray, dtype, scale: Optional[float] = None) -> jnp.ndarray:
    """KV quantization on write (reference: kv_cache_manager.py:636-692):
    direct-cast mode (scale=None) or scaled mode — store x/scale so the fp8
    dynamic range covers the KV distribution."""
    if scale is not None and scale != 1.0:
        x = x.astype(jnp.float32) / scale
    return x.astype(dtype)


def dequantize_kv(x: jnp.ndarray, dtype, scale: Optional[float] = None) -> jnp.ndarray:
    """Inverse of :func:`quantize_kv` on read."""
    if scale is not None and scale != 1.0:
        return (x.astype(jnp.float32) * scale).astype(dtype)
    return x.astype(dtype)


def write_prefill(cache_layer: jnp.ndarray, new: jnp.ndarray,
                  seq_ids: jnp.ndarray, start: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Write a full prefill window into cache rows ``seq_ids``.

    cache_layer (B, H, S, D) head-leading (one V layer; use
    ``k_transposed`` paths for K); new (b, s, H, D); seq_ids (b,). start:
    slot offset (chunked/windowed prefill writes at a running offset,
    reference: fill_prefix / dynamic_update_slice in kvcache/utils.py).
    """
    s = new.shape[1]
    pos = (jnp.arange(s, dtype=jnp.int32) + start)[None, :]        # (1, s)
    pos = jnp.broadcast_to(pos, (new.shape[0], s))
    return write_tokens(cache_layer, new, seq_ids, pos)


def write_tokens(cache_layer: jnp.ndarray, new: jnp.ndarray,
                 seq_ids: jnp.ndarray, positions: jnp.ndarray,
                 window: int = 0) -> jnp.ndarray:
    """Scatter active tokens into the cache (TKG write,
    reference: kv_cache_manager.py:431-586).

    cache_layer (B, H, S, D) head-leading (one V layer); new (b, t, H, D);
    seq_ids (b,); positions (b, t).
    window > 0 applies the rolling write positions % window
    (reference: :605-606 uses % (w-1) to keep one slot for the active token;
    here the active token lives in the same cache so plain modulo is correct).
    """
    return write_tokens_at_layer(cache_layer[None], new, 0, seq_ids,
                                 positions, window)[0]


def write_tokens_at_layer(cache: jnp.ndarray, new: jnp.ndarray, layer,
                          seq_ids: jnp.ndarray, positions: jnp.ndarray,
                          window: int = 0,
                          k_transposed: bool = False) -> jnp.ndarray:
    """In-place token write into the FULL stacked cache at ``layer`` (a
    traced scalar inside the layer scan). ``new`` stays in the projection
    layout (b, t, H, D); ``k_transposed`` writes into the transposed-K
    layout (L, B, H, D, S) instead of the V layout (L, B, H, S, D).
    Writing into the scan-carried full buffer — instead of rewriting a
    per-layer slice into stacked scan outputs — keeps the decode-step HBM
    traffic at read-cache + write-tokens rather than read-cache +
    write-cache (the donated carry makes the update in-place)."""
    if window > 0:
        positions = positions % window
    b, t, h, d = new.shape
    new = jnp.swapaxes(new.astype(cache.dtype), 1, 2)       # (b, H, t, D)
    li = jnp.asarray(layer, jnp.int32)
    s_max = cache.shape[4] if k_transposed else cache.shape[3]
    zero = jnp.zeros((), jnp.int32)
    if t == 1 and b <= 16:
        # decode hot path: per-row dynamic-update-slice instead of one
        # advanced-index scatter — the scatter op forces a layout on the
        # loop-carried cache that conflicts with the attention einsums,
        # costing a materialized relayout of the live cache per layer per
        # step (measured 0.31 -> 0.15 ms/step on v5e at B=2/S=1024).
        # Out-of-range drop semantics are kept by writing back the old
        # value (the tiny read-modify-write is free next to the DUS).
        for i in range(b):
            pos_i = positions[i, 0]
            pos_c = jnp.clip(pos_i, 0, s_max - 1)
            row = seq_ids[i].astype(jnp.int32)
            if k_transposed:
                start = (li, row, zero, zero, pos_c)
                upd = new[i].reshape(h, d)[None, None, :, :, None]
            else:
                start = (li, row, zero, pos_c, zero)
                upd = new[i][None, None, :, :, :]           # (1, 1, H, 1, D)
            old = jax.lax.dynamic_slice(cache, start, upd.shape)
            valid = jnp.logical_and(pos_i >= 0, pos_i < s_max)
            cache = jax.lax.dynamic_update_slice(
                cache, jnp.where(valid, upd, old), start)
        return cache
    hidx = jnp.arange(h, dtype=jnp.int32)
    if k_transposed:
        # advanced indices (b, H, t) around the sliced D dim: the advanced
        # block moves to the front, so the update is (b, H, t, D)
        return cache.at[li, seq_ids[:, None, None], hidx[None, :, None], :,
                        positions[:, None, :]].set(
            new, mode="drop", unique_indices=False)
    return cache.at[li, seq_ids[:, None, None], hidx[None, :, None],
                    positions[:, None, :]].set(
        new, mode="drop", unique_indices=False)


def commit_chunk(cache: jnp.ndarray, side: jnp.ndarray,
                 seq_ids: jnp.ndarray, start_positions: jnp.ndarray,
                 k_transposed: bool = False) -> jnp.ndarray:
    """Commit a decode chunk's side buffer into the big cache — ONE bulk
    write per row per chunk instead of a per-layer write per step (see
    ``ops.attention.mha_decode_merged``; the reference's analog is the
    DMA-skipping batch write kernel, kvcache/utils.py
    ``write_kv_cache_at_batch_kernel``).

    cache (L, B, H, D, S) transposed-K or (L, B, H, S, D);
    side (L, b, H, D, C) / (L, b, H, C, D); start_positions (b,) — row i's
    chunk covers positions [start, start+C). A row whose chunk would
    straddle the cache end keeps its OLD values for the whole chunk
    (unlike write_tokens_at_layer's per-token drop): callers must size
    chunks so start+C <= S — the application's bucket selection
    (application.py _kv_bucket over position+num_steps) guarantees this.
    """
    C = side.shape[4] if k_transposed else side.shape[3]
    s_max = cache.shape[4] if k_transposed else cache.shape[3]
    b = side.shape[1]
    zero = jnp.zeros((), jnp.int32)
    for i in range(b):
        start_i = jnp.clip(start_positions[i], 0, s_max - C)
        row = seq_ids[i].astype(jnp.int32)
        upd = side[:, i][:, None].astype(cache.dtype)   # (L,1,H,D,C)/(L,1,H,C,D)
        if k_transposed:
            start = (zero, row, zero, zero, start_i)
        else:
            start = (zero, row, zero, start_i, zero)
        valid = jnp.logical_and(start_positions[i] >= 0,
                                start_positions[i] <= s_max - C)
        old = jax.lax.dynamic_slice(cache, start, upd.shape)
        cache = jax.lax.dynamic_update_slice(
            cache, jnp.where(valid, upd, old), start)
    return cache


def write_prefill_at_layer(cache: jnp.ndarray, new: jnp.ndarray, layer,
                           seq_ids: jnp.ndarray,
                           start: jnp.ndarray | int = 0,
                           identity_seq_ids: bool = False,
                           k_transposed: bool = False) -> jnp.ndarray:
    """Stacked-cache prefill write: the window goes to slots [start,
    start+s) of rows ``seq_ids`` (start > 0 = chunked/windowed prefill at a
    running offset). identity_seq_ids=True (static guarantee that seq_ids
    == arange over the full cache batch) takes the dynamic-update-slice
    fast path — one contiguous block write instead of a b*H*s-row scatter."""
    b, s, h, _ = new.shape
    li = jnp.asarray(layer, jnp.int32)
    if identity_seq_ids and b == cache.shape[1]:
        if k_transposed:
            new_t = jnp.transpose(new.astype(cache.dtype), (0, 2, 3, 1))
            return jax.lax.dynamic_update_slice(
                cache, new_t[None],
                (li, 0, 0, 0, jnp.asarray(start, jnp.int32)))
        new_t = jnp.swapaxes(new.astype(cache.dtype), 1, 2)   # (b, H, s, D)
        return jax.lax.dynamic_update_slice(
            cache, new_t[None],
            (li, 0, 0, jnp.asarray(start, jnp.int32), 0))
    pos = (jnp.arange(s, dtype=jnp.int32) + start)[None, :]
    pos = jnp.broadcast_to(pos, (b, s))
    return write_tokens_at_layer(cache, new, layer, seq_ids, pos,
                                 k_transposed=k_transposed)


def read_layer_hl(cache: jnp.ndarray, layer) -> jnp.ndarray:
    """Dynamic-slice one layer out of the stacked (L, B, H, S, D) cache in
    its native head-leading layout — pair with ``attention.mha_hl`` so the
    cache is contracted in place (no transposed copy)."""
    return jax.lax.dynamic_index_in_dim(
        cache, jnp.asarray(layer, jnp.int32), 0, keepdims=False)


def read_layer(cache: jnp.ndarray, layer) -> jnp.ndarray:
    """Dynamic-slice one layer out of the stacked (L, B, H, S, D) cache and
    hand it back as (B, S, H, D) — the projection-layout view. NOTE: XLA
    materializes the swapaxes as a transposed copy of the layer when the
    consumer is an einsum over a scatter-updated buffer — the decode hot
    path uses :func:`read_layer_hl` + ``mha_hl`` instead."""
    return jnp.swapaxes(read_layer_hl(cache, layer), 1, 2)


def gather_cache_rows(cache_layer: jnp.ndarray, seq_ids: jnp.ndarray) -> jnp.ndarray:
    """Select the batch rows for the running requests (continuous batching:
    compiled batch may be a subset/permutation of cache lines)."""
    return cache_layer[seq_ids]


class KVCacheManager:
    """Thin stateful wrapper holding the spec + cache pytree.

    The traced model functions use the pure functions above; this class is the
    host-side owner used by the application layer (mirrors the role of
    reference KVCacheManager without being traced itself).
    """

    def __init__(self, spec: KVCacheSpec, mesh: Optional[Mesh] = None):
        self.spec = spec
        self.mesh = mesh
        self.cache = init_cache(spec, mesh)

    def reset(self):
        self.cache = jax.tree.map(lambda x: jnp.zeros_like(x), self.cache)

    @property
    def nbytes(self) -> int:
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(self.cache))
