"""KV cache management (reference: modules/kvcache/kv_cache_manager.py).

TPU-native design: the cache is a pytree of two stacked arrays
  k, v : (num_layers, batch, max_seq, num_kv_heads, head_dim)
sharded P(None, "dp", None, "tp", None) and **donated** into every jitted
step — ``jax.jit(..., donate_argnums)`` is the direct analog of the
reference's input/output aliasing (reference: models/model_wrapper.py:1578-1627,
noted in SURVEY §1).

Layout rationale: head_dim last (128-lane axis), seq in the sublane-tiled
position — the reference's 128-tiling of S for cascaded reductions
(kv_cache_manager.py:29-80) is unnecessary here; XLA handles reduction tiling.

Supported behaviors mirrored from the reference:
  * CTE write  = batch-row scatter at seq_ids (continuous batching single-seq
    update, kv_cache_manager.py:483-497)
  * TKG write  = scatter at (seq_ids, position_ids) (:431-586)
  * sliding-window rolling write pos % window (:605-606) — NOTE: rolling
    cache is not yet wired into the model base (sliding-window families
    currently use a full-length cache + window mask, which is correct but
    not memory-minimal; decode_mask assumes slot i holds position i, so
    wiring the rolling layout needs a position-mapping mask too)
  * per-layer cache sizes for mixed local/global attention (gpt-oss manager)
  * fp8 KV quantization, direct-cast mode (:636-692)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_DP, AXIS_MP


@dataclass(frozen=True)
class KVCacheSpec:
    num_layers: int
    batch_size: int
    max_seq_len: int
    num_kv_heads: int     # padded/replicated per GQASharding
    head_dim: int         # K head dim (MLA: qk_nope + qk_rope)
    dtype: jnp.dtype = jnp.bfloat16
    window: int = 0       # >0: rolling sliding-window cache of this length
    v_head_dim: Optional[int] = None   # MLA: v dim != k dim (deepseek)

    @property
    def cache_len(self) -> int:
        return min(self.max_seq_len, self.window) if self.window > 0 else self.max_seq_len

    @property
    def v_dim(self) -> int:
        return self.v_head_dim if self.v_head_dim is not None else self.head_dim

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.num_layers, self.batch_size, self.cache_len,
                self.num_kv_heads, self.head_dim)

    @property
    def v_shape(self) -> Tuple[int, ...]:
        return self.shape[:-1] + (self.v_dim,)


def cache_pspec(flash_decoding: bool = False) -> P:
    """Cache layout (L, B, S, H, D). Flash decoding shards S over the "cp"
    axis — the decode-time sequence sharding of the reference
    (modules/flashdecode/utils.py): each cp rank holds a slice of every
    sequence's KV; GSPMD turns the decode softmax into the distributed
    max/sum + psum pattern automatically."""
    from ..parallel.mesh import AXIS_CP
    return P(None, AXIS_DP, AXIS_CP if flash_decoding else None, AXIS_MP, None)


def init_cache(spec: KVCacheSpec, mesh: Optional[Mesh] = None,
               flash_decoding: bool = False):
    """Zero-initialized {'k','v'} cache, device-placed with the cache sharding."""
    def zeros(shape):
        x = jnp.zeros(shape, spec.dtype)
        if mesh is not None:
            x = jax.device_put(x, NamedSharding(mesh, cache_pspec(flash_decoding)))
        return x

    return {"k": zeros(spec.shape), "v": zeros(spec.v_shape)}


def quantize_kv(x: jnp.ndarray, dtype, scale: Optional[float] = None) -> jnp.ndarray:
    """KV quantization on write (reference: kv_cache_manager.py:636-692):
    direct-cast mode (scale=None) or scaled mode — store x/scale so the fp8
    dynamic range covers the KV distribution."""
    if scale is not None and scale != 1.0:
        x = x.astype(jnp.float32) / scale
    return x.astype(dtype)


def dequantize_kv(x: jnp.ndarray, dtype, scale: Optional[float] = None) -> jnp.ndarray:
    """Inverse of :func:`quantize_kv` on read."""
    if scale is not None and scale != 1.0:
        return (x.astype(jnp.float32) * scale).astype(dtype)
    return x.astype(dtype)


def write_prefill(cache_layer: jnp.ndarray, new: jnp.ndarray,
                  seq_ids: jnp.ndarray, start: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Write a full prefill window into cache rows ``seq_ids``.

    cache_layer (B, S, H, D); new (b, s, H, D); seq_ids (b,). start: slot
    offset (chunked/windowed prefill writes at a running offset,
    reference: fill_prefix / dynamic_update_slice in kvcache/utils.py).
    """
    s = new.shape[1]
    pos = (jnp.arange(s, dtype=jnp.int32) + start)[None, :]        # (1, s)
    pos = jnp.broadcast_to(pos, (new.shape[0], s))
    return write_tokens(cache_layer, new, seq_ids, pos)


def write_tokens(cache_layer: jnp.ndarray, new: jnp.ndarray,
                 seq_ids: jnp.ndarray, positions: jnp.ndarray,
                 window: int = 0) -> jnp.ndarray:
    """Scatter active tokens into the cache (TKG write,
    reference: kv_cache_manager.py:431-586).

    cache_layer (B, S, H, D); new (b, t, H, D); seq_ids (b,); positions (b, t).
    window > 0 applies the rolling write positions % window
    (reference: :605-606 uses % (w-1) to keep one slot for the active token;
    here the active token lives in the same cache so plain modulo is correct).
    """
    return write_tokens_at_layer(cache_layer[None], new, 0, seq_ids,
                                 positions, window)[0]


def write_tokens_at_layer(cache: jnp.ndarray, new: jnp.ndarray, layer,
                          seq_ids: jnp.ndarray, positions: jnp.ndarray,
                          window: int = 0) -> jnp.ndarray:
    """In-place token write into the FULL stacked cache (L, B, S, H, D) at
    ``layer`` (a traced scalar inside the layer scan). Scattering into the
    scan-carried full buffer — instead of rewriting a per-layer slice into
    stacked scan outputs — keeps the decode-step HBM traffic at
    read-cache + write-tokens rather than read-cache + write-cache
    (the donated carry makes the scatter in-place)."""
    if window > 0:
        positions = positions % window
    new = new.astype(cache.dtype)
    li = jnp.asarray(layer, jnp.int32)
    return cache.at[li, seq_ids[:, None], positions].set(
        new, mode="drop", unique_indices=False)


def write_prefill_at_layer(cache: jnp.ndarray, new: jnp.ndarray, layer,
                           seq_ids: jnp.ndarray,
                           start: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Stacked-cache prefill write: the window goes to slots [start,
    start+s) of rows ``seq_ids`` (start > 0 = chunked/windowed prefill at a
    running offset)."""
    s = new.shape[1]
    pos = (jnp.arange(s, dtype=jnp.int32) + start)[None, :]
    pos = jnp.broadcast_to(pos, (new.shape[0], s))
    return write_tokens_at_layer(cache, new, layer, seq_ids, pos)


def read_layer(cache: jnp.ndarray, layer) -> jnp.ndarray:
    """Dynamic-slice one layer (B, S, H, D) out of the stacked cache."""
    return jax.lax.dynamic_index_in_dim(cache, jnp.asarray(layer, jnp.int32),
                                        0, keepdims=False)


def gather_cache_rows(cache_layer: jnp.ndarray, seq_ids: jnp.ndarray) -> jnp.ndarray:
    """Select the batch rows for the running requests (continuous batching:
    compiled batch may be a subset/permutation of cache lines)."""
    return cache_layer[seq_ids]


class KVCacheManager:
    """Thin stateful wrapper holding the spec + cache pytree.

    The traced model functions use the pure functions above; this class is the
    host-side owner used by the application layer (mirrors the role of
    reference KVCacheManager without being traced itself).
    """

    def __init__(self, spec: KVCacheSpec, mesh: Optional[Mesh] = None):
        self.spec = spec
        self.mesh = mesh
        self.cache = init_cache(spec, mesh)

    def reset(self):
        self.cache = jax.tree.map(lambda x: jnp.zeros_like(x), self.cache)

    @property
    def nbytes(self) -> int:
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(self.cache))
