"""Weight quantization — TPU-native replacement for the reference's NxD
quantization stack (reference: models/config.py:216-241 quantization knobs,
model_wrapper.py:1477-1528 qconfig synthesis, application_base.py:746-799
quantize-and-save; SURVEY §5 "quantization matrix": int8 per-tensor /
per-channel, fp8 weights, fp8 KV direct-cast + scaled, MXFP4 compute).

Design: weight-only quantization represented as a *pytree transform*. A
quantized weight is a dict leaf-group

    {"qweight": int8/fp8/uint8-packed, "scale": fp32[, "qscheme": meta]}

produced host-side by :func:`quantize_params` (or loaded from a quantized
checkpoint) and consumed inside the traced graph by :func:`qlinear` /
:func:`dequantize`. Dequantization is expressed so XLA fuses it into the
consuming matmul:

  * per-channel / per-tensor int8 and fp8: scale factors out of the
    contraction — compute ``(x @ q) * scale_out`` so the MXU sees an
    int8→bf16 cast, never a materialized fp copy of the weight.
  * MXFP4 (group-wise scales along the contraction dim): dequantize the
    weight tile then matmul; packing is 2 fp4 values per uint8 with one
    e8m0 scale per ``group_size`` input channels (OCP MX spec layout, as
    used by gpt-oss checkpoints).

The scheme strings intentionally match the reference's
``quantization_type`` values (models/config.py:229): "per_tensor_symmetric",
"per_channel_symmetric"; plus "fp8" and "mxfp4".
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT8 = "int8"
FP8 = "fp8"
MXFP4 = "mxfp4"

PER_TENSOR = "per_tensor_symmetric"
PER_CHANNEL = "per_channel_symmetric"
# row-blockwise: one scale per ``group_size`` contraction channels per output
# channel (reference: the blockwise qconfigs of model_wrapper.py:1477-1528)
BLOCKWISE = "blockwise_symmetric"

# weights eligible for quantization inside a decoder layer stack; the
# reference's modules_to_not_convert (models/config.py:233) subtracts from
# this set. Router weights stay fp32 always (routing decisions are
# precision-sensitive — same choice the reference makes).
DEFAULT_QUANT_MODULES = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
    "expert_gate", "expert_up", "expert_down",
    "shared_gate", "shared_up", "shared_down",
)

# e2m1 (fp4) value table per the OCP microscaling spec: sign x {0, .5, 1,
# 1.5, 2, 3, 4, 6}
_FP4_VALUES = np.array(
    [0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0,
     -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0], dtype=np.float32)


@dataclass(frozen=True)
class QuantSpec:
    """Static quantization description (hashable; closed over by jit).

    dtype: "int8" | "fp8" | "mxfp4"; scheme per reference quantization_type
    (per-tensor / per-channel / blockwise). group_size applies to mxfp4 AND
    the blockwise scheme (scale per group along the contraction dim).
    modules_to_not_convert: weight names left in full precision.
    """

    dtype: str = INT8
    scheme: str = PER_CHANNEL
    group_size: int = 32
    modules_to_not_convert: Tuple[str, ...] = ()

    def converts(self, name: str) -> bool:
        return (name in DEFAULT_QUANT_MODULES
                and name not in self.modules_to_not_convert)


def quant_spec_from_config(tpu_config) -> Optional[QuantSpec]:
    """Resolve a QuantSpec from TpuConfig knobs
    (reference: models/config.py:216-241)."""
    if not getattr(tpu_config, "quantized", False):
        return None
    dtype = tpu_config.quantization_dtype
    scheme = tpu_config.quantization_type
    if dtype in ("f8e4m3", "float8_e4m3fn"):
        dtype = FP8
    skip = tuple(tpu_config.modules_to_not_convert or ())
    return QuantSpec(dtype=dtype, scheme=scheme, modules_to_not_convert=skip)


def is_quantized_leaf(w: Any) -> bool:
    return isinstance(w, dict) and "qweight" in w


# ---------------------------------------------------------------------------
# host-side quantize (numpy) — reference: generate_quantized_state_dict
# (application_base.py:772-792)
# ---------------------------------------------------------------------------

def _absmax_scale(w: np.ndarray, axis, qmax: float) -> np.ndarray:
    amax = np.max(np.abs(w), axis=axis, keepdims=True)
    return np.maximum(amax, 1e-8).astype(np.float32) / qmax


def quantize_tensor(w: np.ndarray, spec: QuantSpec) -> Dict[str, np.ndarray]:
    """Quantize one weight (..., in, out). Contraction dim is axis -2 (the
    framework stores x@w layouts, family.py converts torch (out,in) on load).
    """
    w = np.asarray(w, dtype=np.float32)
    # leading dims (layer stack L, experts E) are never reduced: "per tensor"
    # means per (layer, expert) weight matrix, matching the reference's
    # per-module qconfigs (model_wrapper.py:1477-1528) — which also makes
    # per-tensor on stacked experts EXPERT-WISE scales for free
    if spec.scheme == BLOCKWISE and spec.dtype in (INT8, FP8):
        *lead, K, N = w.shape
        G = spec.group_size
        assert K % G == 0, (K, G)
        g = w.reshape(*lead, K // G, G, N)
        qmax = 127.0 if spec.dtype == INT8 else 448.0
        scale = _absmax_scale(g, (len(lead) + 1,), qmax)    # (...,K//G,1,N)
        scaled = g / scale
        if spec.dtype == INT8:
            q = np.clip(np.round(scaled), -127, 127).astype(np.int8)
        else:
            q = scaled.astype(jnp.float8_e4m3fn)
        return {"qweight": q.reshape(*lead, K, N),
                "scale": scale.reshape(*lead, K // G, N)}
    if spec.dtype == INT8:
        axis = ((w.ndim - 2, w.ndim - 1) if spec.scheme == PER_TENSOR
                else (w.ndim - 2,))
        scale = _absmax_scale(w, axis, 127.0)
        q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        return {"qweight": q, "scale": scale}
    if spec.dtype == FP8:
        axis = ((w.ndim - 2, w.ndim - 1) if spec.scheme == PER_TENSOR
                else (w.ndim - 2,))
        scale = _absmax_scale(w, axis, 448.0)   # e4m3 max normal
        q = (w / scale).astype(jnp.float8_e4m3fn)
        return {"qweight": q, "scale": scale}
    if spec.dtype == MXFP4:
        return quantize_mxfp4(w, spec.group_size)
    raise ValueError(f"unknown quantization dtype {spec.dtype!r}")


def quantize_mxfp4(w: np.ndarray, group_size: int = 32) -> Dict[str, np.ndarray]:
    """MXFP4: e2m1 values, one power-of-two (e8m0-style) scale per
    ``group_size`` channels of the contraction dim (axis -2). Packed two
    nibbles per uint8 along the contraction dim.

    Layout: w (..., K, N) -> qweight uint8 (..., K//2, N) [low nibble = even
    k, high nibble = odd k], scale fp32 (..., K//group, N).
    """
    *lead, K, N = w.shape
    assert K % group_size == 0, (K, group_size)
    g = w.reshape(*lead, K // group_size, group_size, N)
    amax = np.max(np.abs(g), axis=-2, keepdims=True)
    # power-of-two scale so amax maps into the fp4 range (max 6.0)
    exp = np.ceil(np.log2(np.maximum(amax, 1e-30) / 6.0))
    scale = np.exp2(exp).astype(np.float32)
    scaled = g / scale
    # nearest fp4 value per element: match magnitude, carry sign in bit 3
    idx = np.abs(np.abs(scaled)[..., None] - _FP4_VALUES[:8]).argmin(axis=-1)
    idx = idx.astype(np.uint8) + np.where(scaled < 0, 8, 0).astype(np.uint8)
    idx = idx.reshape(*lead, K, N)
    packed = (idx[..., 0::2, :] | (idx[..., 1::2, :] << 4)).astype(np.uint8)
    return {"qweight": packed,
            "scale": scale.reshape(*lead, K // group_size, N)}


def _leaf_scheme(leaf: Dict[str, Any]) -> str:
    # uint8 = packed fp4 nibbles; int8 / float8_e4m3fn identify themselves;
    # a >1 extent in the scale's contraction slot marks blockwise
    dt = leaf["qweight"].dtype
    if dt == jnp.uint8:
        return MXFP4
    if leaf["scale"].ndim >= 2 and leaf["scale"].shape[-2] > 1:
        return BLOCKWISE
    return FP8 if dt == jnp.float8_e4m3fn else INT8


def quantize_params(params: Dict[str, Any], spec: QuantSpec) -> Dict[str, Any]:
    """Transform a param tree: replace eligible layer weights with quantized
    leaf-groups. Works on host (numpy) arrays; run before device_put."""

    def convert(tree):
        out = {}
        for name, v in tree.items():
            if isinstance(v, dict) and not is_quantized_leaf(v):
                out[name] = convert(v)
            elif spec.converts(name) and not is_quantized_leaf(v):
                out[name] = quantize_tensor(np.asarray(v), spec)
            else:
                out[name] = v
        return out

    return convert(params)


def dequant_oai_mxfp4_blocks(blocks: np.ndarray, scales: np.ndarray
                             ) -> np.ndarray:
    """Decode the gpt-oss checkpoint MXFP4 layout (reference: the mx layout
    transform in models/gpt_oss/, SURVEY §2.7) to fp32.

    blocks: uint8 (..., rows, n_groups, group_bytes) — each byte packs two
    fp4 values, LOW nibble first; scales: uint8 (..., rows, n_groups) e8m0
    exponents biased by 127. Returns (..., rows, n_groups*group_bytes*2).
    """
    blocks = np.asarray(blocks)
    scales = np.asarray(scales).astype(np.int32) - 127
    lut = _FP4_VALUES
    lo = lut[(blocks & 0x0F).astype(np.int32)]
    hi = lut[(blocks >> 4).astype(np.int32)]
    vals = np.stack([lo, hi], axis=-1).reshape(*blocks.shape[:-1], -1)
    return (vals * np.exp2(scales)[..., None]).reshape(
        *blocks.shape[:-2], -1).astype(np.float32)


# ---------------------------------------------------------------------------
# in-graph dequant / matmul
# ---------------------------------------------------------------------------

def dequantize(leaf: Dict[str, Any], dtype=jnp.bfloat16) -> jnp.ndarray:
    """Materialize the fp weight (mxfp4 path; int8/fp8 prefer qlinear)."""
    q, scale = leaf["qweight"], leaf["scale"]
    if _leaf_scheme(leaf) == BLOCKWISE:
        *lead, K, N = q.shape
        group = K // scale.shape[-2]
        vals = q.astype(jnp.float32).reshape(*lead, K // group, group, N)
        vals = vals * scale[..., :, None, :]
        return vals.reshape(*lead, K, N).astype(dtype)
    if _leaf_scheme(leaf) == MXFP4:
        lut = jnp.asarray(_FP4_VALUES)
        lo = lut[(q & 0x0F).astype(jnp.int32)]
        hi = lut[(q >> 4).astype(jnp.int32)]
        *lead, Kh, N = q.shape
        K = Kh * 2
        # byte j: low nibble = channel 2j, high = 2j+1; stacking on a new
        # axis right after Kh then flattening interleaves them back
        vals = jnp.stack([lo, hi], axis=-2)            # (*lead, Kh, 2, N)
        vals = vals.reshape(*lead, K, N)
        group = K // scale.shape[-2]                   # inferred group size
        vals = vals.reshape(*lead, K // group, group, N)
        vals = vals * scale[..., :, None, :]
        return vals.reshape(*lead, K, N).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)


def qlinear(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Linear that accepts fp arrays, quantized leaf-groups, OR low-rank
    factor pairs.

    int8/fp8 per-channel/per-tensor: scale commutes out of the contraction —
    (x @ q) * scale_row keeps the weight stream int8 in HBM (the whole point:
    decode is HBM-bandwidth-bound, int8 halves the weight bytes).
    """
    if isinstance(w, dict) and "lr_u" in w:
        # low-rank (SVD) factor pair (modules/low_rank.py): two skinny
        # matmuls through the rank-r bottleneck; each factor may itself
        # be a quantized leaf-group — the recursion composes both wins
        return qlinear(qlinear(x, w["lr_u"]), w["lr_v"])
    if not is_quantized_leaf(w):
        return x @ w
    scheme = _leaf_scheme(w)
    if scheme in (MXFP4, BLOCKWISE):
        # blockwise scales don't commute out of the contraction; the weight
        # still streams from HBM quantized — the dequant fuses into the
        # matmul read (XLA), preserving the bandwidth win
        return x @ dequantize(w, x.dtype)
    q, scale = w["qweight"], w["scale"]
    y = x @ q.astype(x.dtype)
    # scale (..., 1, out) or scalar (...,) -> broadcast over (B, T, out)
    s = scale[..., 0, :] if scale.ndim >= 2 else scale
    return (y.astype(jnp.float32) * s).astype(x.dtype)


def qeinsum(pattern: str, x: jnp.ndarray, w: Any) -> jnp.ndarray:
    """Einsum accepting quantized expert weights (dense all-experts MoE path).
    Scale layouts follow quantize_tensor: contraction dim is the
    second-to-last axis of w."""
    if not is_quantized_leaf(w):
        return jnp.einsum(pattern, x, w)
    scheme = _leaf_scheme(w)
    if scheme in (MXFP4, BLOCKWISE):
        return jnp.einsum(pattern, x, dequantize(w, x.dtype))
    q, scale = w["qweight"], w["scale"]
    y = jnp.einsum(pattern, x, q.astype(x.dtype))
    if scale.ndim >= 2:
        # (..., 1, out): drop the contraction axis, broadcast to y's trailing
        s = scale[..., 0, :]
        # expert weights (E, 1, out): out dims of y are (..., E?, out) — the
        # einsum puts expert axis before out for "btei"/"bteh" patterns
        y = y.astype(jnp.float32) * s
    else:
        y = y.astype(jnp.float32) * scale
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# sharding of quantized trees
# ---------------------------------------------------------------------------

def _qleaf_shardings(entries: List[Any], v: Dict[str, Any], mesh):
    """Shardings for one quantized leaf-group, given the fp weight's
    PartitionSpec entries: qweight inherits the weight's sharding; scale
    inherits it with the contraction axis unsharded (its extent is 1 or
    K/group); size-1 dims (per-tensor) can't carry a mesh axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    q_ndim = v["qweight"].ndim
    entries = list(entries) + [None] * (q_ndim - len(entries))
    s_shape = v["scale"].shape
    s_entries = entries[:q_ndim - 2] + [None, entries[q_ndim - 1]]
    s_entries = [e if d > 1 else None
                 for e, d in zip(s_entries, s_shape)]
    return {"qweight": NamedSharding(mesh, P(*entries[:q_ndim])),
            "scale": NamedSharding(mesh, P(*s_entries))}


def _low_rank_leaf_shardings(sh, v: Dict[str, Any], mesh):
    """Shardings for a low-rank factor pair (modules/low_rank.py): lr_u
    (..., K, r) keeps the fp weight's contraction-axis sharding with the
    rank dim replicated; lr_v (..., r, N) keeps the out-axis sharding
    with the rank dim replicated — so a column-parallel weight shards V,
    a row-parallel weight shards U, and the reduction lands on the tiny
    rank-r intermediate. Factors that are themselves quantized
    leaf-groups recurse through the qweight/scale rule."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    u = v["lr_u"]
    nd = u["qweight"].ndim if is_quantized_leaf(u) else u.ndim
    entries = list(sh.spec) + [None] * (nd - len(sh.spec))
    lead = entries[:nd - 2]
    u_entries = lead + [entries[nd - 2], None]
    v_entries = lead + [None, entries[nd - 1]]

    def one(factor, ent):
        if is_quantized_leaf(factor):
            return _qleaf_shardings(ent, factor, mesh)
        return NamedSharding(mesh, P(*ent))

    return {"lr_u": one(u, u_entries), "lr_v": one(v["lr_v"], v_entries)}


def quantized_shardings(fp_shardings: Dict[str, Any], params: Dict[str, Any],
                        mesh) -> Dict[str, Any]:
    """Derive shardings for a quantized and/or low-rank-factorized param
    tree from the fp ParamSpec shardings (see :func:`_qleaf_shardings` /
    :func:`_low_rank_leaf_shardings` for the per-leaf rules)."""

    def walk(sh_tree, p_tree):
        out = {}
        for name, v in p_tree.items():
            sh = sh_tree[name]
            if is_quantized_leaf(v):
                wspec = sh.spec
                q_ndim = v["qweight"].ndim
                entries = list(wspec) + [None] * (q_ndim - len(wspec))
                out[name] = _qleaf_shardings(entries, v, mesh)
            elif isinstance(v, dict) and "lr_u" in v:
                out[name] = _low_rank_leaf_shardings(sh, v, mesh)
            elif isinstance(v, dict):
                out[name] = walk(sh, v)
            else:
                out[name] = sh
        return out

    return walk(fp_shardings, params)
