"""Recurrent state-space blocks — the framework's recurrent/hybrid state
axis (reference: contrib/models/Falcon-H1-0.5B-Instruct/src/
modeling_falcon_h1.py FalconH1Mixer and contrib/models/recurrentgemma-2b-it/
src/modeling_recurrent_gemma.py — SURVEY §2.7 contrib inventory).

TPU-first redesign, not a translation:
  * The reference recomputes the FULL quadratic SSD form every forward (no
    decode state cache — its FalconH1Mixer.forward is O(T²) per token).
    Here the recurrent state is a first-class cache pytree carried next to
    the KV cache: prefill computes it once with a chunked ``lax.scan``
    (O(T·chunk) memory, MXU-shaped intra-chunk matmuls), decode is a pure
    O(1) recurrence step.
  * The RG-LRU linear recurrence uses ``jax.lax.associative_scan`` — the
    log-depth parallel scan XLA maps well to TPU — instead of the
    reference's per-timestep Python loop.
  * Mamba's in_proj is stored SPLIT by destination ([gate|x|B|C|dt] →
    five tensors) so tensor parallelism can shard the head-structured
    gate/x paths on the model axis while the tiny per-group B/C/dt stay
    replicated — the clean TP layout the torch reference approximates
    with gather_output=True (i.e. no sharding at all).

State layout (stacked over the SSM-bearing layers, batch-sharded on dp,
channels/heads on the model axis):
  mamba2: conv_x (Ls,B,d_inner,K-1), conv_bc (Ls,B,2·g·N,K-1),
          ssm (Ls,B,nh,hd,N) fp32
  rglru:  conv_x (Ls,B,W,K-1), ssm (Ls,B,W) fp32
The conv tails hold the last K-1 *pre-conv* projected inputs, so a decode
step is ``concat(tail, current) → depthwise dot`` exactly like the
reference's cached path (modeling_falcon_h1.py torch_forward cached branch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.layers import ParamSpec
from ..parallel.mesh import AXIS_DP, AXIS_MP


@dataclass(frozen=True)
class SSMSpec:
    """Geometry of the recurrent block shared by all layers that carry one.

    kind "mamba2": Falcon-H1 / Mamba-2 selective SSM (SSD form).
    kind "rglru": recurrentgemma / Griffin RG-LRU linear recurrence.
    kind "shortconv": LFM2 gated short convolution (conv state only —
      reference: contrib/models/lfm2-2.6b; HF Lfm2ShortConv).
    """

    kind: str                 # "mamba2" | "rglru"
    d_inner: int              # mamba d_ssm / rglru lru_width
    num_heads: int            # mamba_n_heads / rglru num_attention_heads
    head_dim: int             # mamba_d_head / rglru block_width
    d_state: int = 0          # mamba ssm state size N (rglru: unused)
    n_groups: int = 1         # mamba B/C groups
    d_conv: int = 4           # depthwise conv kernel width K
    chunk_size: int = 128     # prefill scan chunk
    conv_bias: bool = True
    gated_norm: bool = False      # mamba_rms_norm: RMSNormGated before out
    norm_before_gate: bool = False
    norm_eps: float = 1e-6        # gated-norm eps (falcon-h1: rms_norm_eps)
    dt_limit: Tuple[float, float] = (0.0, float("inf"))

    @property
    def bc_size(self) -> int:
        return 2 * self.n_groups * self.d_state


# ---------------------------------------------------------------------------
# Parameter + state specs
# ---------------------------------------------------------------------------

def ssm_param_specs(s: SSMSpec, hidden: int, Ls: int, dtype) -> Dict[str, ParamSpec]:
    """Stacked per-layer weights for the recurrent block (layer dim Ls
    first, like every other stacked layer weight in decoder_param_specs)."""
    if s.kind == "mamba2":
        gn = s.n_groups * s.d_state
        specs = {
            "ssm_in_gate": ParamSpec((Ls, hidden, s.d_inner), P(None, None, AXIS_MP), dtype),
            "ssm_in_x": ParamSpec((Ls, hidden, s.d_inner), P(None, None, AXIS_MP), dtype),
            "ssm_in_bc": ParamSpec((Ls, hidden, 2 * gn), P(), dtype),
            "ssm_in_dt": ParamSpec((Ls, hidden, s.num_heads), P(), dtype),
            "ssm_conv_x": ParamSpec((Ls, s.d_inner, s.d_conv), P(None, AXIS_MP, None), dtype),
            "ssm_conv_bc": ParamSpec((Ls, 2 * gn, s.d_conv), P(), dtype),
            "ssm_dt_bias": ParamSpec((Ls, s.num_heads), P(), jnp.float32, "ones"),
            "ssm_A_log": ParamSpec((Ls, s.num_heads), P(), jnp.float32, "zeros"),
            "ssm_D": ParamSpec((Ls, s.num_heads), P(), jnp.float32, "ones"),
            "ssm_out": ParamSpec((Ls, s.d_inner, hidden), P(None, AXIS_MP, None), dtype),
        }
        if s.conv_bias:
            specs["ssm_conv_x_b"] = ParamSpec((Ls, s.d_inner), P(None, AXIS_MP), dtype, "zeros")
            specs["ssm_conv_bc_b"] = ParamSpec((Ls, 2 * gn), P(), dtype, "zeros")
        if s.gated_norm:
            specs["ssm_norm"] = ParamSpec((Ls, s.d_inner), P(None, AXIS_MP), dtype, "ones")
        return specs
    if s.kind == "shortconv":
        W = s.d_inner
        specs = {
            "sc_in_b": ParamSpec((Ls, hidden, W), P(None, None, AXIS_MP), dtype),
            "sc_in_c": ParamSpec((Ls, hidden, W), P(None, None, AXIS_MP), dtype),
            "sc_in_x": ParamSpec((Ls, hidden, W), P(None, None, AXIS_MP), dtype),
            "sc_conv": ParamSpec((Ls, W, s.d_conv), P(None, AXIS_MP, None), dtype),
            "sc_out": ParamSpec((Ls, W, hidden), P(None, AXIS_MP, None), dtype),
        }
        if s.conv_bias:
            specs["sc_in_b_b"] = ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros")
            specs["sc_in_c_b"] = ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros")
            specs["sc_in_x_b"] = ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros")
            specs["sc_conv_b"] = ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros")
            specs["sc_out_b"] = ParamSpec((Ls, hidden), P(), dtype, "zeros")
        return specs
    if s.kind == "rglru":
        W, nh, bw = s.d_inner, s.num_heads, s.head_dim
        return {
            "rg_y": ParamSpec((Ls, hidden, W), P(None, None, AXIS_MP), dtype),
            "rg_y_b": ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros"),
            "rg_x": ParamSpec((Ls, hidden, W), P(None, None, AXIS_MP), dtype),
            "rg_x_b": ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros"),
            "rg_conv": ParamSpec((Ls, W, s.d_conv), P(None, AXIS_MP, None), dtype),
            "rg_conv_b": ParamSpec((Ls, W), P(None, AXIS_MP), dtype, "zeros"),
            "rg_param": ParamSpec((Ls, W), P(None, AXIS_MP), jnp.float32, "ones"),
            "rg_igate_w": ParamSpec((Ls, nh, bw, bw), P(None, AXIS_MP, None, None), dtype),
            "rg_igate_b": ParamSpec((Ls, nh, bw), P(None, AXIS_MP, None), dtype, "zeros"),
            "rg_rgate_w": ParamSpec((Ls, nh, bw, bw), P(None, AXIS_MP, None, None), dtype),
            "rg_rgate_b": ParamSpec((Ls, nh, bw), P(None, AXIS_MP, None), dtype, "zeros"),
            "rg_out": ParamSpec((Ls, W, hidden), P(None, AXIS_MP, None), dtype),
            "rg_out_b": ParamSpec((Ls, hidden), P(), dtype, "zeros"),
        }
    raise ValueError(f"unknown SSM kind {s.kind!r}")


def ssm_state_shapes(s: SSMSpec, Ls: int, batch: int, dtype
                     ) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """{cache_key: (shape, dtype)} for the recurrent state entries."""
    K1 = s.d_conv - 1
    if s.kind == "mamba2":
        return {
            "conv_x": ((Ls, batch, s.d_inner, K1), dtype),
            "conv_bc": ((Ls, batch, s.bc_size, K1), dtype),
            "ssm": ((Ls, batch, s.num_heads, s.head_dim, s.d_state),
                    jnp.float32),
        }
    if s.kind == "shortconv":
        return {"conv_x": ((Ls, batch, s.d_inner, K1), dtype)}
    return {
        "conv_x": ((Ls, batch, s.d_inner, K1), dtype),
        "ssm": ((Ls, batch, s.d_inner), jnp.float32),
    }


def init_ssm_state(s: SSMSpec, Ls: int, batch: int, dtype, mesh=None
                   ) -> Dict[str, Any]:
    """Zero recurrent-state entries, device-placed with their shardings —
    the state analog of kv_cache.init_cache (single source of the state
    pytree layout for the application AND the multichip dryrun)."""
    from jax.sharding import NamedSharding
    pspecs = ssm_state_pspecs(s)
    out = {}
    for k, (shape, dt) in ssm_state_shapes(s, Ls, batch, dtype).items():
        x = jnp.zeros(shape, dt)
        if mesh is not None:
            x = jax.device_put(x, NamedSharding(mesh, pspecs[k]))
        out[k] = x
    return out


def ssm_state_pspecs(s: SSMSpec) -> Dict[str, P]:
    if s.kind == "mamba2":
        return {
            "conv_x": P(None, AXIS_DP, AXIS_MP, None),
            "conv_bc": P(None, AXIS_DP, None, None),
            "ssm": P(None, AXIS_DP, AXIS_MP, None, None),
        }
    if s.kind == "shortconv":
        return {"conv_x": P(None, AXIS_DP, AXIS_MP, None)}
    return {"conv_x": P(None, AXIS_DP, AXIS_MP, None),
            "ssm": P(None, AXIS_DP, AXIS_MP)}


# ---------------------------------------------------------------------------
# Shared pieces
# ---------------------------------------------------------------------------

def _causal_conv_prefill(x, w, b):
    """Depthwise causal conv over (B, T, C) with kernel (C, K): K shifted
    adds — K is 4; XLA fuses this into a handful of vector ops (vs a conv
    primitive whose tiny channel-depthwise form lowers poorly)."""
    K = w.shape[-1]
    out = x * w[:, K - 1]
    for j in range(K - 1):
        shift = K - 1 - j
        shifted = jnp.pad(x, ((0, 0), (shift, 0), (0, 0)))[:, :x.shape[1]]
        out = out + shifted * w[:, j]
    if b is not None:
        out = out + b
    return out


def _conv_tail(x, seq_lens, K1):
    """Last K-1 columns of (B, T, C) ending at seq_len per row (zeros where
    the window reaches before position 0) → (B, C, K-1)."""
    B, T, C = x.shape
    idx = seq_lens[:, None] - K1 + jnp.arange(K1)[None, :]       # (B, K1)
    take = jnp.clip(idx, 0, T - 1)
    tail = jnp.take_along_axis(x, take[:, :, None], axis=1)      # (B, K1, C)
    tail = jnp.where((idx >= 0)[:, :, None], tail, 0)
    return tail.transpose(0, 2, 1)


def _conv_step(tail, cur, w, b):
    """One decode conv step: (B, C, K-1) tail + (B, C) current → (value
    (B, C), new tail). Matches the reference's roll-and-dot cached branch
    (modeling_falcon_h1.py torch_forward)."""
    win = jnp.concatenate([tail, cur[:, :, None]], axis=-1)       # (B,C,K)
    val = jnp.sum(win * w[None], axis=-1)
    if b is not None:
        val = val + b
    return val, win[:, :, 1:]


def _segsum(a_log):
    """Segment-sum decay matrix: M[t, s] = sum_{j=s+1..t} a_log[j] for
    s <= t, -inf otherwise. a_log (B, c, H) → (B, H, c, c)."""
    c = a_log.shape[1]
    acs = jnp.cumsum(a_log, axis=1)                               # (B,c,H)
    diff = acs[:, :, None, :] - acs[:, None, :, :]                # (B,t,s,H)
    mask = jnp.tril(jnp.ones((c, c), bool))
    diff = jnp.where(mask[None, :, :, None], diff, -jnp.inf)
    return diff.transpose(0, 3, 1, 2)


# ---------------------------------------------------------------------------
# Mamba-2 (SSD) mixer — Falcon-H1 flavor
# ---------------------------------------------------------------------------

def mamba2_mixer(s: SSMSpec, lw, x, state: Dict[str, Any], *, phase: str,
                 seq_lens=None, positions=None):
    """One mamba2 block over already-normed input x (B, T, H).

    lw: this layer's weight dict (the ssm_* entries of the stacked layer
    params, indexed at this layer). state: {"conv_x","conv_bc","ssm"} THIS
    layer's state entries. Returns (y (B,T,H), new_state).

    Prefill semantics track the reference's SSD form
    (modeling_falcon_h1.py torch_forward non-cached branch) with one
    divergence that is a fix, not a drift: positions ≥ seq_len get dt = 0
    (decay 1, input contribution 0), so a right-padded prefill leaves the
    carried state exactly as an unpadded run would — the torch reference
    only supports left-padding for this reason.
    """
    B, T, H = x.shape
    f32 = jnp.float32
    gn = s.n_groups * s.d_state
    nh, hd, N = s.num_heads, s.head_dim, s.d_state

    gate = x @ lw["ssm_in_gate"]
    xs = x @ lw["ssm_in_x"]
    bc = x @ lw["ssm_in_bc"]
    dt_raw = (x @ lw["ssm_in_dt"]).astype(f32)

    if phase == "prefill":
        valid = (positions < seq_lens[:, None])                   # (B,T)
        xs = jnp.where(valid[..., None], xs, 0)
        bc = jnp.where(valid[..., None], bc, 0)
        xs_c = jax.nn.silu(_causal_conv_prefill(
            xs, lw["ssm_conv_x"], lw.get("ssm_conv_x_b")))
        bc_c = jax.nn.silu(_causal_conv_prefill(
            bc, lw["ssm_conv_bc"], lw.get("ssm_conv_bc_b")))
        xs_c = jnp.where(valid[..., None], xs_c, 0)
        bc_c = jnp.where(valid[..., None], bc_c, 0)
        new_state = {"conv_x": _conv_tail(xs, seq_lens, s.d_conv - 1),
                     "conv_bc": _conv_tail(bc, seq_lens, s.d_conv - 1)}
    else:
        cx, ncx = _conv_step(state["conv_x"], xs[:, 0],
                             lw["ssm_conv_x"], lw.get("ssm_conv_x_b"))
        cbc, ncbc = _conv_step(state["conv_bc"], bc[:, 0],
                               lw["ssm_conv_bc"], lw.get("ssm_conv_bc_b"))
        xs_c = jax.nn.silu(cx)[:, None]
        bc_c = jax.nn.silu(cbc)[:, None]
        new_state = {"conv_x": ncx, "conv_bc": ncbc}

    dt = jax.nn.softplus(dt_raw + lw["ssm_dt_bias"].astype(f32))
    dt = jnp.clip(dt, s.dt_limit[0], min(s.dt_limit[1], 1e6))
    if phase == "prefill":
        dt = jnp.where(valid[..., None], dt, 0.0)

    A = -jnp.exp(lw["ssm_A_log"].astype(f32))                     # (nh,)
    x_h = xs_c.reshape(B, T, nh, hd).astype(f32)
    Bm = bc_c[..., :gn].reshape(B, T, s.n_groups, N).astype(f32)
    Cm = bc_c[..., gn:].reshape(B, T, s.n_groups, N).astype(f32)
    rep = nh // s.n_groups
    Bm = jnp.repeat(Bm, rep, axis=2)                              # (B,T,nh,N)
    Cm = jnp.repeat(Cm, rep, axis=2)
    dA_log = dt * A[None, None, :]                                # (B,T,nh)
    D_res = lw["ssm_D"].astype(f32)[None, None, :, None] * x_h
    x_dt = x_h * dt[..., None]

    if phase == "decode":
        ssm = state["ssm"]                                        # (B,nh,hd,N)
        dA = jnp.exp(dA_log[:, 0])                                # (B,nh)
        dBx = x_dt[:, 0, :, :, None] * Bm[:, 0, :, None, :]       # (B,nh,hd,N)
        ssm = ssm * dA[..., None, None] + dBx
        y = jnp.einsum("bhdn,bhn->bhd", ssm, Cm[:, 0]) + D_res[:, 0]
        y = y.reshape(B, 1, s.d_inner)
        new_state["ssm"] = ssm
    else:
        cs = min(s.chunk_size, T)
        pad = (cs - T % cs) % cs

        def padc(a):
            return jnp.pad(a, ((0, 0), (0, pad)) + ((0, 0),) * (a.ndim - 2))

        nchunk = (T + pad) // cs
        xc = padc(x_dt).reshape(B, nchunk, cs, nh, hd).transpose(1, 0, 2, 3, 4)
        Bc = padc(Bm).reshape(B, nchunk, cs, nh, N).transpose(1, 0, 2, 3, 4)
        Cc = padc(Cm).reshape(B, nchunk, cs, nh, N).transpose(1, 0, 2, 3, 4)
        ac = padc(dA_log).reshape(B, nchunk, cs, nh).transpose(1, 0, 2, 3)

        def chunk_body(carry, inp):
            st = carry                                            # (B,nh,hd,N)
            xk, Bk, Ck, ak = inp
            acs = jnp.cumsum(ak, axis=1)                          # (B,c,nh)
            L = jnp.exp(_segsum(ak))                              # (B,nh,c,c)
            G = jnp.einsum("bthn,bshn->bhts", Ck, Bk)
            Yd = jnp.einsum("bhts,bshd->bthd", G * L, xk)
            dec = jnp.exp(acs)                                    # (B,c,nh)
            Yoff = jnp.einsum("bthn,bhdn->bthd", Ck * dec[..., None], st)
            last = acs[:, -1:, :]                                 # (B,1,nh)
            Bdec = Bk * jnp.exp(last - acs)[..., None]
            st_new = (st * jnp.exp(last[:, 0])[:, :, None, None]
                      + jnp.einsum("bshn,bshd->bhdn", Bdec, xk))
            return st_new, Yd + Yoff

        # prefill always starts fresh — the cache slot may hold a previous
        # request's state (the KV analog overwrites its rows the same way)
        st0 = jnp.zeros((B, nh, hd, N), f32)
        st_f, Y = jax.lax.scan(chunk_body, st0, (xc, Bc, Cc, ac))
        Y = Y.transpose(1, 0, 2, 3, 4).reshape(B, T + pad, nh, hd)[:, :T]
        y = (Y + D_res).reshape(B, T, s.d_inner)
        new_state["ssm"] = st_f

    gate = gate.astype(f32)
    if s.gated_norm:
        g = s.n_groups
        if not s.norm_before_gate:
            y = y * jax.nn.silu(gate)
        yg = y.reshape(B, T, g, s.d_inner // g)
        var = jnp.mean(yg * yg, axis=-1, keepdims=True)
        yg = yg * jax.lax.rsqrt(var + s.norm_eps)
        y = yg.reshape(B, T, s.d_inner) * lw["ssm_norm"].astype(f32)
        if s.norm_before_gate:
            y = y * jax.nn.silu(gate)
    else:
        y = y * jax.nn.silu(gate)
    out = y.astype(x.dtype) @ lw["ssm_out"]
    return out, new_state


# ---------------------------------------------------------------------------
# RG-LRU recurrent block — recurrentgemma / Griffin flavor
# ---------------------------------------------------------------------------

def rglru_block(s: SSMSpec, lw, x, state: Dict[str, Any], *, phase: str,
                seq_lens=None, positions=None):
    """One Griffin recurrent block over normed input x (B, T, H)
    (reference: contrib/models/recurrentgemma-2b-it/src/
    modeling_recurrent_gemma.py RecurrentGemmaRecurrentBlock):
    y-branch gelu gate, x-branch conv → RG-LRU, elementwise product,
    output projection. Returns (y (B,T,H), new_state)."""
    B, T, H = x.shape
    f32 = jnp.float32
    W, nh, bw = s.d_inner, s.num_heads, s.head_dim

    y_b = jax.nn.gelu(x @ lw["rg_y"] + lw["rg_y_b"], approximate=True)
    xb = x @ lw["rg_x"] + lw["rg_x_b"]

    if phase == "prefill":
        valid = (positions < seq_lens[:, None])
        xb = jnp.where(valid[..., None], xb, 0)
        xc = _causal_conv_prefill(xb, lw["rg_conv"], lw["rg_conv_b"])
        new_state = {"conv_x": _conv_tail(xb, seq_lens, s.d_conv - 1)}
    else:
        val, ntail = _conv_step(state["conv_x"], xb[:, 0],
                                lw["rg_conv"], lw["rg_conv_b"])
        xc = val[:, None]
        new_state = {"conv_x": ntail}

    xh = xc.reshape(B, T, nh, bw)
    igate = jax.nn.sigmoid(
        jnp.einsum("bthw,hwv->bthv", xh, lw["rg_igate_w"]) + lw["rg_igate_b"])
    rgate = jax.nn.sigmoid(
        jnp.einsum("bthw,hwv->bthv", xh, lw["rg_rgate_w"]) + lw["rg_rgate_b"])
    igate = igate.reshape(B, T, W).astype(f32)
    rgate = rgate.reshape(B, T, W).astype(f32)

    log_a = -8.0 * rgate * jax.nn.softplus(lw["rg_param"].astype(f32))
    a = jnp.exp(log_a)
    reset = (positions == 0)[..., None]                           # (B,T,1)
    mult = jnp.where(reset, 1.0, jnp.sqrt(1.0 - jnp.exp(2.0 * log_a)))
    gated = xc.astype(f32) * igate * mult
    a_eff = jnp.where(reset, 0.0, a)

    if phase == "decode":
        h = a_eff[:, 0] * state["ssm"] + gated[:, 0]              # (B,W)
        new_state["ssm"] = h
        seq = h[:, None]
    else:
        # padded positions: identity element (a=1, b=0) so the carried
        # state is exactly the state at seq_len
        a_eff = jnp.where(valid[..., None], a_eff, 1.0)
        gated = jnp.where(valid[..., None], gated, 0.0)

        def comb(e1, e2):
            a1, b1 = e1
            a2, b2 = e2
            return a1 * a2, a2 * b1 + b2

        _, hs = jax.lax.associative_scan(comb, (a_eff, gated), axis=1)
        idx = jnp.maximum(seq_lens - 1, 0)
        new_state["ssm"] = jnp.take_along_axis(
            hs, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        seq = hs

    y = seq.astype(x.dtype) * y_b
    return y @ lw["rg_out"] + lw["rg_out_b"], new_state


def shortconv_block(s: SSMSpec, lw, x, state: Dict[str, Any], *, phase: str,
                    seq_lens=None, positions=None):
    """LFM2 gated short convolution (reference: contrib/models/lfm2-2.6b;
    HF Lfm2ShortConv): y = out(C ⊙ conv(B ⊙ x_proj)) with a depthwise
    causal conv of width d_conv and no nonlinearity. Carries only the
    conv tail of B⊙x."""
    Bg = x @ lw["sc_in_b"]
    Cg = x @ lw["sc_in_c"]
    xg = x @ lw["sc_in_x"]
    if s.conv_bias:
        Bg = Bg + lw["sc_in_b_b"]
        Cg = Cg + lw["sc_in_c_b"]
        xg = xg + lw["sc_in_x_b"]
    bx = Bg * xg
    if phase == "prefill":
        valid = (positions < seq_lens[:, None])
        bx = jnp.where(valid[..., None], bx, 0)
        conv = _causal_conv_prefill(bx, lw["sc_conv"],
                                    lw.get("sc_conv_b"))
        new_state = {"conv_x": _conv_tail(bx, seq_lens, s.d_conv - 1)}
    else:
        val, ntail = _conv_step(state["conv_x"], bx[:, 0],
                                lw["sc_conv"], lw.get("sc_conv_b"))
        conv = val[:, None]
        new_state = {"conv_x": ntail}
    y = (Cg * conv) @ lw["sc_out"]
    if s.conv_bias:
        y = y + lw["sc_out_b"]
    return y, new_state


_SSM_BLOCKS = {"mamba2": mamba2_mixer, "rglru": rglru_block,
               "shortconv": shortconv_block}


def ssm_block(s: SSMSpec, lw, x, state, *, phase, seq_lens=None,
              positions=None):
    return _SSM_BLOCKS[s.kind](s, lw, x, state, phase=phase,
                               seq_lens=seq_lens, positions=positions)
