#!/usr/bin/env python
"""check_metrics_exposition: the served /v1/metrics scrape is valid
Prometheus text — a tier-1 lint (ISSUE 14).

Two halves, both importable so the tier-1 test runs them IN-PROCESS
(never a subprocess that pays a fresh jax import against the tight
suite budget):

  * :func:`validate_prometheus_text` — a dependency-free validating
    parser for the text exposition format 0.0.4: every sample line must
    parse (name, label pairs, float value), every sample's metric family
    must have exactly one ``# TYPE`` line BEFORE its first sample,
    histogram families must expose cumulative non-decreasing ``_bucket``
    series whose ``+Inf`` bucket equals ``_count``, and counters must
    never be negative. Returns a list of problems (empty = valid).
  * :func:`scrape_frontend` — boots a :class:`ServingFrontend` over a
    (caller-provided or tiny synthetic) engine, serves one real request,
    and returns the body of ``GET /v1/metrics`` fetched over the actual
    socket — the scrape a Prometheus agent would see, not a shortcut
    through ``render_prometheus()``.

CLI: ``python scripts/check_metrics_exposition.py`` builds the tiny
synthetic paged engine (CPU), scrapes, validates, and exits 0/1.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))     # package import when run as a script

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"\s*(?:,|$)')

_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_value(text: str) -> Optional[float]:
    if text in ("+Inf", "Inf"):
        return float("inf")
    if text == "-Inf":
        return float("-inf")
    if text == "NaN":
        return float("nan")
    try:
        return float(text)
    except ValueError:
        return None


def _parse_labels(text: str) -> Optional[Dict[str, str]]:
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(text):
        m = _LABEL_RE.match(text, pos)
        if m is None:
            return None
        labels[m.group("k")] = m.group("v")
        pos = m.end()
    return labels


def _family(name: str, types: Dict[str, str]) -> str:
    """The metric family a sample line belongs to: histogram samples
    carry _bucket/_sum/_count suffixes on the family name."""
    for suf in _SUFFIXES:
        base = name[:-len(suf)] if name.endswith(suf) else None
        if base and types.get(base) == "histogram":
            return base
    return name


def validate_prometheus_text(text: str) -> List[str]:
    """Problems with a text-exposition body; empty list = valid."""
    problems: List[str] = []
    types: Dict[str, str] = {}
    seen_samples = False
    # (family, labels-sans-le sorted) -> list of (le, cumulative count)
    buckets: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                  List[Tuple[float, float]]] = {}
    counts: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    if text and not text.endswith("\n"):
        problems.append("body must end with a newline")
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                problems.append(f"line {i}: malformed TYPE line: {line!r}")
                continue
            name = parts[2]
            if name in types:
                problems.append(f"line {i}: duplicate TYPE for {name}")
            types[name] = parts[3]
            continue
        if line.startswith("# HELP "):
            if len(line.split(None, 3)) < 4:
                problems.append(f"line {i}: malformed HELP line: {line!r}")
            continue
        if line.startswith("#"):
            continue                     # free-form comment: allowed
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {i}: unparseable sample: {line!r}")
            continue
        seen_samples = True
        name = m.group("name")
        value = _parse_value(m.group("value"))
        if value is None:
            problems.append(f"line {i}: bad sample value "
                            f"{m.group('value')!r}")
            continue
        labels = _parse_labels(m.group("labels") or "")
        if labels is None:
            problems.append(f"line {i}: unparseable labels in {line!r}")
            continue
        family = _family(name, types)
        ftype = types.get(family)
        if ftype is None:
            problems.append(f"line {i}: sample {name} has no preceding "
                            "# TYPE line for its family")
            continue
        if ftype == "counter" and value < 0:
            problems.append(f"line {i}: counter {name} is negative")
        if ftype == "histogram":
            key_labels = tuple(sorted((k, v) for k, v in labels.items()
                                      if k != "le"))
            key = (family, key_labels)
            if name.endswith("_bucket"):
                if "le" not in labels:
                    problems.append(f"line {i}: histogram bucket without "
                                    "an le label")
                    continue
                le = _parse_value(labels["le"])
                if le is None:
                    problems.append(f"line {i}: bad le value "
                                    f"{labels['le']!r}")
                    continue
                buckets.setdefault(key, []).append((le, value))
            elif name.endswith("_count"):
                counts[key] = value
    for key, series in buckets.items():
        family, labels = key
        les = [le for le, _ in series]
        if les != sorted(les):
            problems.append(f"{family}{dict(labels)}: bucket le bounds "
                            "out of order")
        cums = [c for _, c in series]
        if cums != sorted(cums):
            problems.append(f"{family}{dict(labels)}: bucket counts are "
                            "not cumulative")
        if les and les[-1] != float("inf"):
            problems.append(f"{family}{dict(labels)}: missing +Inf bucket")
        n = counts.get(key)
        if n is None:
            problems.append(f"{family}{dict(labels)}: histogram without "
                            "a _count sample")
        elif series and series[-1][1] != n:
            problems.append(f"{family}{dict(labels)}: +Inf bucket "
                            f"{series[-1][1]} != _count {n}")
    if not seen_samples:
        problems.append("no samples at all — nothing was measured before "
                        "the scrape")
    return problems


def scrape_frontend(engine, path: str = "/v1/metrics", fleet=None,
                    generate: bool = True) -> str:
    """Serve one request through a :class:`ServingFrontend` over
    ``engine`` (skipped with ``generate=False`` — a fleet that already
    served its load) and return the body of ``GET <path>`` fetched over
    the real listener socket."""
    import asyncio
    import json

    from neuronx_distributed_inference_tpu.serving.engine import \
        ServingFrontend

    async def http(host, port, raw):
        r, w = await asyncio.open_connection(host, port)
        w.write(raw)
        await w.drain()
        data = await asyncio.wait_for(r.read(), timeout=90)
        w.close()
        return data

    async def main():
        fe = ServingFrontend(engine, fleet=fleet)
        host, port = await fe.start()
        if generate:
            body = json.dumps({"prompt": [3, 5, 7, 11, 13],
                               "max_new_tokens": 3,
                               "tenant": "scrape"}).encode()
            await http(host, port,
                       b"POST /v1/generate HTTP/1.1\r\nContent-Length: "
                       + str(len(body)).encode() + b"\r\n\r\n" + body)
        resp = await http(host, port,
                          f"GET {path} HTTP/1.1\r\n\r\n".encode())
        await fe.stop()
        head, _, payload = resp.decode().partition("\r\n\r\n")
        status = head.split()[1]
        if status != "200":
            raise RuntimeError(f"GET {path} -> {status}: {payload[:200]}")
        if "text/plain" not in head:
            raise RuntimeError(f"GET {path} served a non-text "
                               f"content type: {head.splitlines()[1:4]}")
        return payload

    return asyncio.run(main())


def scrape_frontend_fleet(engine, router, path: str = "/v1/metrics") -> str:
    """``GET <path>`` on a frontend built with ``fleet=router`` — the
    fleet-aggregated exposition (no extra request served; the router
    already drove its load)."""
    return scrape_frontend(engine, path, fleet=router, generate=False)


def _tiny_engine():
    """The suite's tiny synthetic paged engine (same shapes as
    test_serving_engine, so the persistent compile cache is warm)."""
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.application import \
        PagedCausalLMApplication
    from neuronx_distributed_inference_tpu.models.llama import (
        LlamaFamily, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.serving import PagedEngineAdapter
    from neuronx_distributed_inference_tpu.telemetry.slo import (SLOPolicy,
                                                                 SLOTracker)
    from neuronx_distributed_inference_tpu.serving.engine import ServingEngine

    hf = dict(model_type="llama", hidden_size=64, intermediate_size=128,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, head_dim=16, vocab_size=512,
              rms_norm_eps=1e-5, rope_theta=10000.0, hidden_act="silu",
              tie_word_embeddings=False, torch_dtype="float32")
    tcfg = TpuConfig(batch_size=4, seq_len=64, dtype="float32",
                     enable_bucketing=True, context_encoding_buckets=[16],
                     is_block_kv_layout=True, pa_block_size=8,
                     is_prefix_caching=True)
    app = PagedCausalLMApplication(None, LlamaInferenceConfig(tcfg, **hf),
                                   LlamaFamily)
    app.init_random_weights(7).init_cache()
    tracker = SLOTracker(SLOPolicy(targets={"ttft": 0.5, "tpot": 0.1,
                                            "queue_wait": 1.0}))
    return ServingEngine(PagedEngineAdapter(app), starvation_bound_s=1e9,
                         slo=tracker)


def main(argv=None) -> int:
    import jax

    from neuronx_distributed_inference_tpu import telemetry

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized
    telemetry.enable()
    try:
        text = scrape_frontend(_tiny_engine())
    finally:
        telemetry.disable()
    problems = validate_prometheus_text(text)
    samples = sum(1 for l in text.splitlines()
                  if l and not l.startswith("#"))
    if problems:
        for p in problems:
            print(f"check_metrics_exposition: {p}", file=sys.stderr)
        print(f"check_metrics_exposition: FAIL ({len(problems)} "
              f"problem(s) over {samples} sample(s))", file=sys.stderr)
        return 1
    print(f"check_metrics_exposition: OK — /v1/metrics served {samples} "
          "well-formed sample(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
