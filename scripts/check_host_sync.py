#!/usr/bin/env python
"""Tier-1 lint: no host-blocking materialization in the dispatch region.

The serving adapters' pipelined decode path relies on ``_dispatch_*``
helpers issuing device work WITHOUT fetching any output — a blocking
``np.asarray(out["tokens"])`` (or friends) inside the dispatch region
would serialize host and device and silently destroy the pipeline's
overlap. This lint fails (rc 1) when any function whose name starts with
``_dispatch`` in the checked files contains a call spelled with one of
the blocking/materializing attributes:

    asarray  array  device_get  block_until_ready  item  tolist

The list deliberately OVER-approximates: ``np.array`` over a host list
would not block, but dispatch helpers take fully-prepared scratch inputs
by contract, so any array construction inside the region is a smell and
gets flagged too. The blocking fetch belongs in the retire/fetch helpers
(``_retire`` / ``_fetch_rows``), which run one step behind the dispatch.

The chunked-prefill path is covered the same way: the packed
chunk-dispatch region (``_dispatch_prefill_chunk``) must only issue the
device call and start the async copy — final-chunk tokens are fetched by
the caller, one async hop behind. When the default file set is linted,
the EXPECTED_REGIONS guard additionally fails the lint if a required
region function disappears (a rename would otherwise silently drop its
coverage).

Usage::

    python scripts/check_host_sync.py                 # lint the default set
    python scripts/check_host_sync.py FILE...         # lint specific files
    python scripts/check_host_sync.py --list-regions  # show linted regions

Wired into the test suite as tier-1 tests
(``tests/test_decode_pipeline.py::test_host_sync_lint`` and
``tests/test_chunked_prefill.py::test_chunk_dispatch_region_linted``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Sequence, Tuple

BANNED_ATTRS = ("asarray", "array", "device_get", "block_until_ready",
                "item", "tolist")
REGION_PREFIX = "_dispatch"

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
)
# region functions that MUST exist when linting the default set — a rename
# must move coverage, not lose it
EXPECTED_REGIONS = {
    "neuronx_distributed_inference_tpu/serving/adapter.py": (
        "_dispatch_decode",           # decode pipeline (both adapters)
        "_dispatch_prefill_chunk",    # packed chunked prefill (paged)
    ),
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py": (
        "_dispatch_engine_pass",      # serving engine dispatch-driving loop
    ),
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py": (
        "_dispatch_spec_draft",       # speculative draft pass (self-draft)
        "_dispatch_propose",          # proposer-side draft (Medusa/EAGLE)
        "_dispatch_spec_verify",      # THE one verify dispatch per step
    ),
}


def region_functions(source: str) -> List[str]:
    """Names of every dispatch-region function in ``source``."""
    return [node.name for node in ast.walk(ast.parse(source))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name.startswith(REGION_PREFIX)]


def blocking_calls(source: str) -> List[Tuple[int, str, str]]:
    """(lineno, function, attr) for every banned call inside a dispatch
    region function."""
    bad: List[Tuple[int, str, str]] = []
    for node in ast.walk(ast.parse(source)):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith(REGION_PREFIX):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr in BANNED_ATTRS:
                bad.append((sub.lineno, node.name, fn.attr))
    return bad


def main(argv: Sequence[str] = ()) -> int:
    argv = list(argv)
    list_regions = "--list-regions" in argv
    argv = [a for a in argv if a != "--list-regions"]
    default_set = not argv
    paths = [Path(p) for p in argv] if argv else \
        [REPO_ROOT / p for p in DEFAULT_PATHS]
    rc = 0
    for path in paths:
        if not path.exists():
            print(f"check_host_sync: {path}: missing", file=sys.stderr)
            rc = 1
            continue
        source = path.read_text()
        if list_regions:
            for name in region_functions(source):
                print(f"{path}: {name}")
        for lineno, func, attr in blocking_calls(source):
            print(f"{path}:{lineno}: .{attr}(...) inside dispatch-region "
                  f"function {func!r} — device output must not be "
                  "materialized before retire/fetch (decode pipeline "
                  "contract)", file=sys.stderr)
            rc = 1
        if default_set:
            rel = path.relative_to(REPO_ROOT).as_posix()
            found = set(region_functions(source))
            for required in EXPECTED_REGIONS.get(rel, ()):
                if required not in found:
                    print(f"check_host_sync: {path}: expected dispatch "
                          f"region {required!r} is gone — renamed regions "
                          "must keep the _dispatch prefix (and this list "
                          "updated) or the lint loses coverage",
                          file=sys.stderr)
                    rc = 1
    if rc == 0 and not list_regions:
        print(f"check_host_sync: OK ({len(paths)} file(s) clean)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
