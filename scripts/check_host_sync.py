#!/usr/bin/env python
"""Back-compat shim over ``nxdi_lint``'s ``host-sync`` pass.

DEPRECATED entry point: the checker now lives in
``neuronx_distributed_inference_tpu/analysis/passes/host_sync.py`` and
runs with every other pass through ``scripts/nxdi_lint.py``. The old
hand-maintained EXPECTED_REGIONS table (manually updated in PRs 5, 6
and 9) is GONE: the shared walker now DERIVES dispatch-region coverage —
a function that issues dispatch work without materializing must carry
the ``_dispatch`` prefix, so a rename moves lint coverage instead of
silently dropping it.

Usage::

    python scripts/check_host_sync.py                 # lint the default set
    python scripts/check_host_sync.py FILE...         # lint specific files
    python scripts/check_host_sync.py --list-regions  # show linted regions
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "scripts"))

from nxdi_lint import load_analysis  # noqa: E402


def main(argv=()) -> int:
    analysis = load_analysis()
    argv = [str(a) for a in argv]
    list_regions = "--list-regions" in argv
    argv = [a for a in argv if a != "--list-regions"]
    ctx = analysis.LintContext(REPO_ROOT)
    p = analysis.get_pass("host-sync")
    # argv paths resolve against CWD like the old standalone CLI (the
    # library API's relative paths resolve against the repo root)
    paths = [str(Path(a).resolve()) for a in argv] or None
    rc = 0
    if list_regions:
        # list AND still lint, like the old CLI: --list-regions in a CI
        # step must not report success on a tree with a violation
        import importlib
        hs_mod = importlib.import_module(type(p).__module__)
        for rel in (paths or p.default_paths):
            sf = ctx.source_for(Path(rel))
            if sf is None:
                print(f"check_host_sync: {rel}: missing", file=sys.stderr)
                rc = 1
                continue
            for name in hs_mod.region_functions(sf):
                print(f"{REPO_ROOT / sf.rel}: {name}")
    findings = analysis.run_single(ctx, p.name, paths=paths)
    for f in findings:
        rc = 1
        if f.line == 0:
            print(f"check_host_sync: {f.path}: missing", file=sys.stderr)
        else:
            print(f"{f.path}:{f.line}: {f.message}", file=sys.stderr)
    if rc == 0:
        n_files = len(paths) if paths else len(p.default_paths)
        print(f"check_host_sync: OK ({n_files} file(s) clean)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
