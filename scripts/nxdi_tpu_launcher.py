#!/usr/bin/env python
"""Multi-host launcher (reference: scripts/nxdi_distributed_launcher.py:29-85
— mpirun + NEURON_RT_ROOT_COMM_ID bootstrap; SURVEY §3.5).

TPU equivalent: ``jax.distributed.initialize`` over DCN. One process per
host; rank/coordinator come from flags or the environment
(NXDI_TPU_COORDINATOR / NXDI_TPU_NUM_PROCESSES / NXDI_TPU_PROCESS_ID, with
SLURM_* fallbacks). After initialization the target module runs with
jax.devices() spanning every host's chips.

Usage:
  python scripts/nxdi_tpu_launcher.py --coordinator host0:8476 \
      --num-processes 4 --process-id $RANK \
      -m neuronx_distributed_inference_tpu.inference_demo run ...
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def parse_args(argv):
    p = argparse.ArgumentParser(prog="nxdi_tpu_launcher")
    p.add_argument("--coordinator", default=os.environ.get(
        "NXDI_TPU_COORDINATOR"))
    p.add_argument("--num-processes", type=int, default=int(os.environ.get(
        "NXDI_TPU_NUM_PROCESSES",
        os.environ.get("SLURM_NTASKS", "1"))))
    p.add_argument("--process-id", type=int, default=int(os.environ.get(
        "NXDI_TPU_PROCESS_ID", os.environ.get("SLURM_PROCID", "0"))))
    p.add_argument("--local-device-ids", default=None,
                   help="comma-separated device ids bound to this process")
    p.add_argument("-m", "--module", required=True,
                   help="python module to run after distributed init")
    return p.parse_known_args(argv)


def main(argv=None) -> int:
    args, rest = parse_args(argv)
    import jax
    if args.num_processes > 1:
        kwargs = dict(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        if args.local_device_ids:
            kwargs["local_device_ids"] = [
                int(x) for x in args.local_device_ids.split(",")]
        jax.distributed.initialize(**kwargs)
        print(f"[launcher] process {jax.process_index()}/{jax.process_count()}"
              f" local_devices={len(jax.local_devices())}"
              f" global_devices={len(jax.devices())}")
    sys.argv = [args.module] + rest
    runpy.run_module(args.module, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
