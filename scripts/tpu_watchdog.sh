#!/bin/bash
# Poll the axon TPU tunnel; the moment it answers, run bench.py and persist
# the result to BENCH_interim.json (front-loading perf evidence per the
# round-4 outage lesson). Loops forever; caller kills it.
cd "$(dirname "$0")/.." || exit 1
while true; do
  if timeout 90 python - <<'EOF' 2>/tmp/tpu_health_err.log
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("TPU OK", jax.devices())
EOF
  then
    echo "$(date -Is) tunnel UP — running bench" >> /tmp/tpu_watchdog.log
    timeout 1800 python bench.py > /tmp/bench_out.json 2>/tmp/bench_err.log
    rc=$?
    if [ $rc -eq 0 ] && [ -s /tmp/bench_out.json ]; then
      cp /tmp/bench_out.json /root/repo/BENCH_interim.json
      echo "$(date -Is) bench OK" >> /tmp/tpu_watchdog.log
      exit 0
    fi
    echo "$(date -Is) bench rc=$rc" >> /tmp/tpu_watchdog.log
  else
    echo "$(date -Is) tunnel down" >> /tmp/tpu_watchdog.log
  fi
  sleep 120
done
