#!/bin/bash
# Poll the axon TPU tunnel; the moment it answers, run the FULL measurement
# battery and persist every result under artifacts/ (front-loading perf
# evidence per the round-4 outage lesson). Each battery member is retried
# on later passes until it has produced output; the loop only exits once
# EVERY member has succeeded. Caller kills it to stop early.
cd "$(dirname "$0")/.." || exit 1
mkdir -p artifacts
log() { echo "$(date -Is) $*" >> /tmp/tpu_watchdog.log; }

run_member() {  # run_member <name> <outfile> <timeout> <cmd...>
  local name=$1 out=$2 to=$3; shift 3
  if [ -s "$out" ]; then return 0; fi
  if timeout "$to" "$@" > "$out.tmp" 2>>/tmp/tpu_battery_err.log \
      && [ -s "$out.tmp" ]; then
    mv "$out.tmp" "$out"
    log "$name OK"
    return 0
  fi
  rm -f "$out.tmp"
  log "$name FAILED"
  return 1
}

while true; do
  if timeout 90 python - <<'EOF' 2>/tmp/tpu_health_err.log
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("TPU OK", jax.devices())
EOF
  then
    log "tunnel UP — running measurement battery"
    ok=1
    run_member bench artifacts/bench_r05_interim.json 1800 \
      python bench.py || ok=0
    if [ -s artifacts/bench_r05_interim.json ]; then
      cp artifacts/bench_r05_interim.json BENCH_interim.json
    fi
    run_member profile artifacts/profile_decode_r05.txt 1800 \
      python scripts/profile_decode.py || ok=0
    run_member moe artifacts/bench_moe_decode_r05.json 1800 \
      python scripts/bench_moe_decode.py || ok=0
    run_member flash_prefill artifacts/bench_flash_prefill_r05.txt 2400 \
      python scripts/bench_flash_prefill.py || ok=0
    run_member long_context artifacts/bench_long_context_r05.json 2400 \
      python scripts/bench_long_context.py || ok=0
    if [ "$ok" = 1 ]; then
      log "battery COMPLETE"
      exit 0
    fi
  else
    log "tunnel down"
  fi
  sleep 120
done
