#!/usr/bin/env python
"""Tier-1 lint: the serving surface raises ONLY the typed taxonomy.

Fails (rc 1) when any checked file contains ``raise ValueError(...)`` or
``raise RuntimeError(...)`` — those must be one of the
``resilience.errors`` types instead (``AdmissionError``,
``CapacityError``, ``DeadlineExceeded``, ``StepFailure``, ...), so an
engine can branch on exception type to pick a recovery path. Bare
re-raises (``raise`` with no expression) and every other exception class
are allowed.

Usage::

    python scripts/check_error_paths.py            # lint the default set
    python scripts/check_error_paths.py FILE...    # lint specific files

Wired into the test suite as a tier-1 test
(``tests/test_resilience.py::test_error_path_lint``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Sequence, Tuple

BANNED = ("ValueError", "RuntimeError")

REPO_ROOT = Path(__file__).resolve().parent.parent
DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/engine/queue.py",
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py",
    "neuronx_distributed_inference_tpu/serving/engine/streams.py",
    "neuronx_distributed_inference_tpu/serving/engine/frontend.py",
    "neuronx_distributed_inference_tpu/serving/speculation/__init__.py",
    "neuronx_distributed_inference_tpu/serving/speculation/proposer.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
    "neuronx_distributed_inference_tpu/modules/block_kv_cache.py",
)


def banned_raises(source: str) -> List[Tuple[int, str]]:
    """(lineno, exception name) for every ``raise`` of a banned builtin."""
    bad: List[Tuple[int, str]] = []
    for node in ast.walk(ast.parse(source)):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        target = node.exc
        if isinstance(target, ast.Call):
            target = target.func
        if isinstance(target, ast.Name) and target.id in BANNED:
            bad.append((node.lineno, target.id))
    return bad


def main(argv: Sequence[str] = ()) -> int:
    paths = [Path(p) for p in argv] if argv else \
        [REPO_ROOT / p for p in DEFAULT_PATHS]
    rc = 0
    for path in paths:
        if not path.exists():
            print(f"check_error_paths: {path}: missing", file=sys.stderr)
            rc = 1
            continue
        for lineno, name in banned_raises(path.read_text()):
            print(f"{path}:{lineno}: raise {name}(...) — use the typed "
                  "taxonomy in neuronx_distributed_inference_tpu/"
                  "resilience/errors.py", file=sys.stderr)
            rc = 1
    if rc == 0:
        print(f"check_error_paths: OK ({len(paths)} file(s) clean)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
