#!/usr/bin/env python
"""Back-compat shim over ``nxdi_lint``'s ``error-paths`` pass.

DEPRECATED entry point: the checker now lives in
``neuronx_distributed_inference_tpu/analysis/passes/error_paths.py`` and
runs with every other pass through ``scripts/nxdi_lint.py`` (suppression
syntax, ``--json`` artifact, one process for the whole suite). This CLI
is kept so existing invocations and muscle memory keep working; it
accepts the same arguments and prints the same messages.

Usage::

    python scripts/check_error_paths.py            # lint the default set
    python scripts/check_error_paths.py FILE...    # lint specific files
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "scripts"))

from nxdi_lint import load_analysis  # noqa: E402


def main(argv=()) -> int:
    analysis = load_analysis()
    ctx = analysis.LintContext(REPO_ROOT)
    p = analysis.get_pass("error-paths")
    # argv paths resolve against CWD like the old standalone CLI (the
    # library API's relative paths resolve against the repo root)
    paths = [str(Path(a).resolve()) for a in argv] or None
    findings = analysis.run_single(ctx, p.name, paths=paths)
    n_files = len(paths) if paths else len(p.default_paths)
    rc = 0
    for f in findings:
        rc = 1
        if f.line == 0:
            print(f"check_error_paths: {f.path}: missing", file=sys.stderr)
        else:
            print(f"{f.path}:{f.line}: {f.message}", file=sys.stderr)
    if rc == 0:
        print(f"check_error_paths: OK ({n_files} file(s) clean)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
