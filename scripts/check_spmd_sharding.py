#!/usr/bin/env python
"""Tier-1 SPMD regression guard: compile the multichip graphs on a CPU
mesh and assert on the partitioned HLO (ROADMAP item 2's lint).

Three failure channels, all ahead of hardware:

  1. **Involuntary full rematerialization** — the SPMD partitioner's
     "replicate the tensor and then partition it" last resort (the exact
     regression PR 5 fixed in moe.py's ``tkg_experts_local`` reshard,
     previously only visible as a ``MULTICHIP_r05.json`` tail grep).
     Detected on the compiler's warning channel (stderr captured at the
     fd level around each compile — glog W/E lines from
     ``spmd_partitioner.cc``) AND structurally in the optimized HLO (a
     full-mesh ``all-gather`` feeding a ``dynamic-slice`` is
     replicate-then-partition by construction).
  2. **Collective census drift** — every collective of every pinned
     graph (kind x mesh-axis comm group, counts + payload bytes, via
     ``telemetry/observatory.census_collectives``) is diffed against the
     committed golden ``artifacts/spmd_golden.json``. A new collective,
     a changed count, or payload bytes drifting past ±25% is a red test
     — not a folklore bench delta three rounds later. Improvements fail
     too (symmetric, like check_metric_names): rerun with
     ``--update-golden`` to re-earn the golden.
  3. **SPMD warning channel** — any other ``[SPMD]`` partitioner
     complaint during the pinned compiles fails the run.

Pinned graph set (tiny configs reusing ``__graft_entry__``'s mesh
plumbing; all CPU-mesh compiles, no execution):

  * ``dense_tkg_dp2tp2``  — dense decode step, dp2 x tp2
  * ``moe_tkg_dp2ep2tp2`` — hybrid-MoE decode (``tkg_experts_local``
    reshard — the PR-5 remat surface), dp2 x ep2 x tp2 (8 devices)
  * ``paged_decode_dp2tp2`` / ``paged_loop_dp2tp2`` — the serving/paged
    step + fused decode loop on a mesh (VERDICT weak #6: first compiled
    coverage of the paged path on multi-device)
  * ``cb_decode_dp2tp2``  — continuous-batching decode step
  * ``paged_spec_verify_dp2tp2`` — the speculative ragged k+1-wide
    verify dispatch (serving/speculation/) at the default self-draft
    ladder top (W=4)
  * ``paged_ragged_dp2tp2`` — the ragged UNIFIED mixed
    prefill+decode+verify dispatch (serving/ragged/,
    ``model_base.paged_ragged_step``) at the same W=4
  * ``paged_ragged_lora_dp2tp2`` — the same unified dispatch on a
    LoRA-built app with per-row ``adapter_ids`` (multi-LoRA serving,
    serving/lora_pool.py): the stacked (A, B) gather + delta einsum
    must partition cleanly (lora_A replicated, lora_B sharded with its
    base projection) and add NO collective over the plain ragged graph
  * ``cb_decode_int8_dp2tp2`` / ``paged_decode_fp8_dp2tp2`` — the same
    decode steps with ``CollectiveConfig`` quantized collectives (int8 /
    fp8 wire payloads): the row-parallel output all-reduces lower to
    s8/f8 ppermute rings, and the golden pins the wire-byte reduction
    (the census keys carry the payload dtype, so an accidental fall-back
    to fp32 collectives is a red diff, not a silent 4x wire regression)

Usage::

    python scripts/check_spmd_sharding.py                 # full lint
    python scripts/check_spmd_sharding.py --graphs cb_decode_dp2tp2
    python scripts/check_spmd_sharding.py --update-golden # re-earn golden
    python scripts/check_spmd_sharding.py --hlo-file F    # doctored HLO:
        run the remat detector + census parse on a saved HLO text only
    python scripts/check_spmd_sharding.py --census-json F # diff a census
        snapshot against the golden without compiling
    python scripts/check_spmd_sharding.py --list          # pinned names

Wired into the suite as a tier-1 test
(``tests/test_sharding_observatory.py``), including a doctored-HLO
negative test proving the remat detector fires.

Relationship to ``scripts/nxdi_lint.py``: this script stays the COMPILE
lint (a CPU-mesh XLA compile set is minutes of work, not an AST pass),
while its static golden/pin consistency slice — golden schema, PINNED
<-> golden graph-set sync, census well-formedness — runs in-process with
every other pass as ``nxdi_lint``'s ``spmd-golden`` pass.
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))    # package + __graft_entry__ imports

GOLDEN_PATH = REPO_ROOT / "artifacts" / "spmd_golden.json"
GOLDEN_SCHEMA = "nxdi-spmd-golden-v1"
BYTES_TOL = 1.25          # golden payload-bytes drift tolerance (either way)


# ---------------------------------------------------------------------------
# structural remat detector (doctorable; mirrors the warning channel)
# ---------------------------------------------------------------------------

_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(?P<name>%?[\w.-]+)\s*=\s*(?:\([^)]*\)|\S+)\s+"
    r"(?P<op>[\w-]+)\((?P<operands>[^)]*)\)")
_AG_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{(\{[^=]*?\})\}|\[([0-9,]+)\]<=)")


def _all_gather_spans(line: str, num_partitions: Optional[int]) -> bool:
    """True when the all-gather's replica group spans every partition —
    the replicate step of replicate-then-partition. Subset-axis gathers
    (a legit ep all-gather + local slice) do not match."""
    if num_partitions is None:
        return True          # doctored mode without a mesh: any gather
    m = _AG_GROUPS_RE.search(line)
    if not m:
        return False
    if m.group(1) is not None:
        groups = [g for g in re.findall(r"\{([0-9,\s]*)\}", m.group(1))]
        sizes = [len([x for x in g.split(",") if x.strip()])
                 for g in groups]
        return bool(sizes) and max(sizes) >= num_partitions
    dims = [int(x) for x in m.group(2).split(",")]
    return len(dims) >= 2 and dims[-1] >= num_partitions or \
        (len(dims) == 1 and dims[0] >= num_partitions)


def find_replicate_then_partition(
        hlo_text: str, num_partitions: Optional[int] = None
) -> List[str]:
    """Structural replicate-then-partition findings: a full-mesh
    ``all-gather`` whose value feeds a ``dynamic-slice`` — the HLO shape
    of the partitioner's remat fallback (gather everything, re-slice per
    partition). Returns human-readable finding strings. Instruction
    names are matched with the ``%`` sigil stripped — some dump flavors
    omit it (the census regex tolerates both; so must this detector)."""
    gathers: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.match(line)
        if not m:
            continue
        if m.group("op") in ("all-gather", "all-gather-start") and \
                _all_gather_spans(line, num_partitions):
            gathers[m.group("name").lstrip("%")] = line.strip()
    if not gathers:
        return []
    # async pairs: the consumer slices the -done instruction's value,
    # never the -start's — alias each -done to its flagged -start. The
    # -done operand is TUPLE-typed, which defeats _HLO_OP_RE's
    # first-paren operand capture, so scan the call body directly.
    for line in hlo_text.splitlines():
        if "all-gather-done(" not in line:
            continue
        m = _HLO_OP_RE.match(line)
        if not m:
            continue
        body = line.split("all-gather-done(", 1)[1]
        srcs = {t.lstrip("%") for t in re.findall(r"%?[\w.-]+", body)
                if any(c.isalpha() for c in t)}
        if srcs & set(gathers):
            gathers.setdefault(m.group("name").lstrip("%"), line.strip())
    findings = []
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.match(line)
        if not m or m.group("op") != "dynamic-slice":
            continue
        operands = {t.strip().split(" ")[-1].lstrip("%")
                    for t in m.group("operands").split(",")}
        for name in gathers:
            if name in operands:
                findings.append(
                    f"full-mesh all-gather {name} feeds dynamic-slice "
                    f"{m.group('name')} (replicate-then-partition)")
    return findings


# ---------------------------------------------------------------------------
# pinned multichip graphs (tiny configs; CPU mesh)
# ---------------------------------------------------------------------------

def _tiny_hf():
    return dict(model_type="llama", hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, head_dim=16, vocab_size=512,
                rms_norm_eps=1e-5, rope_theta=10000.0, hidden_act="silu",
                tie_word_embeddings=False, torch_dtype="float32")


def _entry_graph(moe: bool):
    """Dense / hybrid-MoE decode step over __graft_entry__'s mesh
    plumbing and tiny configs (the multichip-runner graphs)."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    import __graft_entry__ as ge
    from neuronx_distributed_inference_tpu.models import model_base
    from neuronx_distributed_inference_tpu.parallel.mesh import (MeshConfig,
                                                                 build_mesh)
    ep = 2 if moe else 1
    n = 4 * ep
    mesh = build_mesh(MeshConfig(tp=2, cp=1, dp=2, ep=ep),
                      devices=jax.devices()[:n])
    batch = 4
    with jax.sharding.set_mesh(mesh):
        tcfg, spec, params, cache = ge._make(
            tp=2 * ep, mesh=mesh, batch=batch, seq=32, moe=moe,
            hybrid_moe=moe)
        fn = jax.jit(partial(model_base.token_generation_step, spec, tcfg),
                     donate_argnums=(1,))
        args = (params, cache, jnp.zeros((batch, 1), jnp.int32),
                jnp.full((batch, 1), 16, jnp.int32),
                jnp.arange(batch, dtype=jnp.int32), None,
                jax.random.PRNGKey(1))
    return mesh, fn, args, {}


_APP_CACHE: Dict[Tuple[bool, Optional[str]], Any] = {}


def _serving_app(paged: bool, collective_dtype: Optional[str] = None,
                 lora: bool = False):
    key = (paged, collective_dtype, lora)
    if key in _APP_CACHE:         # each app serves several pinned graphs
        return _APP_CACHE[key]    # — one weights+cache init per config
    from neuronx_distributed_inference_tpu.config import (CollectiveConfig,
                                                          LoraServingConfig,
                                                          TpuConfig)
    from neuronx_distributed_inference_tpu.models.application import (
        CausalLMApplication, PagedCausalLMApplication)
    from neuronx_distributed_inference_tpu.models.llama import (
        LlamaFamily, LlamaInferenceConfig)
    from neuronx_distributed_inference_tpu.parallel.mesh import \
        mesh_from_config
    extra = ({"is_block_kv_layout": True, "pa_block_size": 16,
              "is_prefix_caching": True}
             if paged else {"is_continuous_batching": True})
    if collective_dtype is not None:
        extra["collective_config"] = CollectiveConfig(dtype=collective_dtype)
    if lora:
        # a SEPARATE app (not the plain paged one): the stacked adapter
        # arrays ride the params pytree, so grafting them onto the
        # shared app would shift every existing pinned graph's signature
        extra["lora_config"] = LoraServingConfig(
            max_loras=3, max_lora_rank=4,
            target_modules=["q_proj", "v_proj"])
    tcfg = TpuConfig(batch_size=2, seq_len=128, dtype="float32",
                     enable_bucketing=True, context_encoding_buckets=[16],
                     decode_chunk_tokens=4, tp_degree=4,
                     attention_dp_degree=2, **extra)
    mesh = mesh_from_config(tcfg)
    cls = PagedCausalLMApplication if paged else CausalLMApplication
    app = cls(None, LlamaInferenceConfig(tcfg, **_tiny_hf()), LlamaFamily,
              mesh=mesh)
    app.init_random_weights(seed=0).init_cache()
    return _APP_CACHE.setdefault(key, app)


def _app_graph(paged: bool, kind: str,
               collective_dtype: Optional[str] = None,
               lora: bool = False):
    from neuronx_distributed_inference_tpu.telemetry import observatory
    app = _serving_app(paged, collective_dtype, lora)
    for k, bucket, build in observatory._graph_entries(app):
        if k == kind:
            fn, args, kwargs = build()
            return app.mesh, fn, args, kwargs
    raise LookupError(f"graph kind {kind!r} not in the app's ladder")


PINNED: Dict[str, Any] = {
    # name -> zero-arg builder returning (mesh, jitted_fn, args, kwargs)
    "dense_tkg_dp2tp2": lambda: _entry_graph(moe=False),
    "moe_tkg_dp2ep2tp2": lambda: _entry_graph(moe=True),
    "paged_decode_dp2tp2": lambda: _app_graph(True, "paged"),
    "paged_loop_dp2tp2": lambda: _app_graph(True, "paged_loop"),
    "cb_decode_dp2tp2": lambda: _app_graph(False, "decode"),
    "paged_spec_verify_dp2tp2": lambda: _app_graph(True, "spec_verify"),
    "paged_ragged_dp2tp2": lambda: _app_graph(True, "ragged"),
    # the multi-LoRA ragged dispatch: per-row gathered (A, B) factors
    # (lora_A replicated, lora_B row-sharded over tp) riding the SAME
    # unified graph — pins that the adapter gather adds no collective
    # beyond the existing row-parallel reduces
    "paged_ragged_lora_dp2tp2": lambda: _app_graph(True, "ragged_lora",
                                                   lora=True),
    # quantized-collective decode graphs (EQuARX-style s8/f8 ppermute
    # rings replacing the row-parallel fp32 all-reduces) — the dtype leg
    # of the census keys pins the wire-byte reduction
    "cb_decode_int8_dp2tp2": lambda: _app_graph(False, "decode", "int8"),
    "paged_decode_fp8_dp2tp2": lambda: _app_graph(True, "paged", "fp8"),
}


def compile_pinned(name: str) -> Tuple[Any, str, str]:
    """Compile one pinned graph on its CPU mesh. Returns (mesh, optimized
    HLO text, captured compiler stderr)."""
    import jax
    from neuronx_distributed_inference_tpu.telemetry.observatory import \
        capture_compiler_stderr
    mesh, fn, args, kwargs = PINNED[name]()
    with capture_compiler_stderr() as captured:
        with jax.sharding.set_mesh(mesh):
            compiled = fn.lower(*args, **kwargs).compile()
    return mesh, compiled.as_text(), captured[0]


# ---------------------------------------------------------------------------
# golden census diff
# ---------------------------------------------------------------------------

def diff_census(graph: str, golden: Dict[str, Dict[str, Any]],
                current: Dict[str, Dict[str, Any]],
                bytes_tol: float = BYTES_TOL) -> List[str]:
    """Symmetric census diff for one graph: any new/missing collective
    key, any count change, payload bytes drifting past ``bytes_tol``
    (ratio, either direction) is a finding."""
    msgs = []
    for key in sorted(set(golden) | set(current)):
        g, c = golden.get(key), current.get(key)
        if g is None:
            msgs.append(f"{graph}: NEW collective {key}: {c} (not in "
                        "golden — a collective was added to this graph)")
        elif c is None:
            msgs.append(f"{graph}: collective {key} DISAPPEARED (golden "
                        f"had {g}; improvement? --update-golden)")
        else:
            if g["count"] != c["count"]:
                msgs.append(f"{graph}: {key} count {g['count']} -> "
                            f"{c['count']}")
            gb, cb = max(g["bytes"], 1), max(c["bytes"], 1)
            ratio = cb / gb
            if ratio > bytes_tol or ratio < 1.0 / bytes_tol:
                msgs.append(f"{graph}: {key} payload bytes {g['bytes']} "
                            f"-> {c['bytes']} ({ratio:.2f}x)")
    return msgs


def load_golden(path: Path) -> Dict[str, Any]:
    data = json.loads(path.read_text())
    if data.get("schema") != GOLDEN_SCHEMA:
        raise ValueError(f"{path}: schema {data.get('schema')!r} != "
                         f"{GOLDEN_SCHEMA!r}")
    return data


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _setup_jax():
    from neuronx_distributed_inference_tpu.compat import (ensure_jax_compat,
                                                          force_cpu_devices)
    force_cpu_devices(8)
    ensure_jax_compat()
    import jax
    if len(jax.devices()) < 8:
        print(f"check_spmd_sharding: SKIP — need 8 virtual CPU devices, "
              f"got {len(jax.devices())} (backend initialized before "
              "force_cpu_devices could run?)", file=sys.stderr)
        return False
    return True


def _lint_hlo(name: str, hlo: str, stderr_text: str,
              num_partitions: Optional[int]) -> List[str]:
    # one copy of the warning spellings, shared with the multichip runner
    from neuronx_distributed_inference_tpu.telemetry.observatory import (
        REMAT_WARNING_RE as REMAT_RE, SPMD_CHANNEL_RE as SPMD_WARNING_RE)
    findings = [f"{name}: {m}" for m in
                find_replicate_then_partition(hlo, num_partitions)]
    remat = REMAT_RE.findall(stderr_text)
    if remat:
        findings.append(
            f"{name}: compiler reported involuntary full "
            f"rematerialization x{len(remat)} (SPMD replicate-then-"
            "partition fallback — see the re-emitted warnings above)")
    spmd_lines = [l for l in stderr_text.splitlines()
                  if SPMD_WARNING_RE.search(l) and not REMAT_RE.search(l)]
    if spmd_lines:
        findings.append(f"{name}: {len(spmd_lines)} other [SPMD] "
                        f"compiler warning(s): {spmd_lines[0][:160]}")
    return findings


def main(argv: Sequence[str] = ()) -> int:
    argv = list(argv)

    def opt(flag: str) -> Optional[str]:
        if flag not in argv:
            return None
        i = argv.index(flag)
        if i + 1 >= len(argv):
            print(f"check_spmd_sharding: {flag} needs a value",
                  file=sys.stderr)
            raise SystemExit(2)
        return argv[i + 1]

    golden_path = Path(opt("--golden") or GOLDEN_PATH)

    if "--list" in argv:
        print("\n".join(PINNED))
        return 0

    hlo_file = opt("--hlo-file")
    if hlo_file is not None:
        # doctored-HLO mode: detectors only, no compile, no golden
        text = Path(hlo_file).read_text()
        np_s = opt("--num-partitions")
        findings = _lint_hlo(Path(hlo_file).name, text, "",
                             int(np_s) if np_s else None)
        for f in findings:
            print(f"check_spmd_sharding: {f}", file=sys.stderr)
        if findings:
            return 1
        print("check_spmd_sharding: OK (no remat pattern in "
              f"{hlo_file})")
        return 0

    census_file = opt("--census-json")
    if census_file is not None:
        # diff-only mode: {"graphs": {name: {"collectives": {...}}}}
        if not golden_path.exists():
            print(f"check_spmd_sharding: golden {golden_path} missing — "
                  "run with --update-golden first", file=sys.stderr)
            return 2
        try:
            golden = load_golden(golden_path)
        except ValueError as e:
            print(f"check_spmd_sharding: {e}", file=sys.stderr)
            return 2
        snap = json.loads(Path(census_file).read_text())
        snap_graphs = snap.get("graphs")
        if not isinstance(snap_graphs, dict):
            print(f"check_spmd_sharding: {census_file} has no 'graphs' "
                  "table — expected a census snapshot shaped like the "
                  "golden, not e.g. the sharding-report artifact",
                  file=sys.stderr)
            return 2
        msgs: List[str] = []
        # symmetric over graphs too: a graph the golden pins but the
        # snapshot dropped (partial census) is as red as a new one
        for gname in sorted(set(golden["graphs"]) | set(snap_graphs)):
            gentry = golden["graphs"].get(gname)
            gdata = snap_graphs.get(gname)
            if gentry is None:
                msgs.append(f"{gname}: not in the golden — run "
                            "--update-golden to pin it")
            elif gdata is None:
                msgs.append(f"{gname}: MISSING from the snapshot (the "
                            "golden pins it — partial census?)")
            elif not isinstance(gdata.get("collectives"), dict):
                print(f"check_spmd_sharding: {census_file}: graph "
                      f"{gname} has no 'collectives' table",
                      file=sys.stderr)
                return 2
            else:
                msgs += diff_census(gname, gentry["collectives"],
                                    gdata["collectives"])
        for m in msgs:
            print(f"check_spmd_sharding: {m}", file=sys.stderr)
        if msgs:
            return 1
        print(f"check_spmd_sharding: OK ({len(snap_graphs)} census "
              "snapshots match the golden)")
        return 0

    if not _setup_jax():
        return 0
    from neuronx_distributed_inference_tpu.telemetry import observatory

    graphs_arg = opt("--graphs")
    names = (graphs_arg or ",".join(PINNED)).split(",")
    unknown = [n for n in names if n not in PINNED]
    if unknown:
        print(f"check_spmd_sharding: unknown graph(s) {unknown}; "
              f"pinned set: {list(PINNED)}", file=sys.stderr)
        return 2

    update = "--update-golden" in argv
    golden = None
    if not update:
        if not golden_path.exists():
            print(f"check_spmd_sharding: golden {golden_path} missing — "
                  "run with --update-golden first", file=sys.stderr)
            return 2
        golden = load_golden(golden_path)

    findings: List[str] = []
    results: Dict[str, Any] = {}
    for name in names:
        import numpy as np
        mesh, hlo, stderr_text = compile_pinned(name)
        n_part = int(np.prod(mesh.devices.shape))
        census = observatory.aggregate_census(
            observatory.census_collectives(hlo, mesh))
        results[name] = {
            "mesh": {a: int(s) for a, s in
                     zip(mesh.axis_names, mesh.devices.shape) if s > 1},
            "collectives": census,
        }
        findings += _lint_hlo(name, hlo, stderr_text, n_part)
        if not census:
            findings.append(f"{name}: zero collectives censused on a "
                            f"{n_part}-device mesh — the graph is not "
                            "actually partitioned (mesh plumbing broke?)")
        if golden is not None:
            gentry = golden["graphs"].get(name)
            if gentry is None:
                findings.append(f"{name}: not in the golden — run "
                                "--update-golden to pin it")
            else:
                findings += diff_census(name, gentry["collectives"],
                                        census)

    for f in findings:
        print(f"check_spmd_sharding: {f}", file=sys.stderr)
    if findings:
        if update:
            # never pin a census the warning/remat channel rejects — a
            # tainted golden would pass cleanly on the next plain run
            print("check_spmd_sharding: golden NOT updated — fix the "
                  "findings above first", file=sys.stderr)
        return 1

    if update:
        # a subset update (--graphs) merges into the existing golden —
        # re-earning one graph must not drop the other pinned ones; a
        # FULL update replaces the table, so a graph dropped from PINNED
        # can be pruned through the documented re-earn flow
        merged = dict(results)
        if graphs_arg is not None and golden_path.exists():
            merged = {**load_golden(golden_path)["graphs"], **results}
        payload = {"schema": GOLDEN_SCHEMA, "graphs": merged,
                   "bytes_tol": BYTES_TOL}
        golden_path.parent.mkdir(parents=True, exist_ok=True)
        golden_path.write_text(json.dumps(payload, indent=1,
                                          sort_keys=True) + "\n")
        print(f"check_spmd_sharding: golden updated ({len(results)} of "
              f"{len(merged)} graphs) -> {golden_path}")
    n_coll = sum(c["count"] for r in results.values()
                 for c in r["collectives"].values())
    print(f"check_spmd_sharding: OK ({len(results)} multichip graphs, "
          f"{n_coll} collectives censused, no remat pattern)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
