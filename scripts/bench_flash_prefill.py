#!/usr/bin/env python
"""Micro-bench: Pallas flash prefill kernel (causal DMA elision) vs the XLA
attention path on the real chip — the VERDICT r3 win-or-delete data.
Prints one JSON line per (seq, window)."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.ops import attention as attn_ops
from neuronx_distributed_inference_tpu.ops import flash_attention as fa

B, HQ, HKV, D = 1, 32, 8, 128


def run(s, window=0, iters=16):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, s, HQ, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, s, HKV, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, s, HKV, D)), jnp.bfloat16)
    scale = D ** -0.5
    pos = jnp.broadcast_to(jnp.arange(s), (B, s))
    mask = attn_ops.causal_mask(pos, pos, None, window, 0)

    def mk(fn, n):
        def loop():
            def body(acc, _):
                o = fn(q + acc * 1e-9)
                return acc + o.sum().astype(jnp.float32), None
            return jax.lax.scan(body, jnp.zeros(()), None, length=n)[0]
        return jax.jit(loop)

    def t(f):
        t0 = time.perf_counter()
        np.asarray(f())
        return time.perf_counter() - t0

    res = {}
    variants = {
        "kernel": lambda qq: fa.flash_attention(
            qq, k, v, scale=scale, causal=True, window=window),
        "xla": lambda qq: attn_ops.mha(qq, k, v, mask, scale),
    }
    for name, fn in variants.items():
        n1, n2 = iters // 4, iters
        f1, f2 = mk(fn, n1), mk(fn, n2)
        np.asarray(f1()); np.asarray(f2())
        t1 = min(t(f1) for _ in range(3))
        t2 = min(t(f2) for _ in range(3))
        res[name] = (t2 - t1) / (n2 - n1) * 1e3
    return res


if __name__ == "__main__":
    for s, w in ((1024, 0), (2048, 0), (4096, 0), (8192, 0), (4096, 1024)):
        r = run(s, w)
        print(json.dumps({
            "seq": s, "window": w,
            "kernel_ms": round(r["kernel"], 3),
            "xla_ms": round(r["xla"], 3),
            "speedup": round(r["xla"] / r["kernel"], 3)}))
