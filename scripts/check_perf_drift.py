#!/usr/bin/env python
"""Perf-drift gate over the committed baseline (ISSUE 16).

Two layers, split by cost:

  1. **Static** (sub-second, runs in tier-1 through ``nxdi_lint``'s
     ``perf-drift`` pass): the committed
     ``artifacts/perf_baseline_r16.json`` is schema-valid, every tracked
     metric is gated (or marked informational on purpose), and its
     ``golden_collective_bytes`` pin matches the SPMD golden. This
     script runs that layer first, always.
  2. **Live** (tens of seconds of jax work, opt-in): re-measure the
     tracked proxies with ``bench.perf_measure()`` — the ragged
     mixed-load structural counts plus the precompile ladder — and diff
     against the baseline with :func:`compare`. Symmetric: an
     improvement past tolerance is red too (re-earn the baseline with
     ``python bench.py --perf-snapshot``, deliberately, in its own
     commit — the README "Cold start, memory & drift" section has the
     ritual).

Usage::

    python scripts/check_perf_drift.py             # static + live measure
    python scripts/check_perf_drift.py --static    # artifact checks only
    python scripts/check_perf_drift.py --current F # diff a saved
        {metric: value} JSON against the baseline without measuring
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Dict, List

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "scripts"))

from nxdi_lint import load_analysis  # noqa: E402

BASELINE = REPO_ROOT / "artifacts" / "perf_baseline_r16.json"


def compare(baseline: Dict, current: Dict[str, float]) -> List[str]:
    """Pure drift diff: one message per gated metric outside its
    symmetric relative tolerance (or missing from ``current``).
    ``baseline`` is the full snapshot payload; ``current`` a flat
    ``{metric: value}`` dict (``bench.perf_measure()``'s shape)."""
    out: List[str] = []
    metrics = baseline.get("metrics", {})
    tolerances = baseline.get("tolerances", {})
    for name in sorted(metrics):
        tol = tolerances.get(name)
        if tol is None:
            continue                      # informational, on purpose
        if name not in current:
            out.append(f"{name}: missing from the current measurement")
            continue
        base, cur = metrics[name], current[name]
        if base == 0:
            drifted, desc = cur != 0, f"{cur} vs baseline 0"
        else:
            rel = abs(cur - base) / abs(base)
            drifted = rel > tol
            desc = (f"{cur} vs baseline {base} "
                    f"({rel:+.1%} > ±{tol:.0%} tolerance)")
        if drifted:
            out.append(
                f"{name}: {desc} — a real regression, or a deliberate "
                "change that must re-earn the baseline "
                "(python bench.py --perf-snapshot)")
    return out


def main(argv=()) -> int:
    argv = [str(a) for a in argv]
    analysis = load_analysis()
    p = analysis.get_pass("perf-drift")
    findings = p.run(analysis.LintContext(REPO_ROOT))
    for f in findings:
        print(f"check_perf_drift: {f.message}", file=sys.stderr)
    if findings:
        return 1
    if "--static" in argv:
        print("check_perf_drift: OK (static; baseline well-formed)")
        return 0
    baseline = json.loads(BASELINE.read_text())
    if "--current" in argv:
        current = json.loads(
            Path(argv[argv.index("--current") + 1]).read_text())
    else:
        sys.path.insert(0, str(REPO_ROOT))
        import bench
        current = bench.perf_measure()
    drift = compare(baseline, current)
    for msg in drift:
        print(f"check_perf_drift: {msg}", file=sys.stderr)
    if drift:
        return 1
    gated = sum(1 for t in baseline.get("tolerances", {}).values()
                if t is not None)
    print(f"check_perf_drift: OK ({gated} gated metrics within tolerance)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
