#!/usr/bin/env python
"""nxdi-lint driver: run every static-analysis pass in ONE process.

The unified front door for the framework in
``neuronx_distributed_inference_tpu/analysis/`` — shared AST walker,
``Pass`` registry, per-line ``# nxdi-lint: disable=<pass>`` suppressions
with an unused-suppression check, and the ``nxdi-lint-v1`` ``--json``
artifact. All passes run in-process (no per-lint subprocess, and via
:func:`load_analysis` no jax import either — the whole run is well under
a second against the 870s tier-1 budget).

Passes (see README "Static analysis" for the catalog):

  error-paths, host-sync, metric-names, spmd-golden   (ported checkers)
  donation-safety, aliasing-safety, recompile-hazard  (tracing safety)
  unused-suppression                                   (always-on check)

The old per-checker CLIs (``check_error_paths.py``, ``check_host_sync
.py``, ``check_metric_names.py``) remain as thin back-compat shims over
the same passes; the CPU-mesh compile lint stays in
``check_spmd_sharding.py`` (its static golden/pin consistency slice runs
here as ``spmd-golden``).

Usage::

    python scripts/nxdi_lint.py                    # --all (default)
    python scripts/nxdi_lint.py --passes host-sync,donation-safety
    python scripts/nxdi_lint.py --list             # pass catalog
    python scripts/nxdi_lint.py --all --json artifacts/lint_report_r10.json

Wired into the suite as tier-1 (``tests/test_nxdi_lint.py``) and into
``bench.py --lint-report`` so findings trend across rounds like bench
numbers.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
_PKG_DIR = (REPO_ROOT / "neuronx_distributed_inference_tpu" / "analysis")


def load_analysis():
    """Import the analysis package WITHOUT executing the parent
    package's ``__init__`` (which pulls jax): registered under the
    synthetic top-level name ``nxdi_analysis`` so its relative imports
    resolve. Reuses the already-imported package when the caller (e.g.
    the test suite) imported it the normal way."""
    for name in ("nxdi_analysis", "neuronx_distributed_inference_tpu.analysis"):
        if name in sys.modules:
            return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        "nxdi_analysis", _PKG_DIR / "__init__.py",
        submodule_search_locations=[str(_PKG_DIR)])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["nxdi_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def run(names=None, repo_root=REPO_ROOT):
    """In-process API (used by bench.py --lint-report and the tests):
    returns the analysis Report."""
    return load_analysis().run_passes(repo_root, names=names)


def write_artifact(report, path) -> None:
    """THE ``nxdi-lint-v1`` artifact serialization — ``--json`` and
    ``bench.py --lint-report`` both write through here, so exactly one
    writer owns the schema at ``artifacts/lint_report_*.json``."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report.to_json(), indent=1,
                               sort_keys=True) + "\n")


def main(argv=()) -> int:
    argv = list(argv)
    analysis = load_analysis()
    if "--list" in argv:
        for name, p in analysis.all_passes().items():
            print(f"{name}: {p.description}")
        print(f"{analysis.UNUSED_PASS}: every nxdi-lint disable comment "
              "still absorbs a finding")
        return 0
    names = None
    if "--passes" in argv:
        i = argv.index("--passes")
        if i + 1 >= len(argv):
            print("nxdi_lint: --passes needs a comma-separated value",
                  file=sys.stderr)
            return 2
        names = [n.strip() for n in argv[i + 1].split(",") if n.strip()]
    json_path = None
    if "--json" in argv:
        i = argv.index("--json")
        if i + 1 >= len(argv):
            print("nxdi_lint: --json needs a path", file=sys.stderr)
            return 2
        json_path = Path(argv[i + 1])
    try:
        report = analysis.run_passes(REPO_ROOT, names=names)
    except KeyError as e:
        print(f"nxdi_lint: {e.args[0]}", file=sys.stderr)
        return 2
    for f in report.findings:
        print(f"nxdi_lint: {f.render()}", file=sys.stderr)
    if json_path is not None:
        write_artifact(report, json_path)
    n_passes = len(report.passes)
    verdict = "OK" if not report.findings else "FAIL"
    print(f"nxdi_lint: {verdict} ({n_passes} passes, "
          f"{len(report.files)} files, {len(report.findings)} finding(s), "
          f"{len(report.suppressed)} suppressed)")
    return report.rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
