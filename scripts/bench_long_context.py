#!/usr/bin/env python
"""Long-context prefill benchmark on the real chip (VERDICT r4 ask #5):
8k-token windowed context encoding on the bench model geometry — prefill
tokens/s and wall time, printed as one JSON line."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from neuronx_distributed_inference_tpu.config import TpuConfig
from neuronx_distributed_inference_tpu.models.application import \
    CausalLMApplication
from neuronx_distributed_inference_tpu.models.llama import (LlamaFamily,
                                                            LlamaInferenceConfig)

S = int(os.environ.get("NXDI_LONG_S", "8192"))
W = int(os.environ.get("NXDI_LONG_W", "2048"))
hf_attrs = dict(
    model_type="llama", hidden_size=2048, intermediate_size=8192,
    num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
    head_dim=64, vocab_size=128256, rms_norm_eps=1e-5, rope_theta=500000.0,
    hidden_act="silu", tie_word_embeddings=True,
)
tcfg = TpuConfig(batch_size=1, seq_len=S + 64, max_context_length=S,
                 dtype="bfloat16", enable_bucketing=False,
                 windowed_context_encoding=W)
app = CausalLMApplication(None, LlamaInferenceConfig(tcfg, **hf_attrs),
                          LlamaFamily)
app.init_random_weights(0).init_cache()
prompt = np.random.default_rng(0).integers(0, 1000, size=(1, S),
                                           dtype=np.int32)

t0 = time.perf_counter()
out = app.generate(prompt, max_new_tokens=2)
compile_s = time.perf_counter() - t0

times = []
for _ in range(3):
    app.reset()
    t0 = time.perf_counter()
    out = app.generate(prompt, max_new_tokens=2)
    times.append(time.perf_counter() - t0)
best = min(times)
print(json.dumps({
    "metric": f"long_context_prefill_{S}_tok_s",
    "value": round(S / best, 1),
    "unit": "tokens/s",
    "vs_baseline": None,
    "details": {"seq": S, "window": W, "wall_s": round(best, 2),
                "compile_plus_first_s": round(compile_s, 1),
                "includes": "windowed CTE prefill + 2 decode steps"},
}))
