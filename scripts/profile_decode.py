#!/usr/bin/env python
"""Decompose decode-step time on the real chip: full step vs layers-only vs
lm_head-only vs sampling-only, each amortized over N in-graph iterations so
host/tunnel latency doesn't pollute the numbers."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.config import TpuConfig
from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig
from neuronx_distributed_inference_tpu.models import model_base
from neuronx_distributed_inference_tpu.modules.kv_cache import KVCacheSpec, init_cache
from neuronx_distributed_inference_tpu.parallel.mesh import MeshConfig, build_mesh

batch, seq_len = 2, 1024
hf_attrs = dict(
    model_type="llama", hidden_size=2048, intermediate_size=8192,
    num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
    head_dim=64, vocab_size=128256, rms_norm_eps=1e-5, rope_theta=500000.0,
    hidden_act="silu", tie_word_embeddings=True,
)
tcfg = TpuConfig(batch_size=batch, seq_len=seq_len, max_context_length=128,
                 dtype="bfloat16", enable_bucketing=False)
icfg = LlamaInferenceConfig(tcfg, **hf_attrs)
mesh = build_mesh(MeshConfig())
spec = model_base.spec_from_config(icfg)
params = model_base.init_params(spec, jax.random.PRNGKey(0), mesh)
kvspec = KVCacheSpec(spec.num_layers, batch, seq_len, spec.gqa.num_kv_heads,
                     spec.head_dim)
cache = init_cache(kvspec, mesh)

N1, N2 = 16, 80


def _scalarize(out):
    leaves = jax.tree.leaves(out)
    return sum(jnp.sum(x).astype(jnp.float32) for x in leaves)


def timed(name, make_fn, *args):
    """make_fn(n) -> jitted fn running n iterations; returns a scalar.
    block_until_ready lies over the axon tunnel, so sync via a tiny fetch;
    slope between two iteration counts cancels the fixed fetch latency."""
    fns = {n: make_fn(n) for n in (N1, N2)}
    for n, fn in fns.items():
        np.asarray(fn(*args))  # compile + warm
    t = {}
    for n, fn in fns.items():
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(*args))
            reps.append(time.perf_counter() - t0)
        t[n] = min(reps)
    per_step = (t[N2] - t[N1]) / (N2 - N1) * 1e3
    print(f"{name:30s} {per_step:8.3f} ms/step   (t{N1}={t[N1]*1e3:.1f}ms t{N2}={t[N2]*1e3:.1f}ms)")
    return per_step


def make_full_loop(n):
    def full_loop(params, cache):
        def step(carry, _):
            tok, pos, cch = carry
            out = model_base.token_generation_step(
                spec, tcfg, params, cch, tok[:, None], pos[:, None],
                jnp.arange(batch), None, jax.random.PRNGKey(0))
            return (out["tokens"], pos + 1, out["cache"]), None
        (tok, _, c), _ = jax.lax.scan(
            step, (jnp.zeros((batch,), jnp.int32),
                   jnp.full((batch,), 128, jnp.int32), cache), None, length=n)
        return tok.sum()
    return jax.jit(full_loop)


def make_layers_only(n):
    def layers_only(params, cache):
        def step(carry, _):
            h_sum, pos, cch = carry
            ai = model_base.attn_inputs(
                spec, pos[:, None],
                lambda w, c: jnp.ones((batch, 1, seq_len), bool))
            hidden = model_base._embed(spec, params,
                                       jnp.zeros((batch, 1), jnp.int32))
            hidden, new_cache, _ = model_base.run_layers(
                spec, params, cch, hidden, ai, jnp.arange(batch),
                pos[:, None], "decode", identity_seq_ids=True)
            return (h_sum + hidden.sum(), pos + 1, new_cache), None
        (s, _, c), _ = jax.lax.scan(
            step, (jnp.zeros((), jnp.bfloat16),
                   jnp.full((batch,), 128, jnp.int32), cache), None, length=n)
        return s.astype(jnp.float32)
    return jax.jit(layers_only)


def make_lm_head_only(n):
    def lm_head_only(params, cache):
        def step(carry, _):
            h = carry
            logits = model_base._lm_head(spec, params, h)
            return h + logits.max(axis=-1).astype(h.dtype)[..., None] * 1e-9, None
        h0 = jnp.ones((batch, 1, spec.hidden_size), jnp.bfloat16)
        h, _ = jax.lax.scan(step, h0, None, length=n)
        return h.sum().astype(jnp.float32)
    return jax.jit(lm_head_only)


def make_attn_only(n):
    from neuronx_distributed_inference_tpu.ops import attention as attn_ops
    from neuronx_distributed_inference_tpu.modules import kv_cache as kvm
    def attn_only(params, cache):
        def step(carry, _):
            acc, cch = carry
            acc2 = acc
            for li in range(spec.num_layers):  # decode unrolls layers too
                k_layer = kvm.read_layer_hl(cch["k"], li)   # (B, H, D, S)
                v_layer = kvm.read_layer_hl(cch["v"], li)   # (B, H, S, D)
                q = jnp.full((batch, 1, spec.gqa.num_q_heads, spec.head_dim),
                             acc2 * 1e-9 + 1.0, jnp.bfloat16)
                o = attn_ops.mha_hl(q, k_layer, v_layer, None, spec.scale)
                acc2 = acc2 + o.sum().astype(jnp.float32)
            return (acc2, cch), None
        (s, _), _ = jax.lax.scan(step, (jnp.zeros((), jnp.float32), cache),
                                 None, length=n)
        return s
    return jax.jit(attn_only)


def make_stream(n):
    def stream(params, cache):
        def body(acc, _):
            s = sum(jnp.sum(x * (1.0 + acc * 1e-30)).astype(jnp.float32)
                    for x in jax.tree.leaves(params))
            return acc + s, None
        acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=n)
        return acc
    return jax.jit(stream)


t_full = timed("full decode step", make_full_loop, params, cache)
t_layers = timed("layers only", make_layers_only, params, cache)
t_head = timed("lm_head only", make_lm_head_only, params, cache)
t_attn = timed("attention-over-cache only", make_attn_only, params, cache)
psize = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
t_stream = timed("param sum (pure stream)", make_stream, params, cache)
print(f"param bytes {psize/1e9:.3f} GB")
print(f"implied stream BW {psize/1e9/t_stream*1e3:.0f} GB/s")
print(f"full-step implied BW {psize/1e9/t_full*1e3:.0f} GB/s")
