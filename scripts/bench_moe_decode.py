#!/usr/bin/env python
"""Measure the XLA decode-MoE path against the HBM roofline on the real
chip (VERDICT r4 ask #10; reference analog: the moe_token_gen NKI kernel of
SURVEY §2.10 — this measurement decides whether a Pallas token-gen MoE
kernel is warranted).

Decode MoE at small batch runs the all-experts dense path: every step
streams ALL expert weights once, so roofline = expert_bytes / HBM_BW.
Prints one JSON line with ms/step and the fraction of roofline."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.modules.moe import MoESpec, moe_block

B, H, E, I = 4, 2048, 8, 4096          # mixtral-shaped slice, bf16
moe = MoESpec(num_experts=E, top_k=2, intermediate_size=I)
key = jax.random.PRNGKey(0)
ks = jax.random.split(key, 5)
lw = {
    "router": jax.random.normal(ks[0], (H, E), jnp.float32) * 0.02,
    "expert_gate": jax.random.normal(ks[1], (E, H, I), jnp.bfloat16) * 0.02,
    "expert_up": jax.random.normal(ks[2], (E, H, I), jnp.bfloat16) * 0.02,
    "expert_down": jax.random.normal(ks[3], (E, I, H), jnp.bfloat16) * 0.02,
}
x = jax.random.normal(ks[4], (B, 1, H), jnp.bfloat16)


def make_loop(n):
    def loop(lw, x):
        def body(h, _):
            y = moe_block(moe, h, lw, phase="decode")
            return (h + y * 1e-3).astype(h.dtype), None
        h, _ = jax.lax.scan(body, x, None, length=n)
        return h.sum().astype(jnp.float32)
    return jax.jit(loop)


def t(fn):
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(fn(lw, x))
        reps.append(time.perf_counter() - t0)
    return min(reps)


N1, N2 = 8, 40
f1, f2 = make_loop(N1), make_loop(N2)
np.asarray(f1(lw, x)); np.asarray(f2(lw, x))        # compile
per_step = (t(f2) - t(f1)) / (N2 - N1)

expert_bytes = sum(int(np.prod(w.shape)) * 2 for k, w in lw.items()
                   if k.startswith("expert"))
hbm = float(os.environ.get("NXDI_TPU_HBM_GBPS", "819")) * 1e9
roofline_s = expert_bytes / hbm
print(json.dumps({
    "metric": "moe_decode_ms_per_step",
    "value": round(per_step * 1e3, 4),
    "unit": "ms",
    "vs_baseline": round(roofline_s / per_step, 4),
    "details": {"roofline_ms": round(roofline_s * 1e3, 4),
                "expert_mbytes": expert_bytes // 2**20,
                "geometry": f"B{B} H{H} E{E} I{I} top2 bf16",
                "verdict": ("XLA path within 15% of roofline — no Pallas "
                            "tokengen kernel needed"
                            if roofline_s / per_step >= 0.85 else
                            "XLA path >15% off roofline — a Pallas tokengen "
                            "MoE kernel is warranted")},
}))
