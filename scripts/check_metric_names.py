#!/usr/bin/env python
"""Tier-1 lint: the metric-name contract and the README table cannot drift.

Metric names are a stable contract (dashboards key on them) and the README
"Observability" table is their documentation of record — but nothing
enforced the pairing, and PR 6's ``nxdi_queue_*`` rows were synced by hand.
This lint fails (rc 1) whenever the two diverge, in either direction:

  * every ``nxdi_*`` name constant in ``telemetry/metrics.py`` (the single
    registration point for canonical names) must appear in the README
    Observability table;
  * every ``nxdi_*`` name in that table must be a registered constant —
    a documented-but-unregistered metric is a typo or a leftover.

Usage::

    python scripts/check_metric_names.py                  # lint the repo
    python scripts/check_metric_names.py --metrics F --readme F   # custom

Wired into the test suite as a tier-1 test
(``tests/test_flight_recorder.py::test_metric_names_lint``).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Sequence, Set

REPO_ROOT = Path(__file__).resolve().parent.parent
METRICS_PATH = (REPO_ROOT / "neuronx_distributed_inference_tpu" /
                "telemetry" / "metrics.py")
README_PATH = REPO_ROOT / "README.md"

_NAME_RE = re.compile(r"nxdi_[a-z0-9_]+")


def registered_names(metrics_source: str) -> Set[str]:
    """``nxdi_*`` string constants assigned at module level in
    telemetry/metrics.py — the canonical registration point."""
    names: Set[str] = set()
    for node in ast.parse(metrics_source).body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if (isinstance(value, ast.Constant) and isinstance(value.value, str)
                and value.value.startswith("nxdi_")):
            names.add(value.value)
    return names


def documented_names(readme_source: str) -> Set[str]:
    """``nxdi_*`` names in the README Observability metric table (table
    rows only — prose mentions elsewhere are cross-references, not
    documentation of record)."""
    lines = readme_source.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.strip() == "## Observability")
    except StopIteration:
        return set()
    names: Set[str] = set()
    for line in lines[start + 1:]:
        if line.startswith("## "):
            break
        if line.lstrip().startswith("|"):
            names.update(_NAME_RE.findall(line))
    return names


def main(argv: Sequence[str] = ()) -> int:
    argv = list(argv)
    metrics_path, readme_path = METRICS_PATH, README_PATH
    if "--metrics" in argv:
        metrics_path = Path(argv[argv.index("--metrics") + 1])
    if "--readme" in argv:
        readme_path = Path(argv[argv.index("--readme") + 1])
    rc = 0
    registered = registered_names(metrics_path.read_text())
    documented = documented_names(readme_path.read_text())
    if not registered:
        print(f"check_metric_names: no nxdi_* constants found in "
              f"{metrics_path} — wrong file?", file=sys.stderr)
        return 1
    if not documented:
        print(f"check_metric_names: no Observability metric table found in "
              f"{readme_path} — wrong file?", file=sys.stderr)
        return 1
    for name in sorted(registered - documented):
        print(f"check_metric_names: {name} is registered in "
              f"{metrics_path.name} but missing from the README "
              "Observability table — document it (names are a stable "
              "contract)", file=sys.stderr)
        rc = 1
    for name in sorted(documented - registered):
        print(f"check_metric_names: {name} appears in the README "
              f"Observability table but is not registered in "
              f"{metrics_path.name} — typo or leftover row",
              file=sys.stderr)
        rc = 1
    if rc == 0:
        print(f"check_metric_names: OK ({len(registered)} names in sync)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
