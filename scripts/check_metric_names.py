#!/usr/bin/env python
"""Back-compat shim over ``nxdi_lint``'s ``metric-names`` pass.

DEPRECATED entry point: the checker now lives in
``neuronx_distributed_inference_tpu/analysis/passes/metric_names.py``
and runs with every other pass through ``scripts/nxdi_lint.py``. Kept
for existing invocations; same arguments, same messages.

Usage::

    python scripts/check_metric_names.py                  # lint the repo
    python scripts/check_metric_names.py --metrics F --readme F   # custom
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "scripts"))

from nxdi_lint import load_analysis  # noqa: E402


def main(argv=()) -> int:
    analysis = load_analysis()
    argv = [str(a) for a in argv]
    p = analysis.get_pass("metric-names")
    # defaults stay repo-relative (resolved against the repo root);
    # flag values resolve against CWD like the old standalone CLI
    metrics_path, readme_path = p.default_paths
    if "--metrics" in argv:
        metrics_path = str(Path(argv[argv.index("--metrics") + 1]).resolve())
    if "--readme" in argv:
        readme_path = str(Path(argv[argv.index("--readme") + 1]).resolve())
    ctx = analysis.LintContext(REPO_ROOT)
    findings = p.run(ctx, paths=(metrics_path, readme_path))
    for f in findings:
        print(f"check_metric_names: {f.message}", file=sys.stderr)
    if findings:
        return 1
    import importlib
    mn_mod = importlib.import_module(type(p).__module__)
    sf = ctx.source_for(Path(metrics_path))
    print(f"check_metric_names: OK ({len(mn_mod.registered_names(sf.tree))} "
          "names in sync)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
