#!/usr/bin/env python
"""Micro-bench: ragged paged decode kernel vs the XLA gather path on the
real chip (VERDICT r3 ask: show the kernel beating the gather path at
max_blocks >= 4x live length). Prints one JSON line per configuration."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_inference_tpu.modules import block_kv_cache as bkv
from neuronx_distributed_inference_tpu.ops import attention as attn_ops
from neuronx_distributed_inference_tpu.ops import decode_attention as da

L, B, HQ, HKV, D, BS = 4, 2, 32, 8, 64, 128


def run(live, mb, iters=64):
    n = 1 + B * mb
    rng = np.random.default_rng(0)
    kp = jnp.asarray(rng.standard_normal((L, n, BS, HKV, D)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((L, n, BS, HKV, D)), jnp.bfloat16)
    table = np.zeros((B, mb), np.int32)
    perm = rng.permutation(n - 1) + 1
    for i in range(B):
        table[i, :mb] = perm[i * mb:(i + 1) * mb]
    table = jnp.asarray(table)
    lens = jnp.full((B,), live, jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, HQ, D)), jnp.bfloat16)
    nk = jnp.asarray(rng.standard_normal((B, HKV, D)), jnp.bfloat16)
    nv = jnp.asarray(rng.standard_normal((B, HKV, D)), jnp.bfloat16)
    scale = D ** -0.5

    def kernel_loop(n_it):
        def body(acc, _):
            out = 0.0
            for li in range(L):
                o = da.paged_decode_attention(
                    q + acc * 1e-9, kp, vp, nk, nv,
                    jnp.asarray(li, jnp.int32), lens, table, scale=scale)
                out = out + o.sum().astype(jnp.float32)
            return acc + out, None
        return jax.jit(lambda: jax.lax.scan(body, jnp.zeros(()), None,
                                            length=n_it)[0])

    def gather_loop(n_it):
        positions = lens[:, None]
        mask = attn_ops.decode_mask(positions, mb * BS)
        def body(acc, _):
            out = 0.0
            for li in range(L):
                k_all = bkv.gather_block_kv(bkv.read_layer(kp, li), table)
                v_all = bkv.gather_block_kv(bkv.read_layer(vp, li), table)
                rows = jnp.arange(B)
                k_all = k_all.at[rows, lens].set(nk)
                v_all = v_all.at[rows, lens].set(nv)
                o = attn_ops.mha((q + acc * 1e-9)[:, None], k_all, v_all,
                                 mask, scale)
                out = out + o.sum().astype(jnp.float32)
            return acc + out, None
        return jax.jit(lambda: jax.lax.scan(body, jnp.zeros(()), None,
                                            length=n_it)[0])

    res = {}
    for name, mk in (("kernel", kernel_loop), ("gather", gather_loop)):
        n1, n2 = iters // 4, iters
        f1, f2 = mk(n1), mk(n2)
        np.asarray(f1()); np.asarray(f2())
        t1 = min(_t(f1) for _ in range(3))
        t2 = min(_t(f2) for _ in range(3))
        res[name] = (t2 - t1) / (n2 - n1) / L * 1e6   # us per layer-call
    return res


def _t(f):
    t0 = time.perf_counter()
    np.asarray(f())
    return time.perf_counter() - t0


if __name__ == "__main__":
    for live, mb in ((256, 8), (256, 32), (512, 32), (1024, 32)):
        r = run(live, mb)
        print(json.dumps({
            "live": live, "max_blocks": mb, "block_size": BS,
            "kernel_us_per_layer": round(r["kernel"], 1),
            "gather_us_per_layer": round(r["gather"], 1),
            "speedup": round(r["gather"] / r["kernel"], 2)}))
